"""EC encode/rebuild: volume .dat -> 14 shard files, GF math on TPU.

Layout parity with ec_encoder.go:57-231: the .dat is striped row-major over
10 data shards — repeat 1 GB x 10 rows while more than 10 GB remains, then
1 MB x 10 rows, zero-padding the tail.

TPU-first restructuring: the reference feeds its CPU codec 256 KB-per-shard
batches inside a per-row loop (encodeDataOneBatch).  Because RS parity is
columnwise, any column grouping is equivalent, so here each striped row
becomes a (10, B) byte matrix and large device-sized column chunks are
encoded in single kernel dispatches (Pallas MXU kernel on TPU) —
maximising MXU occupancy and amortising host<->HBM transfers instead of
translating the 256 KB loop.
"""

from __future__ import annotations

import json
import os
from typing import Optional

import numpy as np

from ...ops import codec as codec_mod
from .. import idx as idx_mod
from ..needle_map import load_needle_map_from_idx
from . import (DATA_SHARDS_COUNT, LARGE_BLOCK_SIZE, PARITY_SHARDS_COUNT,
               SMALL_BLOCK_SIZE, TOTAL_SHARDS_COUNT, to_ext)

DEFAULT_CHUNK_BYTES = 64 * 1024 * 1024  # per-shard column chunk per dispatch


def write_sorted_file_from_idx(base_file_name: str, ext: str = ".ecx"):
    """Generate .ecx (ascending-id sorted copy of live .idx entries) —
    WriteSortedFileFromIdx (ec_encoder.go:27-54).  Entries whose latest
    state is a deletion are omitted (readNeedleMap drops them).  Uses the
    compact (numpy) map kind: its vectorised bulk loader keeps .ecx
    generation O(n log n) array work at 100M-needle scale."""
    nm = load_needle_map_from_idx(base_file_name + ".idx", kind="compact")
    with open(base_file_name + ext, "wb") as f:
        for nid, nv in nm.items_ascending():
            if nv.offset > 0 and nv.size >= 0:
                f.write(idx_mod.pack_entry(nid, nv.offset, nv.size))


def _resolve_family(family):
    """Accept a family name, a CodeFamily, or None (-> RS default)."""
    from .codes import get_family

    if hasattr(family, "data_shards"):
        return family
    return get_family(family)


def write_ec_files(base_file_name: str, encoder=None,
                   large_block_size: int = LARGE_BLOCK_SIZE,
                   small_block_size: int = SMALL_BLOCK_SIZE,
                   chunk_bytes: int = DEFAULT_CHUNK_BYTES,
                   batched: Optional[bool] = None,
                   stage_stats: Optional[dict] = None,
                   family=None):
    """Generate .ec00..ec13 from .dat (WriteEcFiles, ec_encoder.go:57-59).

    Default path (no explicit codec): auto-selected by PREDICTED
    throughput on this machine — the streaming batched TPU pipeline
    (parallel/batched_encode.py; device-batched parity with fused CRC32C
    and pipelined host I/O) when the measured host<->device link can
    carry it faster than the host codec, else the synchronous host loop
    (util/platform.prefer_batched_encode; behind a slow relay tunnel the
    link, not the chip, is the bottleneck).  Returns the 14 shard-file
    CRC32Cs from the batched path, None from the host loop.  An explicit
    `encoder` (or batched=False) forces the host loop; batched=True
    forces the device pipeline (-ec.backend=tpu).  A wedged JAX backend
    falls back to the host codec rather than hanging a daemon.

    stage_stats: optional dict the host pipeline fills with per-stage
    busy seconds (read / encode_crc / write / flush) and fractions —
    see parallel/batched_encode._encode_units_host.

    family: code-family name or CodeFamily (storage/erasure_coding/codes).
    None / the RS default keeps every path above unchanged; other families
    stripe over their own data-shard count and encode through the family's
    generator on the best host kernel, returning the 14 shard CRC32Cs.
    """
    if family is not None:
        fam = _resolve_family(family)
        if fam.name != "rs_vandermonde":
            return _write_ec_files_family(
                base_file_name, fam, large_block_size, small_block_size,
                chunk_bytes)
    auto_host = False
    if batched is None:
        from ...util.platform import prefer_batched_encode

        batched = encoder is None and prefer_batched_encode()
        auto_host = encoder is None and not batched
    if batched:
        from ...parallel.batched_encode import encode_volumes

        crcs = encode_volumes([base_file_name],
                              large_block=large_block_size,
                              small_block=small_block_size,
                              stage_stats=stage_stats)
        return crcs[base_file_name]
    if auto_host:
        # auto-selection rejected the (link-capped) device path: run the
        # host pipeline — fused GFNI parity+CRC spans with preallocated
        # unbuffered shard writes; inline on a single core (no thread
        # convoy), reader thread + a codec worker per core otherwise —
        # and fused shard CRCs come along for the .vif.
        from ...parallel.batched_encode import encode_volumes

        crcs = encode_volumes([base_file_name],
                              large_block=large_block_size,
                              small_block=small_block_size,
                              host_codec=True,
                              stage_stats=stage_stats)
        return crcs[base_file_name]
    if encoder is None:
        # explicit batched=False: the reference-architecture synchronous
        # host loop, with a genuine host codec (not "auto", which would
        # pick the device backend right back on a TPU machine)
        encoder = codec_mod.new_host_encoder(DATA_SHARDS_COUNT,
                                             PARITY_SHARDS_COUNT)
    dat_size = os.path.getsize(base_file_name + ".dat")
    outputs = [open(base_file_name + to_ext(i), "wb")
               for i in range(TOTAL_SHARDS_COUNT)]
    try:
        with open(base_file_name + ".dat", "rb") as dat:
            remaining = dat_size
            while remaining > large_block_size * DATA_SHARDS_COUNT:
                _encode_one_row(dat, encoder, large_block_size, outputs,
                                chunk_bytes)
                remaining -= large_block_size * DATA_SHARDS_COUNT
            while remaining > 0:
                _encode_one_row(dat, encoder, small_block_size, outputs,
                                chunk_bytes)
                remaining -= small_block_size * DATA_SHARDS_COUNT
    finally:
        for f in outputs:
            f.close()


def _encode_one_row(dat, encoder, block_size: int, outputs,
                    chunk_bytes: int):
    """Encode one striped row: 10 consecutive blocks -> 14 shard appends."""
    blocks = []
    for _ in range(DATA_SHARDS_COUNT):
        block = dat.read(block_size)
        if len(block) < block_size:
            block = block + b"\x00" * (block_size - len(block))
        blocks.append(np.frombuffer(block, dtype=np.uint8))
    data = np.stack(blocks)  # (10, block_size)
    parity_matrix = encoder.matrix[DATA_SHARDS_COUNT:]
    for start in range(0, block_size, chunk_bytes):
        end = min(start + chunk_bytes, block_size)
        parity = encoder._apply(parity_matrix, data[:, start:end])
        for i in range(DATA_SHARDS_COUNT):
            outputs[i].seek(0, 2)
            outputs[i].write(data[i, start:end].tobytes())
        for i in range(PARITY_SHARDS_COUNT):
            outputs[DATA_SHARDS_COUNT + i].seek(0, 2)
            outputs[DATA_SHARDS_COUNT + i].write(
                np.ascontiguousarray(parity[i]).tobytes())


def _write_ec_files_family(base_file_name: str, fam,
                           large_block_size: int, small_block_size: int,
                           chunk_bytes: int) -> list:
    """Host encode loop for a non-default code family: stripe the .dat
    over the family's k data shards and run its generator on the best
    host GF kernel (the native backend's _apply takes any matrix, so the
    GFNI/AVX2 path serves every family).  Returns the 14 shard CRC32Cs,
    chained as the shards are written — same record the batched RS
    pipeline fuses, so .vif scrub verification works identically."""
    from ...ops.crc32c import crc32c

    fam.check_block(large_block_size)
    fam.check_block(small_block_size)
    chunk_bytes = max(fam.sub_shards,
                      (chunk_bytes // fam.sub_shards) * fam.sub_shards)
    kernel = codec_mod.new_host_encoder(fam.data_shards, fam.parity_shards)
    k = fam.data_shards
    dat_size = os.path.getsize(base_file_name + ".dat")
    outputs = [open(base_file_name + to_ext(i), "wb")
               for i in range(TOTAL_SHARDS_COUNT)]
    crcs = [0] * TOTAL_SHARDS_COUNT
    try:
        with open(base_file_name + ".dat", "rb") as dat:
            remaining = dat_size
            while remaining > 0:
                block_size = (large_block_size
                              if remaining > large_block_size * k
                              else small_block_size)
                blocks = []
                for _ in range(k):
                    block = dat.read(block_size)
                    if len(block) < block_size:
                        block = block + b"\x00" * (block_size - len(block))
                    blocks.append(np.frombuffer(block, dtype=np.uint8))
                data = np.stack(blocks)  # (k, block_size)
                for start in range(0, block_size, chunk_bytes):
                    end = min(start + chunk_bytes, block_size)
                    parity = fam.encode_blocks(data[:, start:end],
                                               apply_fn=kernel._apply)
                    for i in range(k):
                        chunk = data[i, start:end].tobytes()
                        outputs[i].write(chunk)
                        crcs[i] = crc32c(chunk, crcs[i])
                    for i in range(fam.parity_shards):
                        chunk = np.ascontiguousarray(parity[i]).tobytes()
                        outputs[k + i].write(chunk)
                        crcs[k + i] = crc32c(chunk, crcs[k + i])
                remaining -= block_size * k
    finally:
        for f in outputs:
            f.close()
    return crcs


def rebuild_ec_files(base_file_name: str, encoder=None,
                     buffer_size: int = SMALL_BLOCK_SIZE,
                     batched: Optional[bool] = None,
                     family=None, stats: Optional[dict] = None) -> dict:
    """Regenerate missing .ecNN files from survivors
    (RebuildEcFiles/generateMissingEcFiles, ec_encoder.go:61-118,233-287).
    Returns {shard_id: crc32c-or-None} of the generated shards — CRCs
    come fused from the device path, None from the host loop.

    Default path (no explicit codec): the batched device pipeline —
    survivor chunks stream through one reconstruction bit-matmul with
    fused CRC32C (BASELINE config 3) — when the link can carry it
    faster than the host codec (same auto-selection as write_ec_files).
    Falls back to the synchronous host loop with an explicit `encoder`,
    batched=False, or an unreachable JAX backend.

    family / stats: a non-default code family (name or CodeFamily), or any
    request for read accounting (stats dict), routes through the planned
    rebuild below — the family's repair planner picks the read set (k
    survivors for MDS decode, d sub-shard projections for pm_msr) instead
    of opening every present shard.
    """
    if family is not None or stats is not None:
        fam = _resolve_family(family)
        if fam.name != "rs_vandermonde" or stats is not None:
            return rebuild_ec_files_planned(base_file_name, fam,
                                            buffer_size, stats)
    if batched is None:
        from ...util.platform import prefer_batched_encode

        batched = encoder is None and prefer_batched_encode()
    if batched:
        from ...parallel.batched_encode import rebuild_shards

        return rebuild_shards(base_file_name)
    if encoder is None:
        encoder = codec_mod.new_host_encoder(DATA_SHARDS_COUNT,
                                             PARITY_SHARDS_COUNT)
    has_data = [os.path.exists(base_file_name + to_ext(i))
                for i in range(TOTAL_SHARDS_COUNT)]
    generated = [i for i in range(TOTAL_SHARDS_COUNT) if not has_data[i]]
    if not generated:
        return {}
    inputs = {i: open(base_file_name + to_ext(i), "rb")
              for i in range(TOTAL_SHARDS_COUNT) if has_data[i]}
    outputs = {i: open(base_file_name + to_ext(i), "wb") for i in generated}
    try:
        offset = 0
        while True:
            shards: list[Optional[np.ndarray]] = [None] * TOTAL_SHARDS_COUNT
            n = 0
            for i, f in inputs.items():
                f.seek(offset)
                buf = f.read(buffer_size)
                if not buf:
                    return {i: None for i in generated}
                if n == 0:
                    n = len(buf)
                elif len(buf) != n:
                    raise ValueError(
                        f"ec shard size expected {n} actual {len(buf)}")
                shards[i] = np.frombuffer(buf, dtype=np.uint8)
            restored = encoder.reconstruct(shards)
            for i in generated:
                outputs[i].write(np.ascontiguousarray(restored[i]).tobytes())
            offset += n
    finally:
        for f in inputs.values():
            f.close()
        for f in outputs.values():
            f.close()


def rebuild_ec_files_planned(base_file_name: str, fam,
                             buffer_size: int = SMALL_BLOCK_SIZE,
                             stats: Optional[dict] = None) -> dict:
    """Repair-plan-driven rebuild: read only what the family's planner
    asks for.  MDS decode plans read k full survivors (vs every present
    shard in the legacy loop); pm_msr single-shard plans read the d
    helper *projections* — 1/alpha of each helper — which is the
    regenerating-code bandwidth win.  Returns {shard_id: crc32c}; fills
    `stats` with plan kind and read/rebuilt byte counts, where
    read_bytes counts survivor bytes *consumed* (post-projection, i.e.
    what a distributed rebuild moves over the network)."""
    from ...ops.crc32c import crc32c

    a = fam.sub_shards
    buffer_size = max(a, (buffer_size // a) * a)
    has_data = [os.path.exists(base_file_name + to_ext(i))
                for i in range(TOTAL_SHARDS_COUNT)]
    generated = [i for i in range(TOTAL_SHARDS_COUNT) if not has_data[i]]
    present = [i for i in range(TOTAL_SHARDS_COUNT) if has_data[i]]
    out_stats = stats if stats is not None else {}
    out_stats.update({"plan": None, "read_bytes": 0, "rebuilt_bytes": 0,
                      "read_amp": None, "helpers": ()})
    if not generated:
        return {}
    plan = None
    if len(generated) == 1:
        plan = fam.repair_plan(generated[0], present)
    kernel = codec_mod.new_host_encoder(fam.data_shards, fam.parity_shards)
    read_bytes = rebuilt_bytes = 0
    crcs = {i: 0 for i in generated}
    if plan is not None and plan.kind == "projection":
        lost = generated[0]
        inputs = {h: open(base_file_name + to_ext(h), "rb")
                  for h in plan.helpers}
        try:
            with open(base_file_name + to_ext(lost), "wb") as out:
                while True:
                    chunks = []
                    n = None
                    for h in plan.helpers:
                        buf = inputs[h].read(buffer_size)
                        if n is None:
                            n = len(buf)
                        elif len(buf) != n:
                            raise ValueError(
                                f"ec shard size expected {n} "
                                f"actual {len(buf)}")
                        chunks.append(buf)
                    if not n:
                        break
                    projs = np.stack([
                        fam.project(np.frombuffer(c, dtype=np.uint8),
                                    plan.vector) for c in chunks])
                    restored = np.ascontiguousarray(
                        fam.combine_projections(plan, projs)).tobytes()
                    out.write(restored)
                    crcs[lost] = crc32c(restored, crcs[lost])
                    read_bytes += projs.nbytes
                    rebuilt_bytes += n
        finally:
            for f in inputs.values():
                f.close()
    else:
        chosen = (plan.helpers if plan is not None
                  else fam.choose_survivors(present))
        inputs = {i: open(base_file_name + to_ext(i), "rb")
                  for i in chosen}
        outputs = {i: open(base_file_name + to_ext(i), "wb")
                   for i in generated}
        try:
            while True:
                stack = []
                n = None
                for i in chosen:
                    buf = inputs[i].read(buffer_size)
                    if n is None:
                        n = len(buf)
                    elif len(buf) != n:
                        raise ValueError(
                            f"ec shard size expected {n} actual {len(buf)}")
                    stack.append(np.frombuffer(buf, dtype=np.uint8))
                if not n:
                    break
                restored = fam.decode_blocks(chosen, np.stack(stack),
                                             generated,
                                             apply_fn=kernel._apply)
                for idx, i in enumerate(generated):
                    chunk = np.ascontiguousarray(restored[idx]).tobytes()
                    outputs[i].write(chunk)
                    crcs[i] = crc32c(chunk, crcs[i])
                read_bytes += n * len(chosen)
                rebuilt_bytes += n * len(generated)
        finally:
            for f in inputs.values():
                f.close()
            for f in outputs.values():
                f.close()
    out_stats.update({
        "plan": plan.kind if plan is not None else "decode",
        "read_bytes": read_bytes,
        "rebuilt_bytes": rebuilt_bytes,
        "read_amp": (round(read_bytes / rebuilt_bytes, 4)
                     if rebuilt_bytes else None),
        "helpers": (plan.helpers if plan is not None
                    else tuple(sorted(inputs))),
    })
    return crcs


def save_volume_info(base_file_name: str, version: int,
                     extra: Optional[dict] = None):
    """Persist the .vif sidecar (volume_info/volume_info.go) — JSON here
    rather than protobuf; it carries the same version field."""
    info = {"version": version}
    if extra:
        info.update(extra)
    with open(base_file_name + ".vif", "w") as f:
        json.dump(info, f)


def load_volume_info(base_file_name: str) -> Optional[dict]:
    try:
        with open(base_file_name + ".vif") as f:
            return json.load(f)
    except FileNotFoundError:
        return None
