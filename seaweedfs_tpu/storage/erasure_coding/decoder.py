"""EC decode: shard files back to a normal volume (.dat/.idx).

Parity with ec_decoder.go: the data shards are systematic, so .dat recovery
is a pure interleaved copy of .ec00-.ec09 (no GF math); .idx = .ecx entries
plus tombstones replayed from .ecj.
"""

from __future__ import annotations

import os
import struct

from .. import idx as idx_mod
from .. import types as t
from ..needle import get_actual_size
from ..super_block import SuperBlock
from . import DATA_SHARDS_COUNT, LARGE_BLOCK_SIZE, SMALL_BLOCK_SIZE, to_ext


def iterate_ecx_file(base_file_name: str, fn):
    """fn(needle_id, actual_offset, size) over every .ecx entry."""
    with open(base_file_name + ".ecx", "rb") as f:
        while True:
            buf = f.read(t.NEEDLE_MAP_ENTRY_SIZE)
            if len(buf) != t.NEEDLE_MAP_ENTRY_SIZE:
                return
            fn(*idx_mod.unpack_entry(buf))


def iterate_ecj_file(base_file_name: str, fn):
    """fn(needle_id) over every deletion-journal entry."""
    path = base_file_name + ".ecj"
    if not os.path.exists(path):
        return
    with open(path, "rb") as f:
        while True:
            buf = f.read(t.NEEDLE_ID_SIZE)
            if len(buf) != t.NEEDLE_ID_SIZE:
                return
            fn(struct.unpack(">Q", buf)[0])


def write_idx_file_from_ec_index(base_file_name: str):
    """.ecx + .ecj -> .idx (WriteIdxFileFromEcIndex, ec_decoder.go:18-43):
    a byte copy of .ecx followed by a tombstone entry per journalled id."""
    with open(base_file_name + ".ecx", "rb") as src, \
            open(base_file_name + ".idx", "wb") as dst:
        while True:
            chunk = src.read(1 << 20)
            if not chunk:
                break
            dst.write(chunk)
        iterate_ecj_file(
            base_file_name,
            lambda nid: dst.write(
                idx_mod.pack_entry(nid, 0, t.TOMBSTONE_FILE_SIZE)))


def read_ec_volume_version(base_file_name: str) -> int:
    """Volume version from the superblock at the head of .ec00
    (shard 0 starts with the original .dat's first bytes)."""
    with open(base_file_name + to_ext(0), "rb") as f:
        return SuperBlock.from_file(f).version


def find_dat_file_size(data_base_file_name: str,
                       index_base_file_name: str) -> int:
    """Max (offset + actual size) over live .ecx entries
    (FindDatFileSize, ec_decoder.go:48-70)."""
    version = read_ec_volume_version(data_base_file_name)
    dat_size = 0

    def visit(nid, offset, size):
        nonlocal dat_size
        if t.size_is_deleted(size):
            return
        stop = offset + get_actual_size(size, version)
        dat_size = max(dat_size, stop)

    iterate_ecx_file(index_base_file_name, visit)
    return dat_size


def write_dat_file(base_file_name: str, dat_file_size: int,
                   large_block_size: int = LARGE_BLOCK_SIZE,
                   small_block_size: int = SMALL_BLOCK_SIZE,
                   data_shards: int = DATA_SHARDS_COUNT):
    """Reassemble .dat by interleaved copy of the data shards
    (WriteDatFile, ec_decoder.go:154-195).  All code families are
    systematic, so this is a pure copy regardless of family — only the
    stripe width (``data_shards``) differs."""
    inputs = [open(base_file_name + to_ext(i), "rb")
              for i in range(data_shards)]
    try:
        with open(base_file_name + ".dat", "wb") as dat:
            remaining = dat_file_size
            while remaining >= data_shards * large_block_size:
                for f in inputs:
                    block = f.read(large_block_size)
                    if len(block) != large_block_size:
                        raise IOError("short large-block read during decode")
                    dat.write(block)
                    remaining -= large_block_size
            while remaining > 0:
                for f in inputs:
                    to_read = min(remaining, small_block_size)
                    if to_read <= 0:
                        break
                    block = f.read(small_block_size)[:to_read]
                    if len(block) != to_read:
                        raise IOError("short small-block read during decode")
                    dat.write(block)
                    remaining -= to_read
    finally:
        for f in inputs:
            f.close()
