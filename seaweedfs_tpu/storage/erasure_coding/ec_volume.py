"""EC volume runtime: shard handles, sorted-index search, EC reads, deletes.

Parity with ec_volume.go / ec_shard.go / ec_volume_delete.go / store_ec.go:
  * .ecx binary search over 16-byte sorted entries (SearchNeedleFromSortedIndex,
    ec_volume.go:230-255)
  * read ladder per interval: local shard pread, else remote fetch (hook),
    else reconstruct the interval from >=10 other shards
    (readOneEcShardInterval/recoverOneRemoteEcShardInterval,
    store_ec.go:188-218,328-382)
  * delete = tombstone the size field inside .ecx in place + append the id to
    the .ecj journal (ec_volume_delete.go:13-50); RebuildEcxFile replays the
    journal (ec_volume_delete.go:53-98)
"""

from __future__ import annotations

import hashlib
import os
import struct
import threading
import time
from typing import Callable, Optional

import numpy as np

from ...ops import codec as codec_mod
from .. import types as t
from ..needle import Needle, get_actual_size
from . import (DATA_SHARDS_COUNT, LARGE_BLOCK_SIZE, SMALL_BLOCK_SIZE,
               TOTAL_SHARDS_COUNT, to_ext)
from ... import tracing
from .locate import Interval, locate_data
from .recover import (STATS as RECOVER_STATS, RecoveredBlockCache,
                      SpanDecodeBatcher, recover_knobs)

_recover_pool_lock = threading.Lock()
_recover_pool_inst = None


def _recover_pool():
    """Shared fan-out pool for degraded-read survivor fetches: built
    once, sized for a few concurrent recoveries, never rebuilt on the
    hot path of an outage."""
    global _recover_pool_inst
    with _recover_pool_lock:
        if _recover_pool_inst is None:
            import concurrent.futures as cf

            _recover_pool_inst = cf.ThreadPoolExecutor(
                max_workers=32, thread_name_prefix="ec-recover")
        return _recover_pool_inst


class EcError(Exception):
    pass


class EcNotFoundError(EcError):
    pass


class EcDeletedError(EcError):
    pass


class ShardBits:
    """uint32 bitmask of shard ids (ec_volume_info.go:65-117)."""

    def __init__(self, bits: int = 0):
        self.bits = bits & 0xFFFFFFFF

    def add(self, shard_id: int) -> "ShardBits":
        return ShardBits(self.bits | (1 << shard_id))

    def remove(self, shard_id: int) -> "ShardBits":
        return ShardBits(self.bits & ~(1 << shard_id))

    def has(self, shard_id: int) -> bool:
        return bool(self.bits & (1 << shard_id))

    def shard_ids(self) -> list[int]:
        return [i for i in range(TOTAL_SHARDS_COUNT) if self.has(i)]

    def count(self) -> int:
        return bin(self.bits).count("1")

    def minus(self, other: "ShardBits") -> "ShardBits":
        return ShardBits(self.bits & ~other.bits)

    def plus(self, other: "ShardBits") -> "ShardBits":
        return ShardBits(self.bits | other.bits)

    def __eq__(self, other):
        return isinstance(other, ShardBits) and self.bits == other.bits

    def __hash__(self):
        # __eq__ without __hash__ made instances unhashable (None __hash__),
        # silently breaking set/dict membership; hash the identity __eq__ uses
        return hash(self.bits)

    def __repr__(self):
        return f"ShardBits({self.shard_ids()})"


class EcVolumeShard:
    """One open .ecNN file (ec_shard.go:17-97)."""

    def __init__(self, directory: str, collection: str, vid: int,
                 shard_id: int):
        self.dir = directory
        self.collection = collection
        self.volume_id = vid
        self.shard_id = shard_id
        self._f = open(self.file_name(), "rb")
        self.ecd_file_size = os.path.getsize(self.file_name())

    def base_file_name(self) -> str:
        base = (f"{self.collection}_{self.volume_id}" if self.collection
                else str(self.volume_id))
        return os.path.join(self.dir, base)

    def file_name(self) -> str:
        return self.base_file_name() + to_ext(self.shard_id)

    def read_at(self, size: int, offset: int) -> bytes:
        return os.pread(self._f.fileno(), size, offset)

    def close(self):
        if self._f:
            self._f.close()
            self._f = None

    def destroy(self):
        self.close()
        os.remove(self.file_name())


# Remote fetch hook: (shard_id, offset, size) -> bytes | None
ShardReader = Callable[[int, int, int], Optional[bytes]]


def search_sorted_index(fileno: int, n_entries: int,
                        needle_id: int) -> Optional[int]:
    """Binary search 16-byte sorted entries by pread; returns entry index
    (SearchNeedleFromSortedIndex, ec_volume.go:230-255)."""
    from .. import idx as idx_mod

    lo, hi = 0, n_entries
    while lo < hi:
        mid = (lo + hi) // 2
        buf = os.pread(fileno, t.NEEDLE_MAP_ENTRY_SIZE,
                       mid * t.NEEDLE_MAP_ENTRY_SIZE)
        key, _, _ = idx_mod.unpack_entry(buf)
        if key == needle_id:
            return mid
        if key < needle_id:
            lo = mid + 1
        else:
            hi = mid
    return None


class EcVolume:
    """A mounted EC volume: local shard subset + .ecx/.ecj handles."""

    # inline EC volumes install a hook serving shard-log spans from the
    # in-memory tail stripe: (shard_id, offset, size) -> bytes | None.
    # Sealed volumes leave it None and the classic ladder applies.
    tail_reader: Optional[ShardReader] = None

    def __init__(self, directory: str, collection: str, vid: int,
                 version: int = 3, encoder=None,
                 large_block_size: int = LARGE_BLOCK_SIZE,
                 small_block_size: int = SMALL_BLOCK_SIZE):
        self.dir = directory
        self.collection = collection
        self.volume_id = vid
        self.version = version
        self.large_block_size = large_block_size
        self.small_block_size = small_block_size
        self.shards: dict[int, EcVolumeShard] = {}
        self.shard_locations: dict[int, list[str]] = {}  # shard id -> addrs
        self.remote_reader: Optional[ShardReader] = None
        # code family rides in .vif metadata: volumes encoded before the
        # coding tier existed have no record and resolve to the RS default,
        # so mixed clusters keep reading old volumes correctly
        from .codes import get_family
        from .encoder import load_volume_info
        info = load_volume_info(self.base_file_name()) or {}
        self.family = get_family(info.get("code_family"))
        # lazy: backend selection probes device availability, which must
        # not stall mount/admin paths — only reconstruction needs it
        self._encoder = encoder
        # degraded-read machinery: per-volume recovered-block LRU (keys
        # are shard offsets, which only mean anything within one volume)
        # + the same-survivor-set span-decode batcher
        self._recover_cache = RecoveredBlockCache()
        self._recover_batcher = SpanDecodeBatcher(self._decode_span)
        self._ecx_lock = threading.Lock()
        self._ecj_lock = threading.Lock()
        base = self.base_file_name()
        self._ecx = open(base + ".ecx", "r+b")
        self.ecx_file_size = os.path.getsize(base + ".ecx")
        self._ecj = open(base + ".ecj", "a+b")
        self.ecj_file_size = os.path.getsize(base + ".ecj")

    def base_file_name(self) -> str:
        base = (f"{self.collection}_{self.volume_id}" if self.collection
                else str(self.volume_id))
        return os.path.join(self.dir, base)

    # -- shard management ----------------------------------------------------
    def add_shard(self, shard: EcVolumeShard) -> bool:
        if shard.shard_id in self.shards:
            return False
        self.shards[shard.shard_id] = shard
        return True

    def delete_shard(self, shard_id: int) -> Optional[EcVolumeShard]:
        return self.shards.pop(shard_id, None)

    def shard_bits(self) -> ShardBits:
        bits = ShardBits()
        for sid in self.shards:
            bits = bits.add(sid)
        return bits

    @property
    def shard_size(self) -> int:
        if not self.shards:
            return 0
        return next(iter(self.shards.values())).ecd_file_size

    # -- sorted-index search -------------------------------------------------
    def find_needle_from_ecx(self, needle_id: int) -> tuple[int, int]:
        """Binary search the sorted .ecx -> (offset, size); raises
        EcNotFoundError when absent."""
        entry_pos = self._search_ecx(needle_id)
        if entry_pos is None:
            raise EcNotFoundError(f"needle {needle_id:x} not found")
        _, offset, size = self._read_ecx_entry(entry_pos)
        return offset, size

    def _read_ecx_entry(self, pos: int) -> tuple[int, int, int]:
        buf = os.pread(self._ecx.fileno(), t.NEEDLE_MAP_ENTRY_SIZE,
                       pos * t.NEEDLE_MAP_ENTRY_SIZE)
        from .. import idx as idx_mod

        return idx_mod.unpack_entry(buf)

    def _search_ecx(self, needle_id: int) -> Optional[int]:
        return search_sorted_index(
            self._ecx.fileno(),
            self.ecx_file_size // t.NEEDLE_MAP_ENTRY_SIZE, needle_id)

    # -- needle read (store_ec.go ReadEcShardNeedle:125-163) ------------------
    def locate_needle(self, needle_id: int
                      ) -> tuple[int, int, list[Interval]]:
        offset, size = self.find_needle_from_ecx(needle_id)
        if t.size_is_deleted(size):
            raise EcDeletedError(f"needle {needle_id:x} deleted")
        intervals = locate_data(
            self.large_block_size, self.small_block_size,
            self.family.data_shards * self.shard_size,
            offset, get_actual_size(size, self.version),
            data_shards=self.family.data_shards)
        return offset, size, intervals

    def read_needle(self, needle_id: int,
                    cookie: Optional[int] = None) -> Needle:
        offset, size, intervals = self.locate_needle(needle_id)
        parts = [self._read_interval(iv) for iv in intervals]
        blob = b"".join(parts)
        n = Needle()
        n.read_bytes(blob, offset, size, self.version)
        if cookie is not None and n.cookie != cookie:
            raise EcError(f"cookie mismatch for needle {needle_id:x}")
        return n

    def _read_interval(self, iv: Interval) -> bytes:
        shard_id, inner_offset = iv.to_shard_id_and_offset(
            self.large_block_size, self.small_block_size,
            data_shards=self.family.data_shards)
        return self.read_shard_span(shard_id, inner_offset, iv.size)

    def read_shard_span(self, shard_id: int, offset: int, size: int) -> bytes:
        """Read ladder: local shard -> in-memory tail stripe (inline
        volumes) -> remote hook -> reconstruct."""
        shard = self.shards.get(shard_id)
        if shard is not None:
            data = shard.read_at(size, offset)
            if len(data) == size:
                return data
            if self.tail_reader is not None:
                # the span runs past the shard log's durable extent:
                # the remainder lives in the partially-filled tail
                # stripe (data still buffered, or parity not yet
                # committed for the current row)
                rest = self.tail_reader(shard_id, offset + len(data),
                                        size - len(data))
                if rest is None:
                    # the flusher committed the row between the pread
                    # and the tail lookup — the bytes are on disk now
                    data = shard.read_at(size, offset)
                    if len(data) == size:
                        return data
                else:
                    return data + rest
            raise EcError(
                f"short read shard {shard_id} at {offset}+{size}")
        if self.tail_reader is not None:
            data = self.tail_reader(shard_id, offset, size)
            if data is not None:
                return data
        if self.remote_reader is not None:
            try:
                data = self.remote_reader(shard_id, offset, size)
            except Exception:
                data = None  # unreachable holder: degrade, don't fail
            if data is not None and len(data) == size:
                return data
            # a truncated remote answer degrades to reconstruction too:
            # the holder is damaged, but >=10 survivors can still serve
        return self._recover_span(shard_id, offset, size)

    def recover_stats(self) -> dict:
        """This volume's recovered-block cache occupancy + the process'
        cumulative degraded-read stage stats."""
        out = RECOVER_STATS.snapshot()
        out["cache_blocks"] = len(self._recover_cache)
        out["cache_bytes"] = self._recover_cache.size_bytes
        return out

    # -- degraded reads -------------------------------------------------------
    def _recover_span(self, target_shard: int, offset: int,
                      size: int) -> bytes:
        """Serve a missing shard's span by reconstruction — the fast
        degraded-read path.  Recovery is block-aligned: the span's
        covering WEED_EC_RECOVER_BLOCK_KB blocks are recovered (not the
        exact span), cached in the bounded per-volume LRU, and served
        from cache for every later read that lands in them.  Concurrent
        misses on one block are single-flighted; misses on different
        blocks that picked the same survivors decode in one stacked GF
        mat-vec (recover.py).  With no local shard to size blocks
        against (shard_size unknown) the exact span becomes the unit —
        still coalesced and cached."""
        self._tls.busy = 0.0
        with tracing.span(
                "ec.recover.serve",
                tags={"shard": target_shard, "offset": offset,
                      "size": size}) as sp:
            cache_bytes, block, coalesce = recover_knobs()
            shard_size = self.shard_size
            # recovery units must be sub-shard-aligned so vector codes
            # (alpha > 1) see whole interleaved lane groups; the KB-sized
            # block knob is always a multiple of alpha already
            align = self.family.sub_shards
            if block <= 0 or shard_size <= 0:
                lo = (offset // align) * align
                end = -(-(offset + size) // align) * align
                spans = [(lo, end - lo)]
            else:
                lo = (offset // block) * block
                end = max(offset + size,
                          min(shard_size,
                              -(-(offset + size) // block) * block))
                end = -(-end // align) * align
                spans = [(s, min(block, end - s))
                         for s in range(lo, end, block)]
            parts = []
            for bstart, blen in spans:
                key = (target_shard, bstart, blen)
                parts.append(self._recover_cache.get_or_recover(
                    key, lambda bs=bstart, bl=blen: self._recover_block(
                        target_shard, bs, bl),
                    cache_bytes, coalesce))
            blob = parts[0] if len(parts) == 1 else b"".join(parts)
            out = blob[offset - spans[0][0]:offset - spans[0][0] + size]
            if len(out) != size:
                raise EcError(
                    f"recovered span short for shard {target_shard} at "
                    f"{offset}+{size}: got {len(out)}")
        # the span measured the whole degraded read; the serve stage is
        # that wall minus this thread's fetch+decode busy seconds
        RECOVER_STATS.add_stage(
            "serve", max(0.0, (sp.duration or 0.0)
                         - getattr(self._tls, "busy", 0.0)))
        return out

    # per-thread fetch+decode busy seconds inside the current span, so
    # the serve stage reports assembly/wait overhead, not a double count
    _tls = threading.local()

    def _recover_block(self, target_shard: int, offset: int,
                       size: int) -> bytes:
        """One block's survivor fan-out + decode (the single-flight
        leader's job): fetch >=10 survivor spans, then reconstruct ONLY
        the target row through the decode-plan cache and the span-decode
        batcher."""
        blk0 = time.perf_counter()
        try:
            with tracing.span(
                    "ec.recover.fetch",
                    tags={"shard": target_shard, "bytes": size}) as fsp:
                survivors, inputs = self._fetch_survivors(
                    target_shard, offset, size)
            RECOVER_STATS.add_stage("fetch", fsp.duration or 0.0)
            out = self._recover_batcher.decode(
                survivors, target_shard, inputs)
            return np.ascontiguousarray(out).tobytes()
        finally:
            self._tls.busy = (getattr(self._tls, "busy", 0.0)
                              + (time.perf_counter() - blk0))

    def _fetch_survivors(self, target_shard: int, offset: int,
                         size: int) -> tuple[tuple, np.ndarray]:
        """Collect exactly DATA_SHARDS_COUNT survivor spans for one
        recovery (recoverOneRemoteEcShardInterval, store_ec.go:328-382).

        Survivor fetches fan out in PARALLEL like the reference's
        per-shard goroutines: local shards are read synchronously (disk,
        cheap, first-10-wins), then the remaining remote candidates are
        requested at once on a SHARED pool and the first arrivals win —
        a degraded read during an outage costs ~one RPC round-trip, not
        ten serial ones.  Queued stragglers are cancelled; in-flight
        ones drain on the shared pool (remote_reader RPCs carry their
        own timeouts).  Returns (sorted survivor ids, (k, L) stack in
        that order) — the decode-plan cache key and its matching input;
        k is the volume's code family's data-shard count."""
        k = self.family.data_shards
        shards: dict[int, np.ndarray] = {}
        remote_candidates: list[int] = []
        for sid in range(TOTAL_SHARDS_COUNT):
            if sid == target_shard:
                continue
            shard = self.shards.get(sid)
            if shard is not None:
                if len(shards) >= k:
                    continue  # reconstruct needs exactly k survivors
                data = shard.read_at(size, offset)
                if len(data) != size and self.tail_reader is not None:
                    # inline volume: the span runs past the shard log's
                    # durable extent.  The tail stripe serves pending
                    # rows; past that a DATA shard's content is
                    # definitionally zero (parity rows are encoded over
                    # the zero-padded row), while a parity shard without
                    # tail coverage is simply not a survivor
                    rest = self.tail_reader(sid, offset + len(data),
                                            size - len(data))
                    if rest is None and sid < k:
                        rest = b"\x00" * (size - len(data))
                    if rest is not None:
                        data += rest
                if len(data) == size:
                    shards[sid] = np.frombuffer(data, dtype=np.uint8)
            elif self.remote_reader is not None:
                remote_candidates.append(sid)
        if len(shards) < k and remote_candidates:
            import concurrent.futures as cf

            from ...qos import classify as qos_classify
            from ...rpc.http_rpc import current_deadline, set_deadline

            # pool workers don't share this thread's locals: pin the
            # caller's propagated deadline and QoS context on each fetch
            # so survivor RPCs stay inside the budget the client handed
            # us and keep their class downstream
            dl = current_deadline()
            qctx = (qos_classify.current_class(),
                    qos_classify.current_tenant())

            def fetch(sid: int):
                prev = set_deadline(dl)
                prev_q = qos_classify.set_qos(*qctx)
                try:
                    return self.remote_reader(sid, offset, size)
                finally:
                    qos_classify.set_qos(*prev_q)
                    set_deadline(prev)

            pool = _recover_pool()
            futs = {pool.submit(fetch, sid): sid
                    for sid in remote_candidates}
            try:
                for fut in cf.as_completed(futs):
                    try:
                        data = fut.result()
                    except Exception:
                        data = None
                    if data is not None and len(data) == size:
                        shards[futs[fut]] = np.frombuffer(data,
                                                          dtype=np.uint8)
                        if len(shards) >= k:
                            break
            finally:
                for fut in futs:
                    fut.cancel()
        if len(shards) < k:
            raise EcError(
                f"need {k} shards to recover shard "
                f"{target_shard}, only {len(shards)} available")
        survivors = tuple(sorted(shards))[:k]
        return survivors, np.stack([shards[sid] for sid in survivors])

    def _decode_span(self, survivors: tuple, target: int,
                     inputs: np.ndarray) -> np.ndarray:
        """The batcher's decode hook: one cached decode row applied to
        the (possibly multi-span) survivor stack.  An explicitly-pinned
        encoder backend decodes through reconstruct_one on that backend
        (RS volumes only — pinned backends speak the RS layout); the
        default rides the size-dispatched reconstruct_span with this
        volume's code family."""
        if self._encoder is not None \
                and self.family.name == "rs_vandermonde":
            shard_list: list[Optional[np.ndarray]] = \
                [None] * TOTAL_SHARDS_COUNT
            for i, sid in enumerate(survivors):
                shard_list[sid] = inputs[i]
            return self._encoder.reconstruct_one(shard_list, target)
        slab_key = None
        if (inputs.nbytes >= codec_mod.recover_device_min_bytes()
                and codec_mod.recover_device_enabled()):
            # content identity for the device slab pool: consecutive
            # decodes of the same survivor spans (another missing shard,
            # or a block re-recovered after cache eviction) reuse the
            # HBM-resident upload instead of re-crossing the link
            slab_key = hashlib.blake2b(
                np.ascontiguousarray(inputs), digest_size=16).digest()
        return codec_mod.reconstruct_span(
            survivors, inputs, target,
            self.family.data_shards, TOTAL_SHARDS_COUNT,
            slab_key=slab_key, family=self.family)

    # -- delete (ec_volume_delete.go) -----------------------------------------
    def delete_needle(self, needle_id: int):
        """Tombstone the .ecx entry in place + journal the id in .ecj."""
        with self._ecx_lock:
            pos = self._search_ecx(needle_id)
            if pos is None:
                return
            self._mark_ecx_deleted(pos)
        with self._ecj_lock:
            self._ecj.seek(0, 2)
            self._ecj.write(struct.pack(">Q", needle_id))
            self._ecj.flush()
            self.ecj_file_size += t.NEEDLE_ID_SIZE

    def _mark_ecx_deleted(self, pos: int):
        size_off = (pos * t.NEEDLE_MAP_ENTRY_SIZE
                    + t.NEEDLE_ID_SIZE + t.OFFSET_SIZE)
        os.pwrite(self._ecx.fileno(),
                  struct.pack(">i", t.TOMBSTONE_FILE_SIZE), size_off)

    # -- lifecycle ------------------------------------------------------------
    def close(self):
        for shard in self.shards.values():
            shard.close()
        self.shards.clear()
        self._recover_cache.clear()
        if self._ecx:
            self._ecx.close()
            self._ecx = None
        if self._ecj:
            self._ecj.close()
            self._ecj = None

    def destroy(self):
        base = self.base_file_name()
        for shard in list(self.shards.values()):
            shard.destroy()
        self.shards.clear()
        self.close()
        for ext in (".ecx", ".ecj", ".vif"):
            try:
                os.remove(base + ext)
            except FileNotFoundError:
                pass


def rebuild_ecx_file(base_file_name: str):
    """Replay .ecj tombstones into .ecx then remove the journal
    (RebuildEcxFile, ec_volume_delete.go:53-98)."""
    if not os.path.exists(base_file_name + ".ecj"):
        return
    with open(base_file_name + ".ecx", "r+b") as ecx:
        ecx_size = os.path.getsize(base_file_name + ".ecx")
        n_entries = ecx_size // t.NEEDLE_MAP_ENTRY_SIZE

        with open(base_file_name + ".ecj", "rb") as ecj:
            while True:
                buf = ecj.read(t.NEEDLE_ID_SIZE)
                if len(buf) != t.NEEDLE_ID_SIZE:
                    break
                pos = search_sorted_index(
                    ecx.fileno(), n_entries, struct.unpack(">Q", buf)[0])
                if pos is not None:
                    size_off = (pos * t.NEEDLE_MAP_ENTRY_SIZE
                                + t.NEEDLE_ID_SIZE + t.OFFSET_SIZE)
                    os.pwrite(ecx.fileno(),
                              struct.pack(">i", t.TOMBSTONE_FILE_SIZE),
                              size_off)
    os.remove(base_file_name + ".ecj")
