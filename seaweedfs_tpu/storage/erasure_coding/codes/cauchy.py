"""Cauchy MDS (10, 4): same geometry as RS, cheaper decode planning.

Generator is the systematic [I; C] with C[i, j] = 1/(x_i + y_j), y_j = j for
the data shards and x_i = 10 + i for the parity shards (disjoint sets, so
every square submatrix of C is invertible — MDS by construction).

The planner never runs a k x k Gauss-Jordan sweep: with e erased data shards
the survivor system reduces to an e x e Cauchy subsystem whose inverse has a
closed form (``gf256.cauchy_inverse``), so plan construction is O(e^2 * k)
instead of O(k^3).  Plans are bit-identical to brute-force inversion of the
same generator — the tests assert this — just cheaper to build.
"""

from __future__ import annotations

import numpy as np

from ....ops import gf256
from ....ops.rs_numpy import ReconstructError
from .base import CodeFamily


class CauchyMDS(CodeFamily):
    name = "cauchy"
    data_shards = 10
    parity_shards = 4

    def encode_matrix(self):
        return gf256.build_cauchy_matrix(self.data_shards, self.total_shards)

    def _build_decode_rows(self, survivors, targets):
        k = self.data_shards
        if len(survivors) != k:
            raise ReconstructError(
                f"cauchy: decode plan needs exactly {k} survivors, "
                f"got {len(survivors)}")
        for t in targets:
            if not 0 <= t < self.total_shards:
                raise ReconstructError(f"target shard {t} out of range")
        full = self.encode_matrix()
        mt = gf256.mul_table()
        sset = set(survivors)
        col = {s: i for i, s in enumerate(survivors)}
        data_surv = [s for s in survivors if s < k]
        par_surv = [s for s in survivors if s >= k]
        missing = [m for m in range(k) if m not in sset]
        # |survivors| == k forces |par_surv| == |missing|: the erased data
        # shards are recovered through an e x e Cauchy subsystem
        #   sum_m C[p_i, m] x_m = parity(p_i) + sum_d C[p_i, d] x_d
        # whose inverse B is closed-form — no Gauss-Jordan.
        rec = {}
        if missing:
            binv = gf256.cauchy_inverse(tuple(par_surv), tuple(missing))
            for j, m in enumerate(missing):
                row = np.zeros(k, dtype=np.uint8)
                for i, p in enumerate(par_surv):
                    row[col[p]] = binv[j, i]
                for d in data_surv:
                    acc = 0
                    for i, p in enumerate(par_surv):
                        acc ^= int(mt[binv[j, i], full[p, d]])
                    row[col[d]] = acc
                rec[m] = row
        rows = []
        for t in targets:
            if t in sset:
                row = np.zeros(k, dtype=np.uint8)
                row[col[t]] = 1
            elif t < k:
                row = rec[t]
            else:
                # Missing parity: its encode row composed over recovered data.
                row = np.zeros(k, dtype=np.uint8)
                for d in data_surv:
                    row[col[d]] = full[t, d]
                for m in missing:
                    c = int(full[t, m])
                    if c:
                        row = row ^ mt[c, rec[m]]
            rows.append(row)
        return np.stack(rows)

    def decode_kind(self) -> str:
        return "cauchy closed-form inverse (O(e^2) plans)"
