"""Code-family registry and per-collection policy.

Families (all 14 shards on the wire, so shard spread / heartbeats /
``.ecNN`` naming are family-agnostic):

    rs_vandermonde  RS(10,4), today's format and the default.
    cauchy          Cauchy MDS(10,4): same geometry, closed-form decode
                    planning instead of Gauss-Jordan.
    pm_msr          Product-matrix MSR(14,5): 2 bytes read per rebuilt byte
                    on single-shard repair (vs 10 for RS) at 2.8x storage —
                    the cold/archival point.

Policy resolution for a new volume's collection (first match wins):

    WEED_EC_CODE_<COLLECTION>   per-collection override (non-alnum -> "_",
                                upper-cased; empty collection -> DEFAULT)
    filer path-config           ``ec_code`` on the matching PathConf rule
    WEED_EC_CODE                cluster-wide default override
    rs_vandermonde              built-in default

Volumes carry their family in ``.vif`` metadata (``code_family``), so the
policy only ever applies at encode time — mixed clusters read old volumes
with the family they were written with.
"""

from __future__ import annotations

import os
import re
import threading

from .base import CodeFamily, RepairPlan  # noqa: F401 (re-export)
from .cauchy import CauchyMDS
from .pm_msr import ProductMatrixMSR
from .rs_vandermonde import RSVandermonde

DEFAULT_FAMILY = "rs_vandermonde"

_FAMILIES = {}
for _cls in (RSVandermonde, CauchyMDS, ProductMatrixMSR):
    _FAMILIES[_cls.name] = _cls()


def family_names() -> list:
    return list(_FAMILIES)


def get_family(name: str = None) -> CodeFamily:
    """Resolve a family by name; None/"" means the default (RS)."""
    if not name:
        name = DEFAULT_FAMILY
    try:
        return _FAMILIES[name]
    except KeyError:
        raise ValueError(
            f"unknown EC code family {name!r} (known: {family_names()})")


def describe_families() -> dict:
    return {name: fam.describe() for name, fam in _FAMILIES.items()}


def _collection_env_key(collection: str) -> str:
    slug = re.sub(r"[^A-Za-z0-9]", "_", collection or "DEFAULT").upper()
    return f"WEED_EC_CODE_{slug}"


def family_for_collection(collection: str, path_conf=None) -> str:
    """Pick the code family name for a new EC volume in ``collection``.

    ``path_conf`` is an optional filer ``PathConf`` (or anything with an
    ``ec_code`` attribute) from the collection's matching rule.
    """
    name = os.environ.get(_collection_env_key(collection))
    if not name:
        name = getattr(path_conf, "ec_code", "") or None
    if not name:
        name = os.environ.get("WEED_EC_CODE")
    get_family(name)  # validate (raises on typos before any shard is cut)
    return name or DEFAULT_FAMILY


# -- rebuild read-amplification accounting ----------------------------------

_amp_lock = threading.Lock()
_amp_totals = {}  # family -> [read_bytes, rebuilt_bytes]


def note_rebuild(family: str, read_bytes: int, rebuilt_bytes: int) -> None:
    """Record one rebuild's traffic; mirrors to maintenance_* metrics.

    ``read_bytes`` counts survivor bytes *consumed* by the rebuilder — for
    projection repairs that is the post-projection size, i.e. what crosses
    the network — so the ratio is the repair-bandwidth figure of merit."""
    with _amp_lock:
        tot = _amp_totals.setdefault(family, [0, 0])
        tot[0] += int(read_bytes)
        tot[1] += int(rebuilt_bytes)
        amp = tot[0] / tot[1] if tot[1] else 0.0
    try:  # metrics registry is optional at import time (tools, tests)
        from ....stats import metrics as _m
        _m.MaintEcRebuildReadBytes.labels(family).inc(int(read_bytes))
        _m.MaintEcRebuildRebuiltBytes.labels(family).inc(int(rebuilt_bytes))
        _m.MaintEcRebuildReadAmpGauge.labels(family).set(amp)
    except Exception:
        pass


def rebuild_read_amp_snapshot() -> dict:
    """{family: {read_bytes, rebuilt_bytes, read_amp}} since process start."""
    with _amp_lock:
        return {
            fam: {"read_bytes": r, "rebuilt_bytes": w,
                  "read_amp": round(r / w, 4) if w else None}
            for fam, (r, w) in _amp_totals.items()
        }
