"""Vandermonde RS(10,4) — today's wire format and the default family.

Delegates matrix building and the decode-plan cache to ``ops.gf256`` /
``ops.rs_numpy`` so the plans (and their lru-cache statistics) stay shared
with the pinned-encoder and legacy reconstruct paths: one cache, byte-for-
byte identical behavior for every volume encoded before this tier existed.
"""

from __future__ import annotations

from ....ops import gf256, rs_numpy
from .base import CodeFamily


class RSVandermonde(CodeFamily):
    name = "rs_vandermonde"
    data_shards = 10
    parity_shards = 4

    def encode_matrix(self):
        return gf256.build_matrix(self.data_shards, self.total_shards)

    def decode_rows(self, survivors, targets):
        return rs_numpy.decode_rows(self.data_shards, self.total_shards,
                                    survivors, targets)

    def plan_cache_info(self) -> dict:
        info = rs_numpy.decode_plan_cache_info()
        total = info.hits + info.misses
        return {"hits": info.hits, "misses": info.misses,
                "size": info.currsize,
                "hit_ratio": round(info.hits / total, 4) if total else None}

    def decode_kind(self) -> str:
        return "vandermonde gauss-jordan (shared lru cache)"
