"""Code-family base: the contract every erasure-code family implements.

A family is a (data_shards, parity_shards, sub_shards) geometry plus the
GF(2^8) matrices that drive it:

- ``encode_matrix()``: the full systematic generator over *lanes*.  A shard
  is split into ``sub_shards`` (alpha) interleaved lanes — byte t of a block
  belongs to lane ``t % alpha`` — so the generator is
  ``(total*alpha, data*alpha)`` with the top ``data*alpha`` rows the
  identity.  Scalar codes (RS, Cauchy) have alpha == 1 and this degenerates
  to the classic ``(total, data)`` matrix.
- ``decode_rows(survivors, targets)``: the decode planner.  Given exactly
  ``data_shards`` survivors (any mix of data and parity) it returns the
  matrix mapping the survivor lane stack straight to the target shards'
  lanes — one GF mat-vec per degraded span, never a full Reconstruct.
  Plans are cached per family, so the plan cache is keyed on the code
  family by construction, and each family may build its plan with its own
  cheap inversion (closed-form Cauchy, lane-block inversion for MSR).
- ``repair_plan(lost, alive)``: what to *read* to rebuild a shard.  MDS
  scalar codes read k full shards; regenerating codes read small
  projections from d > k helpers instead (``kind == "projection"``), which
  is where the rebuild read-amplification win comes from.

Everything here is host-side NumPy; the hot kernels (native GFNI, JAX) are
passed in as ``apply_fn`` so the device pipeline reuses its persistent
jitted parity step with a different matrix and nothing else changed.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from dataclasses import dataclass

import numpy as np

from ....ops import gf256
from ....ops.rs_numpy import ReconstructError, gf_apply_matrix

PLAN_CACHE_SIZE = 4096


@dataclass(frozen=True, eq=False)
class RepairPlan:
    """What to read (and how to combine it) to rebuild ``lost``.

    kind:    "decode"     — read k full survivor shards, run decode_rows.
             "projection" — read a 1/alpha-size projection from each of d
                            helpers; combine with ``combine``.
    reads:   ((shard_id, fraction_of_shard_read), ...) in helper order.
    vector:  helper-side projection vector (alpha,) for "projection" plans:
             each helper ships ``vector @ its_lane_stack``.
    combine: (alpha, d) matrix turning the stacked helper projections into
             the lost shard's lanes.
    """

    kind: str
    lost: int
    reads: tuple
    vector: tuple = None
    combine: np.ndarray = None

    @property
    def helpers(self) -> tuple:
        return tuple(s for s, _ in self.reads)

    @property
    def read_fraction(self) -> float:
        """Total survivor bytes consumed per rebuilt shard (the read amp)."""
        return float(sum(f for _, f in self.reads))


class CodeFamily:
    """Base class; subclasses set the geometry and the generator matrix."""

    name = "?"
    data_shards = 0
    parity_shards = 0
    sub_shards = 1       # alpha: lanes per shard (1 for scalar MDS codes)
    repair_helpers = 0   # d: helpers per projection repair (0: none)

    def __init__(self):
        self._plan_lock = threading.Lock()
        self._plans = OrderedDict()
        self._plan_hits = 0
        self._plan_misses = 0

    # -- geometry -----------------------------------------------------------

    @property
    def total_shards(self) -> int:
        return self.data_shards + self.parity_shards

    def check_block(self, nbytes: int) -> None:
        if nbytes % self.sub_shards:
            raise ReconstructError(
                f"{self.name}: block of {nbytes} bytes is not divisible by "
                f"sub_shards={self.sub_shards}")

    # -- matrices -----------------------------------------------------------

    def encode_matrix(self) -> np.ndarray:
        """(total*alpha, data*alpha) systematic generator, read-only."""
        raise NotImplementedError

    def parity_matrix(self) -> np.ndarray:
        """The parity lane rows ((total-data)*alpha, data*alpha)."""
        return self.encode_matrix()[self.data_shards * self.sub_shards:]

    # -- lane interleaving ---------------------------------------------------
    # Byte t of a block belongs to lane t % alpha.  Because every block size
    # the striper produces is divisible by alpha, lane index is uniform over
    # the whole shard file and any alpha-aligned range is self-contained.

    def to_lanes(self, arr: np.ndarray) -> np.ndarray:
        """(m, L) byte rows -> (m*alpha, L/alpha) lane rows."""
        a = self.sub_shards
        if a == 1:
            return arr
        m, length = arr.shape
        self.check_block(length)
        return (arr.reshape(m, length // a, a).swapaxes(1, 2)
                .reshape(m * a, length // a))

    def from_lanes(self, lanes: np.ndarray) -> np.ndarray:
        """(m*alpha, W) lane rows -> (m, W*alpha) byte rows."""
        a = self.sub_shards
        if a == 1:
            return lanes
        ma, width = lanes.shape
        m = ma // a
        return (lanes.reshape(m, a, width).swapaxes(1, 2)
                .reshape(m, width * a))

    # -- encode / decode -----------------------------------------------------

    def encode_blocks(self, data: np.ndarray, apply_fn=None) -> np.ndarray:
        """(data_shards, L) data rows -> (parity_shards, L) parity rows."""
        apply_fn = apply_fn or gf_apply_matrix
        lanes = self.to_lanes(np.ascontiguousarray(data))
        par = apply_fn(self.parity_matrix(), np.ascontiguousarray(lanes))
        return np.ascontiguousarray(self.from_lanes(np.asarray(par)))

    def decode_blocks(self, survivors, inputs: np.ndarray, targets,
                      apply_fn=None) -> np.ndarray:
        """Reconstruct ``targets`` from the (k, L) survivor stack."""
        apply_fn = apply_fn or gf_apply_matrix
        rows = self.decode_rows(tuple(survivors), tuple(targets))
        lanes = self.to_lanes(np.ascontiguousarray(inputs))
        out = apply_fn(np.asarray(rows), np.ascontiguousarray(lanes))
        return np.ascontiguousarray(self.from_lanes(np.asarray(out)))

    def choose_survivors(self, alive) -> tuple:
        """Pick the decode read set: lowest shard ids first, so the all-data
        identity fast path is taken whenever the data shards are alive."""
        picked = tuple(sorted(int(s) for s in alive))[:self.data_shards]
        if len(picked) < self.data_shards:
            raise ReconstructError(
                f"{self.name}: need {self.data_shards} survivors, "
                f"have {len(picked)}")
        return picked

    # -- decode planner ------------------------------------------------------

    def decode_rows(self, survivors, targets) -> np.ndarray:
        """(len(targets)*alpha, data*alpha) decode matrix: maps the lane
        stack of exactly ``data_shards`` survivors (in the given order) to
        the targets' lanes.  Cached per (survivors, targets)."""
        survivors = tuple(int(s) for s in survivors)
        targets = tuple(int(t) for t in targets)
        key = (survivors, targets)
        with self._plan_lock:
            rows = self._plans.get(key)
            if rows is not None:
                self._plan_hits += 1
                self._plans.move_to_end(key)
                return rows
            self._plan_misses += 1
        rows = self._build_decode_rows(survivors, targets)
        rows = np.ascontiguousarray(rows)
        rows.setflags(write=False)
        with self._plan_lock:
            self._plans[key] = rows
            while len(self._plans) > PLAN_CACHE_SIZE:
                self._plans.popitem(last=False)
        return rows

    def _build_decode_rows(self, survivors, targets) -> np.ndarray:
        """Generic planner: invert the survivors' lane submatrix.  Families
        with structure (Cauchy) override this with a cheaper construction."""
        k, a = self.data_shards, self.sub_shards
        if len(survivors) != k:
            raise ReconstructError(
                f"{self.name}: decode plan needs exactly {k} survivors, "
                f"got {len(survivors)}")
        full = self.encode_matrix()
        for t in targets:
            if not 0 <= t < self.total_shards:
                raise ReconstructError(f"target shard {t} out of range")
        if survivors == tuple(range(k)):
            inv = None  # identity submatrix: skip the inversion entirely
        else:
            lane_rows = [s * a + lane for s in survivors for lane in range(a)]
            try:
                inv = gf256.gf_invert(full[lane_rows])
            except np.linalg.LinAlgError:
                raise ReconstructError(
                    f"{self.name}: survivor set {survivors} is singular")
        rows = []
        for t in targets:
            tr = full[t * a:(t + 1) * a]
            rows.append(tr if inv is None else gf256.gf_matmul(tr, inv))
        return np.concatenate(rows)

    def plan_cache_info(self) -> dict:
        with self._plan_lock:
            hits, misses, size = (self._plan_hits, self._plan_misses,
                                  len(self._plans))
        total = hits + misses
        return {"hits": hits, "misses": misses, "size": size,
                "hit_ratio": round(hits / total, 4) if total else None}

    # -- repair -------------------------------------------------------------

    def repair_plan(self, lost: int, alive) -> RepairPlan:
        """Read plan for rebuilding ``lost``.  Base: MDS decode from k full
        survivors.  Regenerating families override with projection plans."""
        alive = [s for s in alive if s != lost]
        chosen = self.choose_survivors(alive)
        return RepairPlan(kind="decode", lost=int(lost),
                          reads=tuple((s, 1.0) for s in chosen))

    def project(self, block: np.ndarray, vector) -> np.ndarray:
        """Helper-side projection: (L,) shard bytes x (alpha,) vector ->
        (L/alpha,) bytes.  Only meaningful when sub_shards > 1."""
        if self.sub_shards == 1:
            raise ReconstructError(
                f"{self.name}: scalar code has no projection repair")
        vec = np.asarray(vector, dtype=np.uint8).reshape(1, self.sub_shards)
        lanes = self.to_lanes(np.asarray(block, dtype=np.uint8).reshape(1, -1))
        return gf_apply_matrix(vec, np.ascontiguousarray(lanes))[0]

    def combine_projections(self, plan: RepairPlan,
                            projections: np.ndarray) -> np.ndarray:
        """(d, W) stacked helper projections -> (alpha*W,) lost shard bytes."""
        if plan.combine is None:
            raise ReconstructError(f"{self.name}: plan has no combine step")
        lanes = gf_apply_matrix(plan.combine,
                                np.ascontiguousarray(projections))
        return self.from_lanes(lanes)[0] if self.sub_shards > 1 else lanes[0]

    # -- introspection -------------------------------------------------------

    def single_repair_read_fraction(self) -> float:
        """Survivor bytes consumed per rebuilt byte for a one-shard repair."""
        if self.repair_helpers:
            return self.repair_helpers / self.sub_shards
        return float(self.data_shards)

    def describe(self) -> dict:
        return {
            "name": self.name,
            "data_shards": self.data_shards,
            "parity_shards": self.parity_shards,
            "total_shards": self.total_shards,
            "sub_shards": self.sub_shards,
            "repair_helpers": self.repair_helpers,
            "single_repair_read_amp": self.single_repair_read_fraction(),
            "decode": self.decode_kind(),
            "plan_cache": self.plan_cache_info(),
        }

    def decode_kind(self) -> str:
        return "lane-block inversion (cached)"
