"""Product-matrix MSR (14, 5): repair-optimal regenerating code.

Rashmi-Shah-Kumar product-matrix construction at the canonical d = 2k-2
point (IT Trans. 2011; PAPERS.md "Fast Product-Matrix Regenerating Codes"),
instantiated over GF(2^8) as:

    n = 14 shards, k = 5 data shards, d = 8 repair helpers,
    alpha = 4 sub-shards per shard, B = k * alpha = 20 message symbols.

MSR codes cannot exist above rate ~1/2 at d = 2k-2, so this family trades
capacity (2.8x storage overhead vs RS(10,4)'s 1.4x) for repair bandwidth:
rebuilding one lost shard reads a 1/alpha-size projection from each of d
helpers — d/alpha = 2 bytes moved per rebuilt byte instead of k_rs = 10.
That is the cold/archival point of the policy knob, not a replacement for
RS on hot data.

Construction (all arithmetic in GF(2^8), evaluation points theta_i = i):

    Psi_i = (1, theta_i, ..., theta_i^(d-1))          encoding row, node i
    phi_i = (1, theta_i, ..., theta_i^(alpha-1))      first half of Psi_i
    lambda_i = theta_i^alpha                           all distinct because
                                                       gcd(alpha, 255) = 1
    M = [S1; S2], S1/S2 symmetric alpha x alpha holding the B message
    symbols; node i stores w_i = Psi_i M = phi_i S1 + lambda_i phi_i S2.

The raw map A: message params -> all n*alpha stored symbols is made
systematic by precoding with the inverse of its top k*alpha block, so data
shards hold plain volume bytes and undegraded reads never touch the code.

Repair of node f from any d helpers: helper h ships the alpha->1 projection
w_h . phi_f; stacking the d projections gives Psi_H (M phi_f), and because
Psi_H is Vandermonde it is invertible, yielding M phi_f = (S1 phi_f,
S2 phi_f) — whence w_f = S1 phi_f + lambda_f S2 phi_f by symmetry of S1/S2.
The combine matrix below is exactly [I | lambda_f I] Psi_H^-1.
"""

from __future__ import annotations

import functools

import numpy as np

from ....ops import gf256
from ....ops.rs_numpy import ReconstructError
from .base import CodeFamily, RepairPlan


def _theta(i: int) -> int:
    return i


def _phi(i: int, alpha: int) -> list:
    return [gf256.gf_exp(_theta(i), c) for c in range(alpha)]


def _lambda(i: int, alpha: int) -> int:
    return gf256.gf_exp(_theta(i), alpha)


@functools.lru_cache(maxsize=4)
def _raw_and_generator(k: int, total: int, alpha: int):
    """Build A (raw param->symbol map) and the systematic generator G.

    The B = k*alpha message parameters are the free entries of the two
    symmetric alpha x alpha matrices S1, S2 (alpha*(alpha+1)/2 each).
    Row (i*alpha + s) of A is the coefficient vector of stored symbol s of
    node i:  w_i[s] = sum_r phi_i[r] S1[r, s] + lambda_i sum_r phi_i[r]
    S2[r, s], where S[r, s] is the parameter indexed by the sorted pair.
    """
    pairs = [(a, b) for a in range(alpha) for b in range(alpha) if a <= b]
    npairs = len(pairs)
    nparams = 2 * npairs
    if nparams != k * alpha:
        raise ValueError("pm_msr geometry mismatch: B != k*alpha")
    raw = np.zeros((total * alpha, nparams), dtype=np.uint8)
    for i in range(total):
        phi = _phi(i, alpha)
        lam = _lambda(i, alpha)
        for s in range(alpha):
            row = raw[i * alpha + s]
            for which in range(2):
                scale = 1 if which == 0 else lam
                for p, (a, b) in enumerate(pairs):
                    # S[r, s] with sorted (r, s) == (a, b): r = a when s = b,
                    # r = b when s = a (one term only when a == b).
                    coeff = 0
                    if s == b:
                        coeff ^= phi[a]
                    if s == a and a != b:
                        coeff ^= phi[b]
                    row[which * npairs + p] = gf256.gf_mul(scale, coeff)
    precode = gf256.gf_invert(raw[:k * alpha])
    gen = gf256.gf_matmul(raw, precode)
    gen.setflags(write=False)
    return raw, gen


class ProductMatrixMSR(CodeFamily):
    name = "pm_msr"
    data_shards = 5
    parity_shards = 9
    sub_shards = 4
    repair_helpers = 8  # d = 2k - 2

    def encode_matrix(self):
        return _raw_and_generator(self.data_shards, self.total_shards,
                                  self.sub_shards)[1]

    def repair_plan(self, lost: int, alive) -> RepairPlan:
        lost = int(lost)
        if not 0 <= lost < self.total_shards:
            raise ReconstructError(f"shard {lost} out of range")
        helpers = tuple(sorted(int(s) for s in alive if int(s) != lost))
        if len(helpers) < self.repair_helpers:
            # Not enough helpers for the bandwidth-optimal path; fall back
            # to the MDS decode plan (any k survivors).
            return super().repair_plan(lost, helpers)
        helpers = helpers[:self.repair_helpers]
        frac = 1.0 / self.sub_shards
        return RepairPlan(
            kind="projection", lost=lost,
            reads=tuple((h, frac) for h in helpers),
            vector=tuple(_phi(lost, self.sub_shards)),
            combine=self._combine_matrix(lost, helpers))

    @functools.lru_cache(maxsize=256)
    def _combine_matrix(self, lost: int, helpers: tuple) -> np.ndarray:
        """(alpha, d) matrix: [I | lambda_lost I] Psi_helpers^-1."""
        a, d = self.sub_shards, self.repair_helpers
        psi = np.zeros((d, d), dtype=np.uint8)
        for r, h in enumerate(helpers):
            for c in range(d):
                psi[r, c] = gf256.gf_exp(_theta(h), c)
        try:
            psi_inv = gf256.gf_invert(psi)
        except np.linalg.LinAlgError:
            raise ReconstructError(f"pm_msr: helper set {helpers} singular")
        lam = _lambda(lost, a)
        sel = np.zeros((a, d), dtype=np.uint8)
        for r in range(a):
            sel[r, r] = 1
            sel[r, a + r] = lam
        out = gf256.gf_matmul(sel, psi_inv)
        out.setflags(write=False)
        return out

    def decode_kind(self) -> str:
        return ("lane-block inversion (cached); single-shard repair via "
                "d-helper projections")
