""".idx file handling: a flat stream of 16-byte entries.

Entry = needle_id(8 BE) | offset(4 BE, ÷8) | size(4 BE signed) — the same
16-byte records the reference appends per write and replays on load
(weed/storage/idx/walk.go:12-50).  A zero offset or tombstone size records a
deletion.
"""

from __future__ import annotations

import struct
from typing import Callable, Iterator

from . import types as t

_ENTRY = struct.Struct(">QIi")


def pack_entry(needle_id: int, actual_offset: int, size: int) -> bytes:
    return _ENTRY.pack(
        needle_id, t.to_stored_offset(actual_offset), size
    )


def unpack_entry(b: bytes) -> tuple[int, int, int]:
    """-> (needle_id, actual_offset, size)"""
    nid, stored, size = _ENTRY.unpack(b)
    return nid, t.from_stored_offset(stored), size


def iter_index(data: bytes, start: int = 0) -> Iterator[tuple[int, int, int]]:
    for pos in range(start, len(data) - len(data) % t.NEEDLE_MAP_ENTRY_SIZE,
                     t.NEEDLE_MAP_ENTRY_SIZE):
        yield unpack_entry(data[pos:pos + t.NEEDLE_MAP_ENTRY_SIZE])


def walk_index_file(path: str,
                    fn: Callable[[int, int, int], None],
                    start_from: int = 0):
    """Stream entries from an .idx file, calling fn(id, actual_offset, size)."""
    with open(path, "rb") as f:
        f.seek(start_from)
        while True:
            chunk = f.read(t.NEEDLE_MAP_ENTRY_SIZE * 4096)
            if not chunk:
                break
            for entry in iter_index(chunk):
                fn(*entry)
