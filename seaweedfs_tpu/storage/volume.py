"""Volume: one append-only .dat + .idx pair with an in-RAM needle index.

Semantics parity with the reference's weed/storage/volume*.go:
  * write: dedup identical re-writes (volume_write.go isFileUnchanged:34-53),
    cookie check against existing needle (doWriteRequest:143-160), append-only
    with monotonic needle-map updates
  * delete: append a zero-data tombstone needle, record TombstoneFileSize in
    the index (doDeleteRequest:211-231)
  * read: index lookup -> one pread -> CRC verify (volume_read.go:19-60)
  * vacuum: Compact2 copy-live-by-index into .cpd/.cpx with bumped compaction
    revision, then CommitCompact with makeupDiff replaying writes that raced
    the copy (volume_vacuum.go:67,102,190)
  * load: superblock read + index/dat integrity check that truncates a
    corrupt tail (volume_checking.go:17-60)
"""

from __future__ import annotations

import os
import threading
import time
from typing import Optional

from . import idx as idx_mod
from . import native_engine
from . import types as t
from .backend import DiskFile
from .needle import (CURRENT_VERSION, Needle, NeedleError, get_actual_size,
                     read_needle_header)
from .needle_map import NeedleMap, new_needle_map
from .super_block import SUPER_BLOCK_SIZE, ReplicaPlacement, SuperBlock
from .ttl import EMPTY_TTL, TTL
from .. import tracing


class VolumeError(Exception):
    pass


class NotFoundError(VolumeError):
    pass


class DeletedError(VolumeError):
    pass


class CookieMismatchError(VolumeError):
    pass


class _FsyncBatcher:
    """Group-commit fsync worker (volume_write.go:233-306 semantics):
    writers append under the volume lock, then park here until one fsync
    covers their append — N concurrent writers share a single fsync
    instead of paying one each."""

    def __init__(self, sync_fn):
        self._sync_fn = sync_fn
        self._cond = threading.Condition()
        self._pending = 0
        self._synced = 0
        self._failed_upto = 0
        self._error: Optional[Exception] = None
        self._closed = False
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def wait_durable(self):
        with self._cond:
            self._pending += 1
            ticket = self._pending
            self._cond.notify_all()
            while (self._synced < ticket and self._failed_upto < ticket
                   and not self._closed):
                self._cond.wait(1.0)
            if self._synced < ticket and self._failed_upto >= ticket:
                # the group commit covering this write failed: surface it
                # to the writer instead of acknowledging a lost write
                raise VolumeError(f"fsync failed: {self._error}")

    def _run(self):
        while True:
            with self._cond:
                while self._pending <= max(self._synced,
                                           self._failed_upto) \
                        and not self._closed:
                    self._cond.wait(0.5)
                if self._closed:
                    return
                target = self._pending
            try:
                self._sync_fn()  # outside the condition: appends continue
            except Exception as e:
                # a dead worker must never strand waiters: fail only the
                # tickets this batch covered and keep serving later ones
                # (the next sync may succeed, e.g. after ENOSPC clears)
                with self._cond:
                    self._error = e
                    self._failed_upto = target
                    self._cond.notify_all()
                continue
            with self._cond:
                self._synced = target
                self._cond.notify_all()

    def close(self):
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        self._thread.join(timeout=5)


class Volume:
    def __init__(self, directory: str, collection: str, vid: int,
                 replica_placement: Optional[ReplicaPlacement] = None,
                 ttl: TTL = EMPTY_TTL, preallocate: int = 0,
                 needle_map_kind: str = "memory", fsync: bool = False):
        self.dir = directory
        self.collection = collection
        self.id = vid
        self.needle_map_kind = needle_map_kind
        self.fsync = fsync
        self._batcher: Optional[_FsyncBatcher] = None
        self.lock = threading.RLock()
        self.data: Optional[DiskFile] = None
        self.nm: Optional[NeedleMap] = None
        self._last_append_at_ns = 0
        self._last_modified_ts = 0
        self.is_compacting = False
        self.last_compact_index_offset = 0
        self.last_compact_revision = 0
        self._read_only = False
        self._load(create_if_missing=True,
                   replica_placement=replica_placement or ReplicaPlacement(),
                   ttl=ttl)

    # -- naming --------------------------------------------------------------
    def file_name(self, ext: str = "") -> str:
        base = (f"{self.collection}_{self.id}" if self.collection
                else str(self.id))
        return os.path.join(self.dir, base + ext)

    @property
    def version(self) -> int:
        return self.super_block.version

    @property
    def ttl(self) -> TTL:
        return self.super_block.ttl

    # -- native-engine coupling ----------------------------------------------
    # read_only and the append/modify timestamps are mirrored with the
    # native engine: its TCP fast path writes volumes without entering
    # Python, so these views merge both sides.

    @property
    def read_only(self) -> bool:
        return self._read_only

    @read_only.setter
    def read_only(self, value: bool):
        self._read_only = value
        nm = getattr(self, "nm", None)
        if isinstance(nm, native_engine.NativeNeedleMap):
            nm.set_flags(read_only=value)

    @property
    def last_append_at_ns(self) -> int:
        nm = getattr(self, "nm", None)
        if isinstance(nm, native_engine.NativeNeedleMap):
            return max(self._last_append_at_ns, nm.last_append_ns())
        return self._last_append_at_ns

    @last_append_at_ns.setter
    def last_append_at_ns(self, value: int):
        self._last_append_at_ns = value

    @property
    def last_modified_ts(self) -> int:
        nm = getattr(self, "nm", None)
        if isinstance(nm, native_engine.NativeNeedleMap):
            return max(self._last_modified_ts, nm.last_modified())
        return self._last_modified_ts

    @last_modified_ts.setter
    def last_modified_ts(self, value: int):
        self._last_modified_ts = value

    def _append_blob(self, blob: bytes) -> int:
        """Append one record to the .dat.  In native mode the engine's
        per-volume mutex serializes this with TCP fast-path writes."""
        if isinstance(self.nm, native_engine.NativeNeedleMap):
            return self.nm.append_dat(blob)
        return self.data.append(blob)

    def _native_writable(self) -> bool:
        """Whether the native fast path may write this volume directly.
        Replicated and TTL volumes qualify too: the engine fans writes
        out to the vid's published replica set (svn_set_replicas; 307
        when unconfigured) and stamps lastModified for the TTL read
        check, so neither bypasses production semantics."""
        return self.version == CURRENT_VERSION

    # -- load/create ---------------------------------------------------------
    def _load(self, create_if_missing: bool, replica_placement=None,
              ttl: TTL = EMPTY_TTL):
        dat = self.file_name(".dat")
        exists = os.path.exists(dat)
        tiered = None
        # a .vif recording remote tier files means the volume was tiered
        # (volume.tier.upload).  The remote is authoritative and the
        # volume is readonly — a kept local .dat (keep_local=True) is
        # only a read cache, never a write target, so the two can't
        # diverge across restarts.
        from .volume_info import load_volume_info

        vif = load_volume_info(self.file_name(".vif"))
        if vif is not None and vif.files:
            self.read_only = True
            if not exists:
                from .tier import open_tiered_dat

                tiered = open_tiered_dat(vif)
        if tiered is not None:
            self.data = tiered
            import io

            self.super_block = SuperBlock.from_file(
                io.BytesIO(self.data.read_at(1024, 0)))
        elif not exists:
            if not create_if_missing:
                raise VolumeError(f"volume data file {dat} does not exist")
            self.data = DiskFile(dat, create=True)
            self.super_block = SuperBlock(
                version=CURRENT_VERSION,
                replica_placement=replica_placement or ReplicaPlacement(),
                ttl=ttl,
            )
            self.data.write_at(self.super_block.to_bytes(), 0)
        else:
            self.data = DiskFile(dat)
            with open(dat, "rb") as f:
                self.super_block = SuperBlock.from_file(f)
        idx_path = self.file_name(".idx")
        if exists or tiered is not None:
            self.last_append_at_ns = self._check_integrity(idx_path)
        if exists:
            # seed quiescence tracking from the .dat mtime so -quietFor
            # gates survive a restart (volume_loading.go:63 semantics)
            self.last_modified_ts = int(os.path.getmtime(dat))
        self.nm = self._new_needle_map(dat, idx_path, tiered)

    def _new_needle_map(self, dat: str, idx_path: str, tiered):
        """Pick the index implementation.  The in-memory kinds upgrade to
        the native engine's shared map when the library is available (one
        index serves both the Python handlers and the native TCP fast
        path); sqlite and tiered volumes keep their Python maps."""
        want_native = (self.needle_map_kind in ("memory", "native")
                       and tiered is None
                       and native_engine.available()
                       and isinstance(self.data, DiskFile))
        if want_native:
            try:
                return native_engine.NativeNeedleMap(
                    dat, idx_path, self.version, self._native_writable(),
                    self.read_only, self.fsync,
                    ttl_sec=self.ttl.minutes() * 60 if self.ttl else 0,
                    extra_copies=(
                        self.super_block.replica_placement.copy_count()
                        - 1),
                    ttl_raw=self.ttl.to_uint32() if self.ttl else 0)
            except (OSError, RuntimeError):
                pass
        kind = ("memory" if self.needle_map_kind == "native"
                else self.needle_map_kind)
        return new_needle_map(kind, idx_path)

    def _check_integrity(self, idx_path: str) -> int:
        """Verify index<->dat consistency; truncate corrupt tails.
        Mirrors CheckAndFixVolumeDataIntegrity (volume_checking.go:17-46)."""
        if not os.path.exists(idx_path):
            if self.data.size() > self.super_block.block_size:
                raise VolumeError(f"idx file {idx_path} does not exist")
            return 0
        index_size = os.path.getsize(idx_path)
        if index_size % t.NEEDLE_MAP_ENTRY_SIZE != 0:
            index_size -= index_size % t.NEEDLE_MAP_ENTRY_SIZE
            with open(idx_path, "r+b") as f:
                f.truncate(index_size)
        if index_size == 0:
            return 0
        healthy = index_size
        last_ns = 0
        with open(idx_path, "rb") as f:
            for i in range(1, 11):
                off = index_size - i * t.NEEDLE_MAP_ENTRY_SIZE
                if off < 0:
                    break
                f.seek(off)
                nid, a_off, size = idx_mod.unpack_entry(
                    f.read(t.NEEDLE_MAP_ENTRY_SIZE))
                try:
                    last_ns = self._verify_entry(nid, a_off, size)
                    break
                except EOFError:
                    healthy = off
                    continue
                except VolumeError:
                    break
        if healthy < index_size:
            with open(idx_path, "r+b") as f:
                f.truncate(healthy)
        return last_ns

    def _verify_entry(self, nid: int, offset: int, size: int) -> int:
        if offset == 0:
            return 0
        if size < 0:
            # deletion entry: tombstone needle sits at EOF
            disk = get_actual_size(0, self.version)
            blob = self.data.read_at(disk, self.data.size() - disk)
            if len(blob) < disk:
                raise EOFError
            n = Needle()
            n.read_bytes(blob, self.data.size() - disk, 0, self.version)
            if n.id != nid:
                raise VolumeError(
                    f"index key {nid:x} != needle id {n.id:x}")
            return n.append_at_ns
        header = self.data.read_at(t.NEEDLE_HEADER_SIZE, offset)
        if len(header) < t.NEEDLE_HEADER_SIZE:
            raise EOFError
        n, _ = read_needle_header(header)
        if n.size != size:
            raise VolumeError("size mismatch")
        ts_off = (offset + t.NEEDLE_HEADER_SIZE + size
                  + t.NEEDLE_CHECKSUM_SIZE)
        ts = self.data.read_at(t.TIMESTAMP_SIZE, ts_off)
        if len(ts) < t.TIMESTAMP_SIZE:
            raise EOFError
        append_at_ns = int.from_bytes(ts, "big")
        tail = offset + get_actual_size(size, self.version)
        if self.data.size() > tail:
            self.data.truncate(tail)
        return append_at_ns

    # -- write ---------------------------------------------------------------
    def _is_file_unchanged(self, n: Needle) -> bool:
        if self.ttl:
            return False
        nv = self.nm.get(n.id)
        if nv is None or nv.offset == 0 or not t.size_is_valid(nv.size):
            return False
        old = Needle()
        try:
            blob = self.data.read_at(
                get_actual_size(nv.size, self.version), nv.offset)
            old.read_bytes(blob, nv.offset, nv.size, self.version)
        except (NeedleError, Exception):
            return False
        return (old.cookie == n.cookie and old.checksum == n.checksum
                and old.data == n.data)

    def write_needle(self, n: Needle, check_cookie: bool = True
                     ) -> tuple[int, int, bool]:
        """Append a needle; returns (offset, size, is_unchanged)."""
        with self.lock:
            if self.read_only:
                raise VolumeError(f"volume {self.id} is read only")
            actual = get_actual_size(len(n.data), self.version)
            if self.nm.content_size() + actual > t.MAX_POSSIBLE_VOLUME_SIZE:
                raise VolumeError(
                    f"volume size limit {t.MAX_POSSIBLE_VOLUME_SIZE} exceeded")
            if not n.has_ttl and self.ttl:
                n.ttl = self.ttl
                n._set_flag(0x10)
            if self._is_file_unchanged(n):
                return 0, len(n.data), True
            nv = self.nm.get(n.id)
            if nv is not None:
                header = self.data.read_at(t.NEEDLE_HEADER_SIZE, nv.offset)
                existing, _ = read_needle_header(header)
                if n.cookie == 0 and not check_cookie:
                    n.cookie = existing.cookie
                if existing.cookie != n.cookie:
                    raise CookieMismatchError(
                        f"mismatching cookie {n.cookie:x}")
            n.append_at_ns = time.time_ns()
            blob = n.to_bytes(self.version)
            offset = self._append_blob(blob)
            self.last_append_at_ns = n.append_at_ns
            if isinstance(self.nm, native_engine.NativeNeedleMap):
                # the "newer offset wins" check must read the map under
                # its own lock: a native-port write to the same id may
                # have landed after our pre-append lookup
                self.nm.put_if_newer(n.id, offset, n.size)
            elif nv is None or nv.offset < offset:
                self.nm.put(n.id, offset, n.size)
            if n.last_modified > self.last_modified_ts:
                self.last_modified_ts = n.last_modified
        if self.fsync:
            # outside the lock: other writers append while this one waits
            # for the shared group-commit fsync
            with tracing.span("fsync.group_commit", tags={"vid": self.id}):
                self._fsync_batcher().wait_durable()
        return offset, n.size, False

    def delete_needle(self, n: Needle) -> int:
        """Tombstone-append; returns the freed size (0 if absent)."""
        with self.lock:
            if self.read_only:
                raise VolumeError(f"volume {self.id} is read only")
            nv = self.nm.get(n.id)
            if nv is None or not t.size_is_valid(nv.size):
                return 0
            size = nv.size
            n.data = b""
            n.append_at_ns = time.time_ns()
            blob = n.to_bytes(self.version)
            offset = self._append_blob(blob)
            self.last_append_at_ns = n.append_at_ns
            self.nm.delete(n.id, offset)
        if self.fsync:
            with tracing.span("fsync.group_commit", tags={"vid": self.id}):
                self._fsync_batcher().wait_durable()
        return size

    # -- read ----------------------------------------------------------------
    def read_needle(self, nid: int, cookie: Optional[int] = None) -> Needle:
        with self.lock:
            nv = self.nm.get(nid)
            if nv is None or nv.offset == 0:
                raise NotFoundError(f"needle {nid:x} not found")
            if t.size_is_deleted(nv.size):
                raise DeletedError(f"needle {nid:x} already deleted")
            blob = self.data.read_at(
                get_actual_size(nv.size, self.version), nv.offset)
            n = Needle()
            n.read_bytes(blob, nv.offset, nv.size, self.version)
            if cookie is not None and n.cookie != cookie:
                raise CookieMismatchError(
                    f"cookie mismatch for needle {nid:x}")
            if n.has_ttl and self.ttl and n.last_modified:
                expiry = n.last_modified + self.ttl.minutes() * 60
                if time.time() >= expiry:
                    raise NotFoundError(f"needle {nid:x} expired")
            return n

    def read_needle_blob(self, offset: int, size: int) -> bytes:
        return self.data.read_at(get_actual_size(size, self.version), offset)

    def read_needle_slice(self, nid: int, cookie: Optional[int] = None,
                          min_size: int = 0):
        """Zero-copy read: ``(needle, data_offset, data_length, fd)``
        where `needle` carries full metadata (flags/name/mime/etag/TTL)
        but an EMPTY data field — the payload is meant to go straight
        from the .dat to the socket via sendfile.  Returns None when the
        record is not eligible (v1 volume, remote tier, compressed or
        manifest payload, below `min_size`) so the caller falls back to
        read_needle(); raises the same errors as read_needle for
        missing/deleted/expired needles.  The returned fd is dup'd — the
        caller owns it and must close it — so a racing vacuum commit that
        swaps the .dat cannot invalidate an in-flight transfer."""
        from .needle import VERSION1, VERSION3

        with self.lock:
            if self.version == VERSION1:
                return None
            fileno = getattr(self.data, "fileno", None)
            raw_fd = fileno() if fileno is not None else None
            if raw_fd is None:
                return None  # remote tier (or closed handle)
            nv = self.nm.get(nid)
            if nv is None or nv.offset == 0:
                raise NotFoundError(f"needle {nid:x} not found")
            if t.size_is_deleted(nv.size):
                raise DeletedError(f"needle {nid:x} already deleted")
            if nv.size <= 0:
                return None  # empty payload: nothing to sendfile
            head = self.data.read_at(t.NEEDLE_HEADER_SIZE + 4, nv.offset)
            if len(head) < t.NEEDLE_HEADER_SIZE + 4:
                raise NotFoundError(f"needle {nid:x}: truncated record")
            n = Needle()
            n.parse_header(head)
            if n.size != nv.size:
                return None  # index/data divergence: read_needle reports it
            data_size = int.from_bytes(
                head[t.NEEDLE_HEADER_SIZE:t.NEEDLE_HEADER_SIZE + 4], "big")
            if data_size < min_size or data_size == 0:
                return None
            # the metadata sections, CRC and (v3) appendAtNs trail the data
            meta_len = n.size - 4 - data_size
            tail_len = meta_len + t.NEEDLE_CHECKSUM_SIZE
            if self.version == VERSION3:
                tail_len += t.TIMESTAMP_SIZE
            tail_off = nv.offset + t.NEEDLE_HEADER_SIZE + 4 + data_size
            tail = self.data.read_at(tail_len, tail_off)
            if len(tail) < tail_len:
                raise NotFoundError(f"needle {nid:x}: truncated record")
            # a synthetic zero-length dataSize prefix parses just the
            # metadata sections into the needle, skipping the payload
            n._parse_body_v2(b"\x00\x00\x00\x00" + tail[:meta_len])
            n.data = b""
            # stored CRC, unverified (the payload never enters memory);
            # the write path stores the raw value, so the etag matches
            n.checksum = int.from_bytes(tail[meta_len:meta_len + 4], "big")
            if self.version == VERSION3:
                n.append_at_ns = int.from_bytes(tail[meta_len + 4:], "big")
            if cookie is not None and n.cookie != cookie:
                raise CookieMismatchError(
                    f"cookie mismatch for needle {nid:x}")
            if n.is_compressed or n.is_chunk_manifest:
                return None  # the response path needs these in memory
            if n.has_ttl and self.ttl and n.last_modified:
                expiry = n.last_modified + self.ttl.minutes() * 60
                if time.time() >= expiry:
                    raise NotFoundError(f"needle {nid:x} expired")
            fd = os.dup(raw_fd)
        return n, nv.offset + t.NEEDLE_HEADER_SIZE + 4, data_size, fd

    # -- scan (export/fsck support; volume_read.go:213-232) ------------------
    def scan(self):
        """Yield (needle, offset) for every record in the .dat, in file order."""
        pos = self.super_block.block_size
        end = self.data.size()
        while pos < end:
            header = self.data.read_at(t.NEEDLE_HEADER_SIZE, pos)
            if len(header) < t.NEEDLE_HEADER_SIZE:
                break
            n, _ = read_needle_header(header)
            body_len = (get_actual_size(n.size, self.version)
                        - t.NEEDLE_HEADER_SIZE)
            body = self.data.read_at(body_len, pos + t.NEEDLE_HEADER_SIZE)
            n.read_needle_body(body, self.version)
            yield n, pos
            pos += t.NEEDLE_HEADER_SIZE + body_len

    # -- stats ---------------------------------------------------------------
    def content_size(self) -> int:
        return self.nm.content_size()

    def deleted_size(self) -> int:
        return self.nm.deleted_size()

    def file_count(self) -> int:
        return self.nm.file_count

    def deleted_count(self) -> int:
        return self.nm.deleted_count

    def max_file_key(self) -> int:
        return self.nm.max_file_key()

    def garbage_level(self) -> float:
        if self.content_size() == 0:
            return 0.0
        return self.deleted_size() / self.content_size()

    def file_stat(self) -> tuple[int, int]:
        """(dat size, idx size).  Takes the volume lock: a vacuum commit
        closes and swaps self.data under it, and an unlocked fstat on the
        closed handle races to a TypeError (found by the mixed-path
        soak: the dying heartbeat thread then strands the whole node)."""
        with self.lock:
            idx_path = self.file_name(".idx")
            return (self.data.size(),
                    os.path.getsize(idx_path)
                    if os.path.exists(idx_path) else 0)

    def index_file_size(self) -> int:
        return self.file_stat()[1]

    # -- vacuum --------------------------------------------------------------
    def compact(self):
        """Copy live needles (by index) into .cpd/.cpx with a bumped
        compaction revision (Compact2, volume_vacuum.go:67-100)."""
        with self.lock:
            self.is_compacting = True
            # flush buffered idx appends before snapshotting the watermark,
            # or makeupDiff would replay the whole index
            self.nm.flush()
            self.data.sync()
            self.last_compact_index_offset = self.index_file_size()
            self.last_compact_revision = self.super_block.compaction_revision
            # snapshot the live map: writes may race the copy (makeupDiff
            # replays them at commit) and would otherwise mutate the dict
            # mid-iteration
            snapshot = [(nid, nv.offset, nv.size)
                        for nid, nv in self.nm.items_ascending()]
        try:
            self._copy_data_based_on_index(snapshot)
        finally:
            self.is_compacting = False

    def _copy_data_based_on_index(self, snapshot):
        new_sb = SuperBlock(
            version=self.super_block.version,
            replica_placement=self.super_block.replica_placement,
            ttl=self.super_block.ttl,
            compaction_revision=self.super_block.compaction_revision + 1,
            extra=self.super_block.extra,
        )
        now = time.time()
        with DiskFile(self.file_name(".cpd"), create=True) as dst, \
                open(self.file_name(".cpx"), "wb") as new_idx:
            dst.truncate(0)
            dst.write_at(new_sb.to_bytes(), 0)
            new_offset = new_sb.block_size
            for nid, offset, size in snapshot:
                if offset == 0 or t.size_is_deleted(size):
                    continue
                blob = self.read_needle_blob(offset, size)
                n = Needle()
                n.read_bytes(blob, offset, size, self.version)
                if (n.has_ttl and self.ttl and n.last_modified
                        and now >= n.last_modified + self.ttl.minutes() * 60):
                    continue
                dst.write_at(blob, new_offset)
                new_idx.write(idx_mod.pack_entry(nid, new_offset, n.size))
                new_offset += len(blob)

    def commit_compact(self):
        """Swap in .cpd/.cpx, replaying any writes that raced the copy
        (CommitCompact + makeupDiff, volume_vacuum.go:102-190)."""
        with self.lock:
            if isinstance(self.nm, native_engine.NativeNeedleMap):
                # barrier: no native fast-path write may land after the
                # diff replay reads the idx tail (clients get a 307 and
                # retry over HTTP, which blocks on self.lock)
                self.nm.quiesce()
            self.nm.flush()
            try:
                self._makeup_diff()
            except VolumeError:
                os.remove(self.file_name(".cpd"))
                os.remove(self.file_name(".cpx"))
                if isinstance(self.nm, native_engine.NativeNeedleMap):
                    # aborted commit: the old files stay live, so native
                    # writes may resume
                    self.nm.set_flags(writable=self._native_writable())
                raise
            self.nm.close()
            self.data.close()
            os.replace(self.file_name(".cpd"), self.file_name(".dat"))
            os.replace(self.file_name(".cpx"), self.file_name(".idx"))
            self._load(create_if_missing=False)

    def _makeup_diff(self):
        idx_path = self.file_name(".idx")
        index_size = os.path.getsize(idx_path)
        if index_size <= self.last_compact_index_offset:
            return
        # newest-first unique entries appended after the compaction snapshot
        updated: dict[int, tuple[int, int]] = {}
        with open(idx_path, "rb") as f:
            off = index_size - t.NEEDLE_MAP_ENTRY_SIZE
            while off >= self.last_compact_index_offset:
                f.seek(off)
                nid, a_off, size = idx_mod.unpack_entry(
                    f.read(t.NEEDLE_MAP_ENTRY_SIZE))
                updated.setdefault(nid, (a_off, size))
                off -= t.NEEDLE_MAP_ENTRY_SIZE
        if not updated:
            return
        with open(self.file_name(".cpd"), "rb") as f:
            new_sb = SuperBlock.from_file(f)
        if new_sb.compaction_revision != self.last_compact_revision + 1:
            raise VolumeError(
                f"compact revision {new_sb.compaction_revision} != "
                f"{self.last_compact_revision + 1}")
        with DiskFile(self.file_name(".cpd")) as dst, \
                open(self.file_name(".cpx"), "ab") as new_idx:
            for nid, (a_off, size) in updated.items():
                offset = dst.size()
                if offset % t.NEEDLE_PADDING_SIZE != 0:
                    offset += (t.NEEDLE_PADDING_SIZE
                               - offset % t.NEEDLE_PADDING_SIZE)
                if a_off != 0 and t.size_is_valid(size):
                    blob = self.read_needle_blob(a_off, size)
                    dst.write_at(blob, offset)
                    new_idx.write(idx_mod.pack_entry(nid, offset, size))
                else:
                    tomb = Needle(id=nid, cookie=0x12345678,
                                  append_at_ns=time.time_ns())
                    dst.write_at(tomb.to_bytes(self.version), offset)
                    new_idx.write(idx_mod.pack_entry(
                        nid, 0, t.TOMBSTONE_FILE_SIZE))

    # -- lifecycle -----------------------------------------------------------
    def _fsync_batcher(self) -> _FsyncBatcher:
        with self.lock:
            if self._batcher is None:
                self._batcher = _FsyncBatcher(self._durable_sync)
            return self._batcher

    def _durable_sync(self):
        """One group commit: .dat fsync + .idx flush+fsync — an
        acknowledged write must survive a host crash, so the index entry
        must be as durable as the data it points at."""
        with self.lock:
            self.nm.sync()
            self.data.sync()
        from ..stats import metrics as stats

        stats.VolumeFsyncBatchCounter.inc()

    def sync(self):
        with self.lock:
            self.nm.flush()
            self.data.sync()

    def close(self):
        if self._batcher is not None:
            self._batcher.close()
            self._batcher = None
        with self.lock:
            if self.nm is not None:
                self.nm.close()
            if self.data is not None:
                self.data.close()

    def destroy(self):
        with self.lock:
            self.close()
            from .erasure_coding import TOTAL_SHARDS_COUNT, to_ext

            exts = [".dat", ".idx", ".vif", ".cpd", ".cpx", ".note"]
            if any(os.path.exists(self.file_name(to_ext(i)))
                   for i in range(TOTAL_SHARDS_COUNT)):
                # the .vif doubles as the EC volume's sidecar (version +
                # fused shard CRCs); deleting the original volume after
                # ec.encode must not strip it from the surviving shards
                exts.remove(".vif")
            for ext in exts:
                try:
                    os.remove(self.file_name(ext))
                except FileNotFoundError:
                    pass
