"""Core storage types: needle ids, offsets, sizes, cookies, file ids.

Layout parity with the reference's weed/storage/types package:
  * NeedleId — uint64 (needle_id_type.go:9-14)
  * Cookie   — uint32 (needle_types.go:19)
  * Offset   — 4 bytes on disk, stored as actual_offset/8, capping volumes at
    32 GB (offset.go:24,61-68); big-endian byte order on disk
  * Size     — int32; negative or -1 means deleted; -1 is the tombstone
    (needle_types.go:10-17)
  * idx entry = 8 (id) + 4 (offset) + 4 (size) = 16 bytes (needle_types.go:25)
"""

from __future__ import annotations

import struct

NEEDLE_ID_SIZE = 8
OFFSET_SIZE = 4
SIZE_SIZE = 4
COOKIE_SIZE = 4
NEEDLE_HEADER_SIZE = COOKIE_SIZE + NEEDLE_ID_SIZE + SIZE_SIZE  # 16
NEEDLE_MAP_ENTRY_SIZE = NEEDLE_ID_SIZE + OFFSET_SIZE + SIZE_SIZE  # 16
TIMESTAMP_SIZE = 8
NEEDLE_PADDING_SIZE = 8
NEEDLE_CHECKSUM_SIZE = 4
TOMBSTONE_FILE_SIZE = -1
MAX_POSSIBLE_VOLUME_SIZE = 4 * 1024 * 1024 * 1024 * 8  # 32 GB

NEEDLE_ID_EMPTY = 0


def size_is_deleted(size: int) -> bool:
    return size < 0 or size == TOMBSTONE_FILE_SIZE


def size_is_valid(size: int) -> bool:
    return size > 0 and size != TOMBSTONE_FILE_SIZE


def offset_to_bytes(actual_offset: int) -> bytes:
    """Actual byte offset -> 4-byte on-disk form (divided by padding unit)."""
    return struct.pack(">I", actual_offset // NEEDLE_PADDING_SIZE)


def offset_from_bytes(b: bytes) -> int:
    """4-byte on-disk form -> actual byte offset."""
    return struct.unpack(">I", b)[0] * NEEDLE_PADDING_SIZE


def to_stored_offset(actual_offset: int) -> int:
    return actual_offset // NEEDLE_PADDING_SIZE


def from_stored_offset(stored: int) -> int:
    return stored * NEEDLE_PADDING_SIZE


def size_to_bytes(size: int) -> bytes:
    return struct.pack(">I", size & 0xFFFFFFFF)


def size_from_bytes(b: bytes) -> int:
    v = struct.unpack(">I", b)[0]
    return v - (1 << 32) if v >= (1 << 31) else v


def needle_id_to_bytes(nid: int) -> bytes:
    return struct.pack(">Q", nid)


def needle_id_from_bytes(b: bytes) -> int:
    return struct.unpack(">Q", b)[0]


def cookie_to_bytes(cookie: int) -> bytes:
    return struct.pack(">I", cookie)


def cookie_from_bytes(b: bytes) -> int:
    return struct.unpack(">I", b)[0]


# -- file id strings ("vid,idhex[cookiehex]") --------------------------------


def format_file_id(volume_id: int, needle_id: int, cookie: int) -> str:
    """fid string: "<vid>,<idhex><cookie8hex>" (needle.go formatNeedleIdCookie)."""
    return f"{volume_id},{needle_id:x}{cookie:08x}"


def parse_needle_id_cookie(key_hash: str) -> tuple[int, int]:
    """Parse "<idhex><cookie8hex>" -> (needle_id, cookie); needle.go:141-158."""
    if len(key_hash) <= COOKIE_SIZE * 2:
        raise ValueError("key hash too short")
    if len(key_hash) > (NEEDLE_ID_SIZE + COOKIE_SIZE) * 2:
        raise ValueError("key hash too long")
    split = len(key_hash) - COOKIE_SIZE * 2
    return int(key_hash[:split], 16), int(key_hash[split:], 16)


def parse_file_id(fid: str) -> tuple[int, int, int]:
    """Parse "vid,<idhex><cookiehex>[_delta]" -> (vid, needle_id, cookie)."""
    if "," not in fid:
        raise ValueError(f"invalid fid {fid!r}")
    vid_s, key_hash = fid.split(",", 1)
    delta = 0
    if "_" in key_hash:
        key_hash, delta_s = key_hash.rsplit("_", 1)
        delta = int(delta_s)
    nid, cookie = parse_needle_id_cookie(key_hash)
    return int(vid_s), nid + delta, cookie
