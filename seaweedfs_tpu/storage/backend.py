"""Storage backend abstraction: positional-IO file objects.

Equivalent of the reference's BackendStorageFile interface
(weed/storage/backend/backend.go:15-23): ReadAt/WriteAt/Truncate/Close/
GetStat/Sync over a local file.  Tiered backends (S3) slot in behind the
same interface later.
"""

from __future__ import annotations

import os

from ..util import faults as _faults


class DiskFile:
    """Positional-IO wrapper over one OS file (backend/disk_file.go).
    Every operation passes the fault-injection disk hook first (a no-op
    module-bool check while no rules are loaded), so chaos tests can
    make a specific .dat file start throwing EIO and watch the volume
    demote itself to read-only."""

    def __init__(self, path: str, create: bool = False):
        self.path = path
        flags = os.O_RDWR
        if create:
            flags |= os.O_CREAT
        self._fd = os.open(path, flags, 0o644)

    def read_at(self, size: int, offset: int) -> bytes:
        if _faults.ACTIVE:
            _faults.on_disk(self.path, "read")
        return os.pread(self._fd, size, offset)

    def write_at(self, data: bytes, offset: int) -> int:
        if _faults.ACTIVE:
            _faults.on_disk(self.path, "write")
        return os.pwrite(self._fd, data, offset)

    def append(self, data: bytes) -> int:
        """Write at EOF; returns the offset the data landed at."""
        if _faults.ACTIVE:
            _faults.on_disk(self.path, "write")
        end = self.size()
        os.pwrite(self._fd, data, end)
        return end

    def truncate(self, size: int):
        os.ftruncate(self._fd, size)

    def size(self) -> int:
        return os.fstat(self._fd).st_size

    def sync(self):
        if _faults.ACTIVE:
            _faults.on_disk(self.path, "sync")
        os.fsync(self._fd)

    def close(self):
        if self._fd is not None:
            os.close(self._fd)
            self._fd = None

    def fileno(self) -> "int | None":
        """Raw fd for zero-copy sendfile; None once closed."""
        return self._fd

    @property
    def name(self) -> str:
        return self.path

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class MmapFile:
    """mmap-backed reads + positional writes (backend/memory_map/):
    reads hit the page cache mapping directly; the map is regrown lazily
    when appends extend the file."""

    def __init__(self, path: str, create: bool = False):
        import mmap as _mmap

        self._mmap_mod = _mmap
        self.path = path
        flags = os.O_RDWR
        if create:
            flags |= os.O_CREAT
        self._fd = os.open(path, flags, 0o644)
        self._map = None
        self._remap()

    def _remap(self):
        size = os.fstat(self._fd).st_size
        if self._map is not None:
            self._map.close()
            self._map = None
        if size > 0:
            self._map = self._mmap_mod.mmap(self._fd, size,
                                            access=self._mmap_mod.ACCESS_READ)

    def read_at(self, size: int, offset: int) -> bytes:
        end = offset + size
        if self._map is None or end > len(self._map):
            self._remap()
        if self._map is None:
            return b""
        return bytes(self._map[offset:min(end, len(self._map))])

    def write_at(self, data: bytes, offset: int) -> int:
        if _faults.ACTIVE:
            _faults.on_disk(self.path, "write")
        n = os.pwrite(self._fd, data, offset)
        if self._map is not None and offset + n <= len(self._map):
            self._remap()  # overwrite within the mapped range: refresh
        return n

    def append(self, data: bytes) -> int:
        if _faults.ACTIVE:
            _faults.on_disk(self.path, "write")
        end = os.fstat(self._fd).st_size
        os.pwrite(self._fd, data, end)
        return end

    def truncate(self, size: int):
        os.ftruncate(self._fd, size)
        self._remap()

    def size(self) -> int:
        return os.fstat(self._fd).st_size

    def sync(self):
        os.fsync(self._fd)

    def close(self):
        if self._map is not None:
            self._map.close()
            self._map = None
        if self._fd is not None:
            os.close(self._fd)
            self._fd = None

    def fileno(self) -> "int | None":
        """Raw fd for zero-copy sendfile; None once closed."""
        return self._fd

    @property
    def name(self) -> str:
        return self.path


class TieredFile:
    """Read-only BackendStorageFile over a remote tier
    (backend/s3_backend/s3_backend.go S3BackendStorageFile): ranged
    reads against the remote object, LRU block cache in front."""

    BLOCK = 1 << 20

    def __init__(self, fetch_range, total_size: int, name: str = "",
                 cache_blocks: int = 32):
        from collections import OrderedDict

        self._fetch = fetch_range  # (offset, size) -> bytes
        self._size = total_size
        self._name = name
        self._cache: "OrderedDict[int, bytes]" = OrderedDict()
        self._cache_blocks = cache_blocks

    def _block(self, index: int) -> bytes:
        if index in self._cache:
            self._cache.move_to_end(index)
            return self._cache[index]
        offset = index * self.BLOCK
        data = self._fetch(offset, min(self.BLOCK, self._size - offset))
        self._cache[index] = data
        if len(self._cache) > self._cache_blocks:
            self._cache.popitem(last=False)
        return data

    def read_at(self, size: int, offset: int) -> bytes:
        if offset >= self._size:
            return b""
        size = min(size, self._size - offset)
        parts = []
        while size > 0:
            index, inner = divmod(offset, self.BLOCK)
            chunk = self._block(index)[inner:inner + size]
            if not chunk:
                break
            parts.append(chunk)
            offset += len(chunk)
            size -= len(chunk)
        return b"".join(parts)

    def write_at(self, data: bytes, offset: int) -> int:
        raise OSError("tiered volume file is read-only")

    def append(self, data: bytes) -> int:
        raise OSError("tiered volume file is read-only")

    def truncate(self, size: int):
        raise OSError("tiered volume file is read-only")

    def size(self) -> int:
        return self._size

    def sync(self):
        pass

    def close(self):
        self._cache.clear()

    def fileno(self) -> "int | None":
        return None  # remote tier: no local fd to sendfile from

    @property
    def name(self) -> str:
        return self._name
