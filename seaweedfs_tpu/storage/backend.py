"""Storage backend abstraction: positional-IO file objects.

Equivalent of the reference's BackendStorageFile interface
(weed/storage/backend/backend.go:15-23): ReadAt/WriteAt/Truncate/Close/
GetStat/Sync over a local file.  Tiered backends (S3) slot in behind the
same interface later.
"""

from __future__ import annotations

import os


class DiskFile:
    """Positional-IO wrapper over one OS file (backend/disk_file.go)."""

    def __init__(self, path: str, create: bool = False):
        self.path = path
        flags = os.O_RDWR
        if create:
            flags |= os.O_CREAT
        self._fd = os.open(path, flags, 0o644)

    def read_at(self, size: int, offset: int) -> bytes:
        return os.pread(self._fd, size, offset)

    def write_at(self, data: bytes, offset: int) -> int:
        return os.pwrite(self._fd, data, offset)

    def append(self, data: bytes) -> int:
        """Write at EOF; returns the offset the data landed at."""
        end = self.size()
        os.pwrite(self._fd, data, end)
        return end

    def truncate(self, size: int):
        os.ftruncate(self._fd, size)

    def size(self) -> int:
        return os.fstat(self._fd).st_size

    def sync(self):
        os.fsync(self._fd)

    def close(self):
        if self._fd is not None:
            os.close(self._fd)
            self._fd = None

    @property
    def name(self) -> str:
        return self.path

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
