"""Offline volume tooling: index repair, export, offline compaction.

Parity with the reference's maintenance commands that operate on volume
files directly, without a running server: `weed fix` (rebuild .idx by
scanning the .dat; command/fix.go), `weed export` (dump live needles to
a tar; command/export.go), `weed compact` (offline vacuum;
command/compact.go), and `weed backup`'s local volume copy
(command/backup.go).
"""

from __future__ import annotations

import io
import os
import tarfile
import time
from typing import Callable, Optional

from . import types as t
from .backend import DiskFile
from .needle import get_actual_size, read_needle_header
from .needle_map import NeedleMap
from .super_block import SuperBlock


def _base(directory: str, collection: str, vid: int) -> str:
    name = f"{collection}_{vid}" if collection else str(vid)
    return os.path.join(directory, name)


def scan_dat(dat_path: str):
    """Yield (needle, offset) for every record in a .dat, without
    loading an index (the `weed fix`/`weed export` walk)."""
    data = DiskFile(dat_path)
    try:
        with open(dat_path, "rb") as f:
            sb = SuperBlock.from_file(f)
        pos = sb.block_size
        end = data.size()
        while pos < end:
            header = data.read_at(t.NEEDLE_HEADER_SIZE, pos)
            if len(header) < t.NEEDLE_HEADER_SIZE:
                break
            n, _ = read_needle_header(header)
            body_len = (get_actual_size(n.size, sb.version)
                        - t.NEEDLE_HEADER_SIZE)
            body = data.read_at(body_len, pos + t.NEEDLE_HEADER_SIZE)
            n.read_needle_body(body, sb.version)
            yield n, pos
            pos += t.NEEDLE_HEADER_SIZE + body_len
    finally:
        data.close()


def rebuild_index(directory: str, collection: str, vid: int) -> int:
    """`weed fix`: reconstruct the .idx from the .dat append log.  A
    record with data is a put; a zero-size record is a tombstone."""
    base = _base(directory, collection, vid)
    dat, idx = base + ".dat", base + ".idx"
    tmp = idx + ".rebuild"
    if os.path.exists(tmp):
        os.remove(tmp)
    nm = NeedleMap(tmp)
    count = 0
    for n, offset in scan_dat(dat):
        if n.size > 0 and n.data:
            nm.put(n.id, offset, n.size)
        else:
            nm.delete(n.id, offset)
        count += 1
    nm.close()
    os.replace(tmp, idx)
    return count


def export_volume(directory: str, collection: str, vid: int,
                  output_tar: str = "",
                  newer_than_ts: float = 0.0,
                  include_deleted: bool = False) -> list[dict]:
    """`weed export`: list (and optionally tar) the live needles."""
    base = _base(directory, collection, vid)
    live: dict[int, tuple] = {}
    for n, offset in scan_dat(base + ".dat"):
        if n.size > 0 and n.data:
            live[n.id] = (n, offset)
        elif not include_deleted:
            live.pop(n.id, None)
    records = []
    tar = tarfile.open(output_tar, "w") if output_tar else None
    try:
        for nid, (n, offset) in sorted(live.items()):
            last_modified = getattr(n, "last_modified", 0)
            if newer_than_ts and last_modified \
                    and last_modified < newer_than_ts:
                continue
            name = (n.name.decode(errors="replace")
                    if getattr(n, "has_name", False) and n.name
                    else f"{vid}_{nid}")
            records.append({"id": nid, "name": name,
                            "size": len(n.data), "offset": offset})
            if tar is not None:
                info = tarfile.TarInfo(name=name)
                info.size = len(n.data)
                info.mtime = last_modified or int(time.time())
                tar.addfile(info, io.BytesIO(n.data))
    finally:
        if tar is not None:
            tar.close()
    return records


def compact_offline(directory: str, collection: str, vid: int) -> dict:
    """`weed compact`: run the copy-live-data vacuum on an offline
    volume directory."""
    from .volume import Volume

    v = Volume(directory, collection, vid)
    try:
        before = v.data.size()
        v.compact()
        v.commit_compact()
        after = v.data.size()
    finally:
        v.close()
    return {"volume": vid, "before_bytes": before, "after_bytes": after,
            "reclaimed": before - after}


def shard_file_crc32c(path: str, chunk_size: int = 4 << 20,
                      throttle: Optional[Callable[[int], None]] = None
                      ) -> int:
    """Whole-file CRC32C, streamed in bounded chunks.  `throttle` is
    called with each chunk's byte count *before* the bytes are hashed —
    the curator's BytePacer plugs in here so a background scrub never
    streams a shard file faster than the paced rate (an unthrottled
    whole-file read stalls foreground I/O on the same spindle)."""
    from ..ops.crc32c import crc32c

    chunk_size = max(64 << 10, int(chunk_size))
    crc = 0
    with open(path, "rb") as f:
        while True:
            chunk = f.read(chunk_size)
            if not chunk:
                break
            if throttle is not None:
                throttle(len(chunk))
            crc = crc32c(chunk, crc)
    return crc


def verify_shard_files(base: str, stored,
                       chunk_size: int = 4 << 20,
                       throttle: Optional[Callable[[int], None]] = None
                       ) -> tuple[list, list, list]:
    """Classify the .ecNN files at `base` against the recorded CRCs:
    -> (clean, corrupt, absent) shard-id lists.  Shared by the offline
    `weed scrub` and the volume server's /admin/ec/scrub handler (where
    'absent' just means not held locally).  Raises ValueError when the
    .vif carries no CRC record."""
    from .erasure_coding import TOTAL_SHARDS_COUNT, to_ext

    if not isinstance(stored, list) or len(stored) != TOTAL_SHARDS_COUNT:
        raise ValueError(
            f"{base}.vif has no shard_crc32c record to scrub against")
    clean, corrupt, absent = [], [], []
    for sid in range(TOTAL_SHARDS_COUNT):
        path = base + to_ext(sid)
        if not os.path.exists(path):
            absent.append(sid)
        elif shard_file_crc32c(path, chunk_size=chunk_size,
                               throttle=throttle) == stored[sid]:
            clean.append(sid)
        else:
            corrupt.append(sid)
    return clean, corrupt, absent


def scrub_ec_volume(directory: str, collection: str, vid: int,
                    repair: bool = False) -> dict:
    """Verify every local .ecNN against the CRC32Cs the batched encode
    fused on device and persisted in the .vif sidecar (no reference
    analogue — the reference has no stored shard checksums to scrub
    against).  With repair=True, corrupt/missing shards are deleted and
    regenerated from survivors via the batched rebuild pipeline.

    Returns {"checked": [...], "corrupt": [...], "missing": [...],
    "repaired": [...]}."""
    from .erasure_coding import to_ext
    from .erasure_coding.encoder import load_volume_info

    base = _base(directory, collection, vid)
    info = load_volume_info(base) or {}
    stored = info.get("shard_crc32c")
    checked, corrupt, missing = verify_shard_files(base, stored)
    repaired: list[int] = []
    if repair and (corrupt or missing):
        from .erasure_coding.codes import get_family
        from .erasure_coding.encoder import rebuild_ec_files

        # clean-survivor bound is the volume's code family's data_shards
        # (10 for RS/Cauchy, 5 for pm_msr), recorded in the .vif
        family = get_family(info.get("code_family"))
        if len(checked) < family.data_shards:
            raise ValueError(
                f"only {len(checked)} clean shards — cannot rebuild "
                f"{sorted(corrupt + missing)}; corrupt files left in place")
        # move corrupt shards ASIDE (never destroy potentially-useful
        # bytes before the rebuild is known to succeed)
        for sid in corrupt:
            os.replace(base + to_ext(sid), base + to_ext(sid) + ".corrupt")
        try:
            if family.name != "rs_vandermonde":
                crcs = rebuild_ec_files(base, family=family)
            else:
                crcs = rebuild_ec_files(base)  # device path or host fallback
        except Exception:
            for sid in corrupt:  # restore the evidence
                os.replace(base + to_ext(sid) + ".corrupt",
                           base + to_ext(sid))
            raise
        # verify EVERY rebuilt shard against the record; host-path
        # rebuilds (crc None) hash the produced file
        bad = []
        for sid, crc in crcs.items():
            if crc is None:
                crc = shard_file_crc32c(base + to_ext(sid))
            if crc != stored[sid]:
                bad.append(sid)
        if bad:
            for sid in corrupt:
                os.replace(base + to_ext(sid) + ".corrupt",
                           base + to_ext(sid))
            raise ValueError(
                f"rebuilt shards {bad} still mismatch the recorded CRCs "
                "— survivors are corrupt beyond the stored checksums")
        for sid in corrupt:
            os.remove(base + to_ext(sid) + ".corrupt")
        repaired = sorted(crcs)
    return {"checked": checked, "corrupt": corrupt,
            "missing": missing, "repaired": repaired}
