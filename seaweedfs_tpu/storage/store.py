"""Store: the volume-server-wide registry of disk locations and volumes.

Parity with weed/storage/store.go:55-73 + store_ec.go: owns DiskLocations,
routes reads/writes/deletes to volumes, assembles heartbeat payloads, and
serves EC reads with the local/remote/reconstruct ladder.
"""

from __future__ import annotations

import os
import threading
from typing import Callable, Optional

from . import types as t
from .disk_location import DiskLocation
from .erasure_coding import encoder as ec_encoder
from .erasure_coding.ec_volume import EcVolume
from .needle import Needle
from .super_block import ReplicaPlacement
from .ttl import TTL
from .volume import NotFoundError, Volume, VolumeError


class Store:
    def __init__(self, directories: list[str],
                 max_volume_counts: Optional[list[int]] = None,
                 ip: str = "127.0.0.1", port: int = 0,
                 public_url: str = "", data_center: str = "",
                 rack: str = "", ec_encoder_backend=None,
                 needle_map_kind: str = "memory", fsync: bool = False):
        counts = max_volume_counts or [8] * len(directories)
        self.locations = [DiskLocation(d, c,
                                       needle_map_kind=needle_map_kind,
                                       fsync=fsync)
                          for d, c in zip(directories, counts)]
        for loc in self.locations:
            loc.load_existing_volumes()
        self.ip = ip
        self.port = port
        self.public_url = public_url or f"{ip}:{port}"
        self.data_center = data_center
        self.rack = rack
        # master's soft volume size cap, refreshed from each heartbeat
        # response.  As in the reference, the volume server does not reject
        # writes past it (only the 32 GB hard cap applies locally); the
        # master stops assigning to oversized volumes instead
        # (volume_layout.go oversized tracking).
        self.volume_size_limit = 0
        self.lock = threading.RLock()
        self.ec_encoder_backend = ec_encoder_backend
        # called with the vid after a disk-failure read-only demotion so
        # the owning daemon can push a heartbeat immediately (the master
        # must stop assigning writes before the next pulse)
        self.on_demote: Optional[Callable[[int], None]] = None

    @property
    def url(self) -> str:
        return f"{self.ip}:{self.port}"

    # -- lookup ---------------------------------------------------------------
    def find_volume(self, vid: int) -> Optional[Volume]:
        for loc in self.locations:
            v = loc.volumes.get(vid)
            if v is not None:
                return v
        return None

    def find_ec_volume(self, vid: int) -> Optional[EcVolume]:
        for loc in self.locations:
            ev = loc.ec_volumes.get(vid)
            if ev is not None:
                return ev
        return None

    def location_of(self, vid: int) -> Optional[DiskLocation]:
        for loc in self.locations:
            if vid in loc.volumes or vid in loc.ec_volumes:
                return loc
        return None

    def has_volume(self, vid: int) -> bool:
        return self.find_volume(vid) is not None

    # -- volume admin (store.go AddVolume path) -------------------------------
    def add_volume(self, vid: int, collection: str = "",
                   replication: str = "000", ttl: str = ""):
        from .erasure_coding.inline import inline_family_for

        with self.lock:
            if self.find_volume(vid) is not None \
                    or self.find_ec_volume(vid) is not None:
                raise VolumeError(f"volume {vid} already exists")
            loc = max(self.locations, key=lambda l: l.free_slots())
            if loc.free_slots() <= 0:
                raise VolumeError("no free volume slots")
            # assign-time policy: an EC-policy collection with
            # WEED_EC_INLINE=1 gets shard logs as its PRIMARY write
            # path — no .dat, no replica fan-out, no post-hoc encode
            family = inline_family_for(collection)
            if family is not None:
                return loc.add_inline_volume(vid, collection,
                                             family=family)
            return loc.add_volume(
                vid, collection,
                replica_placement=ReplicaPlacement.parse(replication),
                ttl=TTL.parse(ttl))

    def delete_volume(self, vid: int):
        with self.lock:
            for loc in self.locations:
                if vid in loc.volumes:
                    loc.delete_volume(vid)
                    return
                ev = loc.ec_volumes.get(vid)
                if ev is not None and getattr(ev, "writer", None):
                    loc.ec_volumes.pop(vid)
                    ev.destroy()
                    return
            raise NotFoundError(f"volume {vid} not found")

    def mark_volume_readonly(self, vid: int, read_only: bool = True):
        v = self.find_volume(vid)
        if v is None:
            raise NotFoundError(f"volume {vid} not found")
        v.read_only = read_only

    # -- data path ------------------------------------------------------------
    def write_needle(self, vid: int, n: Needle,
                     check_cookie: bool = True) -> tuple[int, bool]:
        v = self.find_volume(vid)
        if v is None:
            ev = self.find_ec_volume(vid)
            if ev is not None and getattr(ev, "writer", None):
                # inline EC volume: the needle streams straight into
                # the striped shard logs, parity follows per stripe
                _, size, unchanged = ev.write_needle(
                    n, check_cookie=check_cookie)
                return size, unchanged
            raise NotFoundError(f"volume {vid} not found")
        try:
            _, size, unchanged = v.write_needle(
                n, check_cookie=check_cookie)
        except OSError as e:
            # a failing disk write demotes the volume to read-only on
            # the spot: reads still serve, the next heartbeat reports
            # read_only and the master stops assigning writes here
            # (store.go MarkVolumeReadonly on write error)
            self._demote_readonly(vid, v, e)
            raise VolumeError(
                f"volume {vid} demoted read-only: "
                f"disk write failed: {e}") from e
        return size, unchanged

    def _demote_readonly(self, vid: int, v: Volume, err: Exception):
        from ..stats import metrics as stats
        from ..util import glog

        try:
            v.read_only = True
        except Exception:
            # even flag persistence may fail on a dead disk; the
            # in-memory flag below is what gates writes
            v._read_only = True
        stats.VolumeReadonlyDemotions.inc()
        glog.errorf("volume %d demoted read-only after disk error: %s",
                    vid, err)
        if self.on_demote is not None:
            try:
                self.on_demote(vid)
            except Exception:
                pass  # heartbeat push is best-effort

    def read_needle(self, vid: int, nid: int,
                    cookie: Optional[int] = None) -> Needle:
        v = self.find_volume(vid)
        if v is not None:
            return v.read_needle(nid, cookie=cookie)
        ev = self.find_ec_volume(vid)
        if ev is not None:
            return ev.read_needle(nid, cookie=cookie)
        raise NotFoundError(f"volume {vid} not found")

    def delete_needle(self, vid: int, n: Needle) -> int:
        v = self.find_volume(vid)
        if v is not None:
            return v.delete_needle(n)
        ev = self.find_ec_volume(vid)
        if ev is not None:
            ev.delete_needle(n.id)
            return 0
        raise NotFoundError(f"volume {vid} not found")

    # -- EC admin (volume_grpc_erasure_coding.go handlers) --------------------
    def _resolve_ec_encoder(self):
        """-ec.backend semantics: None or "tpu" select the batched
        device pipeline (encoder=None downstream); a codec NAME
        ("cpu" | "jax" | "numpy") resolves to that host/per-row codec;
        an explicit encoder object passes through."""
        backend = self.ec_encoder_backend
        if backend is None or backend == "tpu":
            return None
        if isinstance(backend, str):
            from ..ops import codec
            from .erasure_coding import (DATA_SHARDS_COUNT,
                                         PARITY_SHARDS_COUNT)

            return codec.new_encoder(DATA_SHARDS_COUNT,
                                     PARITY_SHARDS_COUNT, backend=backend)
        return backend

    def ec_generate(self, vid: int, encoder=None, code_family: str = None):
        """VolumeEcShardsGenerate: encode a local volume into shard files.

        Backend: -ec.backend=tpu forces the streaming batched device
        pipeline; the default (None) auto-selects batched vs host codec
        by predicted throughput on this machine's host<->device link
        (write_ec_files).  Fused per-shard-file CRC32Cs from the batched
        path are persisted in the .vif sidecar for scrub tooling.

        code_family: explicit erasure-code family; None resolves the
        per-collection policy (WEED_EC_CODE[_<COLLECTION>], filer config,
        default RS).  The chosen family is recorded in the .vif so every
        later read/rebuild uses the matrices the shards were cut with.
        """
        from .erasure_coding import codes as ec_codes

        v = self.find_volume(vid)
        if v is None:
            raise NotFoundError(f"volume {vid} not found")
        family = code_family or ec_codes.family_for_collection(v.collection)
        base = v.file_name()
        v.sync()
        forced = True if (encoder is None
                          and self.ec_encoder_backend == "tpu") else None
        if family != ec_codes.DEFAULT_FAMILY:
            crcs = ec_encoder.write_ec_files(base, family=family)
        else:
            crcs = ec_encoder.write_ec_files(
                base, encoder=encoder or self._resolve_ec_encoder(),
                batched=forced)
        ec_encoder.write_sorted_file_from_idx(base)
        extra = {"code_family": family}
        if crcs:
            extra["shard_crc32c"] = crcs
        ec_encoder.save_volume_info(base, version=v.version, extra=extra)

    def ec_generate_batch(self, vids: list[int]):
        """Batched VolumeEcShardsGenerate: encode MANY local volumes in one
        device pipeline — their row chunks share (B, 10, L) dispatches
        (BASELINE config 4; no reference analogue, per-volume sequential at
        ec_encoder.go:194).  Used when -ec.backend=tpu forces the device
        path or the link-throughput auto-selection predicts the device
        pipeline beats the host codec on this machine."""
        from ..util.platform import prefer_batched_encode

        use_batched = self.ec_encoder_backend == "tpu" or (
            self.ec_encoder_backend is None and prefer_batched_encode())
        if not use_batched:
            enc = self._resolve_ec_encoder()  # resolve the codec ONCE
            for vid in vids:
                self.ec_generate(vid, encoder=enc)
            return
        from ..parallel.batched_encode import encode_volumes
        from .erasure_coding import codes as ec_codes

        vols = []
        for vid in vids:
            v = self.find_volume(vid)
            if v is None:
                raise NotFoundError(f"volume {vid} not found")
            # the shared-dispatch device pipeline speaks the RS layout;
            # collections whose policy picks another family encode
            # per-volume through the family host loop
            if (ec_codes.family_for_collection(v.collection)
                    != ec_codes.DEFAULT_FAMILY):
                self.ec_generate(vid)
                continue
            v.sync()
            vols.append(v)
        if not vols:
            return
        crc_map = encode_volumes([v.file_name() for v in vols])
        for v in vols:
            base = v.file_name()
            ec_encoder.write_sorted_file_from_idx(base)
            ec_encoder.save_volume_info(
                base, version=v.version,
                extra={"shard_crc32c": crc_map[base],
                       "code_family": ec_codes.DEFAULT_FAMILY})

    def ec_rebuild(self, vid: int, collection: str = "") -> list[int]:
        """VolumeEcShardsRebuild: regenerate missing local shard files.

        When the batched device path produced fused CRCs AND the .vif
        records the original shard CRCs, the rebuilt values are VERIFIED
        against the record — a correct rebuild reproduces the original
        bytes, so a mismatch means a survivor is silently corrupt and the
        rebuild is reported rather than laundered into the record.

        The .vif's code family picks the rebuild path: RS volumes keep
        the legacy device/host pipeline; other families run the planned
        rebuild (the family's repair-optimal read set).  Either way the
        survivor-bytes-per-rebuilt-byte traffic lands in the
        maintenance_ec_rebuild_* metrics, labeled by family."""
        from .erasure_coding import TOTAL_SHARDS_COUNT, to_ext
        from .erasure_coding import codes as ec_codes

        loc = self.location_of(vid)
        base = (loc._base_name(collection, vid) if loc
                else self.locations[0]._base_name(collection, vid))
        info = ec_encoder.load_volume_info(base) or {}
        family = info.get("code_family") or ec_codes.DEFAULT_FAMILY
        if family != ec_codes.DEFAULT_FAMILY:
            rb_stats: dict = {}
            crcs = ec_encoder.rebuild_ec_files(base, family=family,
                                               stats=rb_stats)
            if rb_stats.get("rebuilt_bytes"):
                ec_codes.note_rebuild(family, rb_stats["read_bytes"],
                                      rb_stats["rebuilt_bytes"])
        else:
            # legacy loop reads every present survivor in full: account
            # the actual traffic from the on-disk sizes
            present_bytes = sum(
                os.path.getsize(base + to_ext(i))
                for i in range(TOTAL_SHARDS_COUNT)
                if os.path.exists(base + to_ext(i)))
            crcs = ec_encoder.rebuild_ec_files(
                base, encoder=self._resolve_ec_encoder())
            rebuilt_bytes = sum(
                os.path.getsize(base + to_ext(sid)) for sid in crcs
                if os.path.exists(base + to_ext(sid)))
            if crcs and rebuilt_bytes:
                ec_codes.note_rebuild(family, present_bytes, rebuilt_bytes)
        stored = info.get("shard_crc32c")
        if isinstance(stored, list) and len(stored) == TOTAL_SHARDS_COUNT:
            bad = [sid for sid, crc in crcs.items()
                   if crc is not None and crc != stored[sid]]
            if bad:
                raise VolumeError(
                    f"rebuilt shards {bad} of volume {vid} do not match "
                    "the recorded CRCs — a survivor shard is corrupt")
        return sorted(crcs)

    def ec_mount(self, collection: str, vid: int, shard_ids: list[int]):
        loc = self.location_of(vid) or self.locations[0]
        for sid in shard_ids:
            loc.mount_ec_shard(collection, vid, sid)

    def ec_unmount(self, vid: int, shard_ids: list[int]):
        for loc in self.locations:
            if vid in loc.ec_volumes:
                for sid in shard_ids:
                    loc.unmount_ec_shard(vid, sid)
                return

    # -- heartbeat assembly (store.go CollectHeartbeat) -----------------------
    def collect_heartbeat(self) -> dict:
        volumes = []
        ec_shards = []
        max_file_key = 0
        max_volume_count = 0
        for loc in self.locations:
            max_volume_count += loc.max_volume_count
            with loc.lock:
                for vid, v in loc.volumes.items():
                    max_file_key = max(max_file_key, v.max_file_key())
                    dat_size, idx_size = v.file_stat()
                    volumes.append({
                        "id": vid,
                        "collection": v.collection,
                        "size": dat_size,
                        "file_count": v.file_count(),
                        "delete_count": v.deleted_count(),
                        "deleted_byte_count": v.deleted_size(),
                        "read_only": v.read_only,
                        "replica_placement":
                            v.super_block.replica_placement.to_byte(),
                        "ttl": v.ttl.to_uint32(),
                        "compact_revision":
                            v.super_block.compaction_revision,
                        "modified_at_second": int(v.last_modified_ts),
                    })
                for vid, ev in loc.ec_volumes.items():
                    if getattr(ev, "writer", None):
                        # inline EC volume: report as a WRITABLE volume
                        # so the master keeps assigning fids to it —
                        # parity is already current, there is nothing
                        # to seal or encode later
                        max_file_key = max(max_file_key,
                                           ev.max_file_key())
                        volumes.append({
                            "id": vid,
                            "collection": ev.collection,
                            "size": ev.writer.logical_size,
                            "file_count": ev.file_count(),
                            "delete_count": ev.deleted_count(),
                            "deleted_byte_count": ev.deleted_size(),
                            "read_only": ev.read_only,
                            "replica_placement": 0,
                            "ttl": 0,
                            "compact_revision": 0,
                            "modified_at_second":
                                int(ev.last_modified_ts),
                        })
                        continue
                    ec_shards.append({
                        "id": vid,
                        "collection": ev.collection,
                        "ec_index_bits": ev.shard_bits().bits,
                    })
        return {
            "ip": self.ip,
            "port": self.port,
            "public_url": self.public_url,
            "data_center": self.data_center,
            "rack": self.rack,
            "max_volume_count": max_volume_count,
            "max_file_key": max_file_key,
            "volumes": volumes,
            "ec_shards": ec_shards,
        }

    def status(self) -> dict:
        hb = self.collect_heartbeat()
        hb["free_slots"] = sum(l.free_slots() for l in self.locations)
        hb["volume_size_limit"] = self.volume_size_limit
        return hb

    def close(self):
        for loc in self.locations:
            loc.close()
