"""In-RAM needle index: id -> (offset, size), plus volume statistics.

Python-idiomatic equivalent of the reference's NeedleMapper family
(weed/storage/needle_map.go:24-38, needle_map_memory.go, needle_map/
memdb.go): a dict keyed by needle id with the same bookkeeping the
reference's mapMetric maintains (file/deleted counts and byte totals,
max key), an append-log .idx writer, and sorted ascending iteration for
.ecx generation (memdb.go AscendingVisit).

The reference offers memory/leveldb{,Medium,Large} variants purely as
RAM/disk trade-offs; here one implementation covers the semantics, and the
CompactMap micro-optimisation (sectioned sorted arrays, compact_map.go) is
unnecessary under CPython — dict + 16-byte tuples is the moral equivalent.
"""

from __future__ import annotations

import io
import os
from typing import Callable, Iterator, Optional

from . import idx as idx_mod
from . import types as t


class NeedleValue:
    __slots__ = ("offset", "size")

    def __init__(self, offset: int, size: int):
        self.offset = offset  # actual byte offset
        self.size = size

    def __repr__(self):
        return f"NeedleValue(offset={self.offset}, size={self.size})"


class NeedleMap:
    """id -> NeedleValue with live/deleted statistics and an .idx append log."""

    def __init__(self, index_path: Optional[str] = None):
        self._m: dict[int, NeedleValue] = {}
        self.file_count = 0
        self.deleted_count = 0
        self.deleted_bytes = 0
        self.content_bytes = 0
        self.max_key = 0
        self._index_file: Optional[io.BufferedWriter] = None
        self.index_path = index_path
        if index_path is not None:
            if os.path.exists(index_path):
                self._load_from_idx(index_path)
            self._index_file = open(index_path, "ab")

    # -- load ---------------------------------------------------------------
    def _load_from_idx(self, path: str):
        def visit(nid: int, offset: int, size: int):
            self._apply(nid, offset, size)

        idx_mod.walk_index_file(path, visit)

    def _apply(self, nid: int, offset: int, size: int):
        """Replay one idx entry (needle_map_memory.go doLoading semantics):
        a zero offset or tombstone size marks a deletion; deletions keep the
        entry with negated size so reads distinguish deleted from absent
        (compact_map.go Delete; volume_read.go:27-35)."""
        self.max_key = max(self.max_key, nid)
        if offset > 0 and size != t.TOMBSTONE_FILE_SIZE:
            prev = self._m.get(nid)
            if prev is not None and prev.size > 0:
                self.deleted_count += 1
                self.deleted_bytes += prev.size
            self._m[nid] = NeedleValue(offset, size)
            self.file_count += 1
            self.content_bytes += size
        else:
            prev = self._m.get(nid)
            if prev is not None and prev.size > 0:
                self.deleted_count += 1
                self.deleted_bytes += prev.size
                prev.size = -prev.size

    # -- mutate -------------------------------------------------------------
    def put(self, nid: int, offset: int, size: int):
        self._apply(nid, offset, size)
        self._append_idx(nid, offset, size)

    def delete(self, nid: int, offset: int):
        """Record a tombstone; offset is where the tombstone needle landed."""
        self._apply(nid, 0, t.TOMBSTONE_FILE_SIZE)
        self._append_idx(nid, offset, t.TOMBSTONE_FILE_SIZE)

    def set_in_memory(self, nid: int, offset: int, size: int):
        """Update the map without touching the idx log (for rebuilds)."""
        self._apply(nid, offset, size)

    def _append_idx(self, nid: int, offset: int, size: int):
        if self._index_file is not None:
            self._index_file.write(idx_mod.pack_entry(nid, offset, size))

    # -- query --------------------------------------------------------------
    def get(self, nid: int) -> Optional[NeedleValue]:
        return self._m.get(nid)

    def __contains__(self, nid: int) -> bool:
        return nid in self._m

    def __len__(self) -> int:
        return len(self._m)

    def ascending_visit(self, fn: Callable[[int, NeedleValue], None]):
        """Visit live entries in ascending id order (memdb.go:100-123) —
        the ordering contract .ecx files depend on."""
        for nid in sorted(self._m):
            fn(nid, self._m[nid])

    def items_ascending(self) -> Iterator[tuple[int, NeedleValue]]:
        for nid in sorted(self._m):
            yield nid, self._m[nid]

    # -- stats (needle_map.go mapMetric interface) ---------------------------
    def content_size(self) -> int:
        return self.content_bytes

    def deleted_size(self) -> int:
        return self.deleted_bytes

    def max_file_key(self) -> int:
        return self.max_key

    # -- lifecycle ----------------------------------------------------------
    def flush(self):
        if self._index_file is not None:
            self._index_file.flush()

    def close(self):
        if self._index_file is not None:
            self._index_file.flush()
            os.fsync(self._index_file.fileno())
            self._index_file.close()
            self._index_file = None


def load_needle_map_from_idx(path: str) -> NeedleMap:
    """Read-only map from an existing .idx (no append log) — the shape
    WriteSortedFileFromIdx consumes (ec_encoder.go:27-54, readNeedleMap)."""
    nm = NeedleMap()
    idx_mod.walk_index_file(path, nm._apply)
    return nm
