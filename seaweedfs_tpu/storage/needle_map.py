"""Needle index kinds: id -> (offset, size), plus volume statistics.

Equivalent of the reference's NeedleMapper family (weed/storage/
needle_map.go:15-38: memory / leveldb / leveldbMedium / leveldbLarge):

  * NeedleMap        — dict-backed (kind "memory"): simplest, ~100 B/entry
                       under CPython; fine for small volumes.
  * CompactNeedleMap — numpy struct-of-arrays (kind "compact"): 16 bytes
                       per entry like the reference's CompactMap sectioned
                       arrays (compact_map.go:10-48), with a sorted bulk
                       region + small overflow dict merged in batches, and
                       a fully vectorised .idx bulk loader (the 100M-needle
                       scale path; perf pinned by tests/test_needle_map_perf
                       the way compact_map_perf_test.go does).
  * SqliteNeedleMap  — disk-backed (kind "sqlite"): the leveldb-variant
                       analogue for RAM-constrained servers; the .idx
                       remains the durable log, the DB is the lookup
                       structure, rebuilt from .idx when stale
                       (needle_map_leveldb.go semantics).

All kinds share the same bookkeeping the reference's mapMetric maintains
(cumulative file/deleted counts and byte totals, max key), an append-log
.idx writer, and ascending iteration for .ecx generation (memdb.go
AscendingVisit).
"""

from __future__ import annotations

import io
import os
import sqlite3
from typing import Callable, Iterator, Optional

import numpy as np

from . import idx as idx_mod
from . import types as t

_IDX_DTYPE = np.dtype([("key", ">u8"), ("off", ">u4"), ("size", ">i4")])

# prefork gateways: the parent process serves all writes while forked
# workers serve reads from their fork-time map snapshot.  Flushing every
# idx append lets workers tail the file (refresh_from_idx) to pick up
# needles written after the fork without any IPC.
FLUSH_APPENDS = False


class NeedleValue:
    __slots__ = ("offset", "size")

    def __init__(self, offset: int, size: int):
        self.offset = offset  # actual byte offset
        self.size = size

    def __repr__(self):
        return f"NeedleValue(offset={self.offset}, size={self.size})"


class BaseNeedleMap:
    """Shared statistics bookkeeping + .idx append log."""

    def __init__(self, index_path: Optional[str] = None):
        self.file_count = 0
        self.deleted_count = 0
        self.deleted_bytes = 0
        self.content_bytes = 0
        self.max_key = 0
        self._index_file: Optional[io.BufferedWriter] = None
        self.index_path = index_path
        self._idx_tail = 0  # bytes of the .idx this map has consumed
        if index_path is not None:
            if os.path.exists(index_path):
                self._load_from_idx(index_path)
                self._idx_tail = os.path.getsize(index_path)
            self._index_file = open(index_path, "ab")

    # kind-specific storage hooks -------------------------------------------
    def _get(self, nid: int) -> Optional[tuple[int, int]]:
        """-> (actual_offset, size) or None; negative size = deleted."""
        raise NotImplementedError

    def _set(self, nid: int, offset: int, size: int):
        raise NotImplementedError

    def _mark_deleted(self, nid: int):
        """Negate the stored size in place, keeping the offset."""
        raise NotImplementedError

    def _visit_ascending(self) -> Iterator[tuple[int, int, int]]:
        """Yield (nid, actual_offset, size) in ascending id order."""
        raise NotImplementedError

    def __len__(self) -> int:
        raise NotImplementedError

    # -- load ---------------------------------------------------------------
    def _load_from_idx(self, path: str):
        idx_mod.walk_index_file(path, self._apply)

    def _apply(self, nid: int, offset: int, size: int):
        """Replay one idx entry (needle_map_memory.go doLoading semantics):
        a zero offset or tombstone size marks a deletion; deletions keep the
        entry with negated size so reads distinguish deleted from absent
        (compact_map.go Delete; volume_read.go:27-35)."""
        self.max_key = max(self.max_key, nid)
        if offset > 0 and size != t.TOMBSTONE_FILE_SIZE:
            prev = self._get(nid)
            if prev is not None and prev[1] > 0:
                self.deleted_count += 1
                self.deleted_bytes += prev[1]
            self._set(nid, offset, size)
            self.file_count += 1
            self.content_bytes += size
        else:
            prev = self._get(nid)
            if prev is not None and prev[1] > 0:
                self.deleted_count += 1
                self.deleted_bytes += prev[1]
                self._mark_deleted(nid)

    # -- mutate -------------------------------------------------------------
    def put(self, nid: int, offset: int, size: int):
        self._apply(nid, offset, size)
        self._append_idx(nid, offset, size)

    def delete(self, nid: int, offset: int):
        """Record a tombstone; offset is where the tombstone needle landed."""
        self._apply(nid, 0, t.TOMBSTONE_FILE_SIZE)
        self._append_idx(nid, offset, t.TOMBSTONE_FILE_SIZE)

    def set_in_memory(self, nid: int, offset: int, size: int):
        """Update the map without touching the idx log (for rebuilds)."""
        self._apply(nid, offset, size)

    def _append_idx(self, nid: int, offset: int, size: int):
        if self._index_file is not None:
            self._index_file.write(idx_mod.pack_entry(nid, offset, size))
            self._idx_tail += t.NEEDLE_MAP_ENTRY_SIZE
            if FLUSH_APPENDS:
                self._index_file.flush()

    def refresh_from_idx(self) -> int:
        """Replay entries another process appended to the .idx since this
        map last read it (prefork workers tailing the parent's writes).
        Returns the number of entries applied.  Only valid for maps that
        are not appending concurrently themselves — the prefork design
        guarantees that by forwarding all writes to the parent."""
        if self.index_path is None or not os.path.exists(self.index_path):
            return 0
        size = os.path.getsize(self.index_path)
        size -= size % t.NEEDLE_MAP_ENTRY_SIZE
        if size <= self._idx_tail:
            return 0
        applied = 0
        with open(self.index_path, "rb") as f:
            f.seek(self._idx_tail)
            while self._idx_tail + t.NEEDLE_MAP_ENTRY_SIZE <= size:
                entry = f.read(t.NEEDLE_MAP_ENTRY_SIZE)
                if len(entry) < t.NEEDLE_MAP_ENTRY_SIZE:
                    break
                nid, off, sz = idx_mod.unpack_entry(entry)
                self._apply(nid, off, sz)
                self._idx_tail += t.NEEDLE_MAP_ENTRY_SIZE
                applied += 1
        return applied

    # -- query --------------------------------------------------------------
    def get(self, nid: int) -> Optional[NeedleValue]:
        got = self._get(nid)
        return None if got is None else NeedleValue(got[0], got[1])

    def __contains__(self, nid: int) -> bool:
        return self._get(nid) is not None

    def ascending_visit(self, fn: Callable[[int, NeedleValue], None]):
        """Visit entries in ascending id order (memdb.go:100-123) — the
        ordering contract .ecx files depend on."""
        for nid, offset, size in self._visit_ascending():
            fn(nid, NeedleValue(offset, size))

    def items_ascending(self) -> Iterator[tuple[int, NeedleValue]]:
        for nid, offset, size in self._visit_ascending():
            yield nid, NeedleValue(offset, size)

    # -- stats (needle_map.go mapMetric interface) ---------------------------
    def content_size(self) -> int:
        return self.content_bytes

    def deleted_size(self) -> int:
        return self.deleted_bytes

    def max_file_key(self) -> int:
        return self.max_key

    # -- lifecycle ----------------------------------------------------------
    def flush(self):
        if self._index_file is not None:
            self._index_file.flush()

    def sync(self):
        """Durably flush the .idx append log (fsync write path)."""
        if self._index_file is not None:
            self._index_file.flush()
            os.fsync(self._index_file.fileno())

    def close(self):
        if self._index_file is not None:
            self._index_file.flush()
            os.fsync(self._index_file.fileno())
            self._index_file.close()
            self._index_file = None


class NeedleMap(BaseNeedleMap):
    """dict-backed map (kind "memory")."""

    def __init__(self, index_path: Optional[str] = None):
        self._m: dict[int, NeedleValue] = {}
        super().__init__(index_path)

    def _get(self, nid):
        nv = self._m.get(nid)
        return None if nv is None else (nv.offset, nv.size)

    def _set(self, nid, offset, size):
        self._m[nid] = NeedleValue(offset, size)

    def _mark_deleted(self, nid):
        nv = self._m[nid]
        nv.size = -nv.size

    def _visit_ascending(self):
        for nid in sorted(self._m):
            nv = self._m[nid]
            yield nid, nv.offset, nv.size

    def __len__(self):
        return len(self._m)


class CompactNeedleMap(BaseNeedleMap):
    """numpy struct-of-arrays map (kind "compact"): 16 bytes/entry.

    Layout mirrors the on-disk idx entry: u64 key + u32 stored offset (÷8,
    the reference's Offset type, offset.go:24) + i32 size.  Lookups are a
    binary search over the sorted bulk region (np.searchsorted), new keys
    land in a small overflow dict merged in batches — the same
    sorted-arrays-plus-overflow shape as the reference's CompactMap
    (compact_map.go:10-48, 194-263) without per-section Python objects.
    """

    _MERGE_MIN = 4096

    def __init__(self, index_path: Optional[str] = None):
        self._keys = np.empty(0, dtype=np.uint64)
        self._offs = np.empty(0, dtype=np.uint32)   # stored form (÷8)
        self._sizes = np.empty(0, dtype=np.int32)
        self._overflow: dict[int, tuple[int, int]] = {}  # nid -> (stored, sz)
        super().__init__(index_path)

    # -- bulk load ----------------------------------------------------------
    def _load_from_idx(self, path: str):
        """Vectorised replay of the whole .idx — no per-entry Python loop.

        Resolves last-writer-wins per key, delete-negates-size semantics,
        and the cumulative mapMetric counters in O(n) numpy passes.
        """
        raw = np.fromfile(path, dtype=_IDX_DTYPE)
        if raw.size == 0:
            return
        keys = raw["key"].astype(np.uint64)
        offs = raw["off"].astype(np.uint32)
        sizes = raw["size"].astype(np.int64)
        puts = (offs > 0) & (sizes != t.TOMBSTONE_FILE_SIZE)

        uniq, inv = np.unique(keys, return_inverse=True)
        n = uniq.size
        order = np.arange(raw.size, dtype=np.int64)
        last_put = np.full(n, -1, dtype=np.int64)
        np.maximum.at(last_put, inv[puts], order[puts])
        last_del = np.full(n, -1, dtype=np.int64)
        np.maximum.at(last_del, inv[~puts], order[~puts])

        valid = last_put >= 0
        deleted = valid & (last_del > last_put)
        lp = last_put[valid]
        final_off = offs[lp]
        final_size = sizes[lp].astype(np.int32)
        final_size = np.where(deleted[valid], -final_size, final_size)

        # cumulative metrics (mapMetric semantics: every put counts toward
        # file_count/content_bytes; a put only counts as *deleted* when a
        # later put/delete supersedes it while live with size > 0 — the
        # sequential _apply guards on prev.size > 0, so size-0 puts never
        # increment the deleted counters)
        pos_puts = puts & (sizes > 0)
        pos_per_key = np.zeros(n, dtype=np.int64)
        np.add.at(pos_per_key, inv[pos_puts], 1)
        pos_size_sums = np.zeros(n, dtype=np.int64)
        np.add.at(pos_size_sums, inv[pos_puts], sizes[pos_puts])
        last_sizes = sizes[lp]
        last_pos = last_sizes > 0
        self.file_count += int(puts.sum())
        self.content_bytes += int(sizes[puts].sum())
        superseded = pos_per_key[valid] - last_pos.astype(np.int64)
        trailing = deleted[valid] & last_pos
        self.deleted_count += int(superseded.sum() + trailing.sum())
        self.deleted_bytes += int(
            (pos_size_sums[valid] - last_sizes * last_pos).sum()
            + last_sizes[trailing].sum())
        self.max_key = max(self.max_key, int(keys.max()))

        self._keys = uniq[valid]
        self._offs = final_off
        self._sizes = final_size

    # -- storage hooks ------------------------------------------------------
    def _find_sorted(self, nid: int) -> int:
        i = int(np.searchsorted(self._keys, np.uint64(nid)))
        if i < self._keys.size and int(self._keys[i]) == nid:
            return i
        return -1

    def _get(self, nid):
        got = self._overflow.get(nid)
        if got is not None:
            return t.from_stored_offset(got[0]), got[1]
        i = self._find_sorted(nid)
        if i < 0:
            return None
        return t.from_stored_offset(int(self._offs[i])), int(self._sizes[i])

    def _set(self, nid, offset, size):
        stored = t.to_stored_offset(offset)
        i = self._find_sorted(nid)
        if i >= 0 and nid not in self._overflow:
            self._offs[i] = stored
            self._sizes[i] = size
        else:
            self._overflow[nid] = (stored, size)
            self._maybe_merge()

    def _mark_deleted(self, nid):
        got = self._overflow.get(nid)
        if got is not None:
            self._overflow[nid] = (got[0], -got[1])
            return
        i = self._find_sorted(nid)
        if i >= 0:
            self._sizes[i] = -self._sizes[i]

    def _maybe_merge(self, force: bool = False):
        if not self._overflow:
            return
        if not force and len(self._overflow) < max(self._MERGE_MIN,
                                                   self._keys.size // 8):
            return
        ov_keys = np.fromiter(self._overflow.keys(), dtype=np.uint64,
                              count=len(self._overflow))
        ov_vals = np.array(list(self._overflow.values()), dtype=np.int64)
        order = np.argsort(ov_keys)
        ov_keys = ov_keys[order]
        ov_offs = ov_vals[order, 0].astype(np.uint32)
        ov_sizes = ov_vals[order, 1].astype(np.int32)
        # overflow keys are disjoint from the sorted region by construction
        keys = np.concatenate([self._keys, ov_keys])
        offs = np.concatenate([self._offs, ov_offs])
        sizes = np.concatenate([self._sizes, ov_sizes])
        order = np.argsort(keys, kind="stable")
        self._keys = keys[order]
        self._offs = offs[order]
        self._sizes = sizes[order]
        self._overflow.clear()

    def _visit_ascending(self):
        self._maybe_merge(force=True)
        for i in range(self._keys.size):
            yield (int(self._keys[i]),
                   t.from_stored_offset(int(self._offs[i])),
                   int(self._sizes[i]))

    def __len__(self):
        return int(self._keys.size) + len(self._overflow)

    def bytes_per_entry(self) -> float:
        n = len(self)
        if n == 0:
            return 0.0
        core = (self._keys.nbytes + self._offs.nbytes + self._sizes.nbytes)
        return core / max(1, self._keys.size)


class SqliteNeedleMap(BaseNeedleMap):
    """sqlite-backed map (kind "sqlite") for RAM-constrained servers.

    The .idx append log stays authoritative; the DB (at index_path +
    ".sqlite") is a lookup structure rebuilt from the .idx whenever its
    recorded idx size is stale — needle_map_leveldb.go's recovery story.
    Cumulative metrics persist in a meta table on flush/close; after a
    crash they are re-derived from live rows (same degradation as the
    reference's metric recomputation).
    """

    def __init__(self, index_path: Optional[str] = None,
                 db_path: Optional[str] = None):
        if db_path is None:
            db_path = (index_path + ".sqlite") if index_path else ":memory:"
        # volume-server handlers run on per-connection threads; access is
        # serialised by Volume.lock, so cross-thread use is safe
        self._db = sqlite3.connect(db_path, check_same_thread=False)
        self._db.execute("PRAGMA journal_mode=WAL")
        self._db.execute("PRAGMA synchronous=NORMAL")
        self._db.execute(
            "CREATE TABLE IF NOT EXISTS needles ("
            "key INTEGER PRIMARY KEY, off INTEGER, size INTEGER)")
        self._db.execute(
            "CREATE TABLE IF NOT EXISTS meta (k TEXT PRIMARY KEY, v INTEGER)")
        self._pending = 0
        super().__init__(index_path)

    def _meta(self, k: str) -> Optional[int]:
        row = self._db.execute("SELECT v FROM meta WHERE k=?", (k,)).fetchone()
        return None if row is None else int(row[0])

    def _set_meta(self, k: str, v: int):
        self._db.execute(
            "INSERT INTO meta(k, v) VALUES(?, ?) "
            "ON CONFLICT(k) DO UPDATE SET v=excluded.v", (k, v))

    def _load_from_idx(self, path: str):
        idx_size = os.path.getsize(path)
        if self._meta("idx_size") == idx_size:
            # DB is current: restore metrics, skip the replay
            for attr in ("file_count", "deleted_count", "deleted_bytes",
                         "content_bytes", "max_key"):
                v = self._meta(attr)
                if v is not None:
                    setattr(self, attr, v)
            return
        self._db.execute("DELETE FROM needles")
        super()._load_from_idx(path)
        self._persist_meta(idx_size)

    def _persist_meta(self, idx_size: Optional[int] = None):
        if idx_size is None and self.index_path:
            if self._index_file is not None:
                self._index_file.flush()
            idx_size = (os.path.getsize(self.index_path)
                        if os.path.exists(self.index_path) else 0)
        self._set_meta("idx_size", idx_size or 0)
        for attr in ("file_count", "deleted_count", "deleted_bytes",
                     "content_bytes", "max_key"):
            self._set_meta(attr, getattr(self, attr))
        self._db.commit()

    @staticmethod
    def _sql_key(nid: int) -> int:
        # sqlite INTEGER is signed 64-bit; wrap u64 keys into its range
        return nid - (1 << 64) if nid >= (1 << 63) else nid

    @staticmethod
    def _from_sql_key(k: int) -> int:
        return k + (1 << 64) if k < 0 else k

    def _get(self, nid):
        row = self._db.execute(
            "SELECT off, size FROM needles WHERE key=?",
            (self._sql_key(nid),)).fetchone()
        if row is None:
            return None
        return t.from_stored_offset(int(row[0])), int(row[1])

    def _set(self, nid, offset, size):
        self._db.execute(
            "INSERT INTO needles(key, off, size) VALUES(?, ?, ?) "
            "ON CONFLICT(key) DO UPDATE SET off=excluded.off, "
            "size=excluded.size",
            (self._sql_key(nid), t.to_stored_offset(offset), size))
        self._bump()

    def _mark_deleted(self, nid):
        self._db.execute("UPDATE needles SET size=-size WHERE key=?",
                         (self._sql_key(nid),))
        self._bump()

    def _bump(self):
        self._pending += 1
        if self._pending >= 1024:
            self._db.commit()
            self._pending = 0

    def _visit_ascending(self):
        # two passes ordered by the unsigned key value (negative sql keys
        # are the u64 upper half)
        for clause in ("key >= 0", "key < 0"):
            cur = self._db.execute(
                f"SELECT key, off, size FROM needles WHERE {clause} "
                "ORDER BY key")
            for k, off, size in cur:
                yield (self._from_sql_key(int(k)),
                       t.from_stored_offset(int(off)), int(size))

    def __len__(self):
        return int(self._db.execute(
            "SELECT COUNT(*) FROM needles").fetchone()[0])

    def flush(self):
        super().flush()
        self._persist_meta()

    def close(self):
        super().close()
        self._persist_meta(
            os.path.getsize(self.index_path)
            if self.index_path and os.path.exists(self.index_path) else 0)
        self._db.close()


_KINDS = {
    "memory": NeedleMap,
    "compact": CompactNeedleMap,
    "sqlite": SqliteNeedleMap,
}


def new_needle_map(kind: str = "memory",
                   index_path: Optional[str] = None) -> BaseNeedleMap:
    """Factory mirroring NeedleMapKind selection (needle_map.go:15-22)."""
    try:
        cls = _KINDS[kind]
    except KeyError:
        raise ValueError(f"unknown needle map kind {kind!r}") from None
    return cls(index_path)


def load_needle_map_from_idx(path: str, kind: str = "memory"
                             ) -> BaseNeedleMap:
    """Read-only map from an existing .idx (no append log) — the shape
    WriteSortedFileFromIdx consumes (ec_encoder.go:27-54, readNeedleMap)."""
    nm = _KINDS[kind]()
    nm._load_from_idx(path)
    return nm
