"""Incremental volume backup/tail: follow another replica's appends.

Parity with weed/storage/volume_backup.go: ``binary_search_by_append_at_ns``
(:171) locates the first .dat offset whose needle was appended after a
timestamp by binary-searching the .idx (append order == timestamp order);
``incremental_backup`` (:66) pulls the delta from a source replica and
replays it locally; the tail side streams raw needle records from that
offset (volume_grpc_tail.go).
"""

from __future__ import annotations

import os
from typing import Callable, Optional

from . import idx as idx_mod
from . import types as t
from .needle import Needle, get_actual_size, read_needle_header
from .volume import Volume, VolumeError


def _append_at_ns_of(v: Volume, offset: int, size: int) -> int:
    """Read a needle's append timestamp straight from the .dat."""
    if size < 0:
        size = 0  # tombstones store no data
    ts_off = (offset + t.NEEDLE_HEADER_SIZE + size + t.NEEDLE_CHECKSUM_SIZE)
    blob = v.data.read_at(t.TIMESTAMP_SIZE, ts_off)
    if len(blob) < t.TIMESTAMP_SIZE:
        raise VolumeError(f"short read at {ts_off}")
    return int.from_bytes(blob, "big")


def binary_search_by_append_at_ns(v: Volume, since_ns: int) -> int:
    """First .dat offset with append_at_ns > since_ns, or the .dat size if
    fully caught up (BinarySearchByAppendAtNs, volume_backup.go:171-222)."""
    if v.nm is not None:
        v.nm.flush()  # the idx appends are buffered; search reads the file
    idx_path = v.file_name(".idx")
    if not os.path.exists(idx_path):
        return v.super_block.block_size
    entry_count = os.path.getsize(idx_path) // t.NEEDLE_MAP_ENTRY_SIZE
    if entry_count == 0:
        return v.super_block.block_size
    with open(idx_path, "rb") as f:
        def entry(i: int) -> tuple[int, int, int]:
            f.seek(i * t.NEEDLE_MAP_ENTRY_SIZE)
            return idx_mod.unpack_entry(f.read(t.NEEDLE_MAP_ENTRY_SIZE))

        lo, hi = 0, entry_count  # invariant: ts(lo-1) <= since < ts(hi)
        while lo < hi:
            mid = (lo + hi) // 2
            _, offset, size = entry(mid)
            if offset == 0:
                # unrecorded deletion entry; skip forward linearly
                lo_scan = mid + 1
                while lo_scan < hi:
                    _, o2, s2 = entry(lo_scan)
                    if o2 != 0:
                        offset, size = o2, s2
                        mid = lo_scan
                        break
                    lo_scan += 1
                else:
                    hi = mid
                    continue
            if _append_at_ns_of(v, offset, size) <= since_ns:
                lo = mid + 1
            else:
                hi = mid
        if lo >= entry_count:
            return v.data.size()
        _, offset, size = entry(lo)
        if offset == 0:
            return v.data.size()
        return offset


def read_appended_bytes(v: Volume, since_ns: int,
                        limit: int = 64 << 20) -> tuple[bytes, int]:
    """-> (raw needle records appended after since_ns, resume cursor).

    The cursor is the append_at_ns of the LAST record actually included —
    a truncated read must not skip the unsent tail — and the blob is cut
    at a whole-record boundary."""
    with v.lock:
        start = binary_search_by_append_at_ns(v, since_ns)
        end = min(v.data.size(), start + limit)
        blob = v.data.read_at(end - start, start)
    # cut at the last complete record and find its timestamp
    version = v.version
    pos = 0
    cursor = since_ns
    while pos + t.NEEDLE_HEADER_SIZE <= len(blob):
        n, _ = read_needle_header(blob[pos:pos + t.NEEDLE_HEADER_SIZE])
        size = max(n.size, 0)  # tombstones carry no data
        actual = get_actual_size(size, version)
        if pos + actual > len(blob):
            break
        ts_off = pos + t.NEEDLE_HEADER_SIZE + size + t.NEEDLE_CHECKSUM_SIZE
        cursor = int.from_bytes(
            blob[ts_off:ts_off + t.TIMESTAMP_SIZE], "big")
        pos += actual
    return blob[:pos], cursor


def iter_appended_bytes(v: Volume, since_ns: int, limit: int = 64 << 20,
                        chunk_size: int = 4 << 20):
    """Streaming read_appended_bytes: -> (chunk iterator, length, cursor).

    The record boundary and resume cursor are found by a header-only walk
    (pread of each needle header, skipping the data), so the server never
    buffers the payload; chunks are then read lazily.

    The walk and chunk reads go through a dedicated fd opened on the .dat
    PATH while the volume lock is held: a vacuum commit that os.replace()s
    the .dat mid-stream leaves this fd on the old inode, so the stream
    stays internally consistent instead of serving bytes from the new,
    differently-laid-out file.  Non-file backends (tiered volumes) fall
    back to one locked buffered read."""
    dat_path = v.file_name(".dat")
    with v.lock:
        if not os.path.exists(dat_path):
            blob, cursor = read_appended_bytes(v, since_ns, limit)
            return iter([blob]), len(blob), cursor
        f = open(dat_path, "rb")
        start = binary_search_by_append_at_ns(v, since_ns)
        end = min(v.data.size(), start + limit)
    version = v.version
    fd = f.fileno()
    pos = start
    cursor = since_ns
    while pos + t.NEEDLE_HEADER_SIZE <= end:
        header = os.pread(fd, t.NEEDLE_HEADER_SIZE, pos)
        if len(header) < t.NEEDLE_HEADER_SIZE:
            break
        n, _ = read_needle_header(header)
        size = max(n.size, 0)  # tombstones carry no data
        actual = get_actual_size(size, version)
        if pos + actual > end:
            break
        ts_off = pos + t.NEEDLE_HEADER_SIZE + size + t.NEEDLE_CHECKSUM_SIZE
        ts = os.pread(fd, t.TIMESTAMP_SIZE, ts_off)
        cursor = int.from_bytes(ts, "big")
        pos += actual
    length = pos - start

    def gen():
        try:
            at = start
            left = length
            while left > 0:
                chunk = os.pread(fd, min(chunk_size, left), at)
                if not chunk:
                    return
                at += len(chunk)
                left -= len(chunk)
                yield chunk
        finally:
            f.close()

    return gen(), length, cursor


def replay_appended_bytes(v: Volume, blob: bytes) -> int:
    """Append raw needle records fetched from a replica, updating the
    index (tombstones delete).  Returns the number of records applied."""
    applied = 0
    pos = 0
    version = v.version
    with v.lock:
        while pos + t.NEEDLE_HEADER_SIZE <= len(blob):
            n, _ = read_needle_header(blob[pos:pos + t.NEEDLE_HEADER_SIZE])
            actual = get_actual_size(n.size, version)
            record = blob[pos:pos + actual]
            if len(record) < actual:
                break
            full = Needle()
            full.read_bytes(record, 0, n.size, version)
            offset = v.data.append(record)
            if full.size > 0 or full.data:
                v.nm.put(full.id, offset, n.size)
            else:
                # zero-size append records a deletion
                v.nm.delete(full.id, offset)
            if full.append_at_ns > v.last_append_at_ns:
                v.last_append_at_ns = full.append_at_ns
            applied += 1
            pos += actual
    return applied


def incremental_backup(dst: Volume,
                       fetch: Callable[[int], bytes],
                       max_rounds: int = 1024) -> int:
    """Pull appended records from a source replica until caught up.
    ``fetch(since_ns)`` returns raw bytes after that timestamp (empty when
    caught up).  Mirrors IncrementalBackup (volume_backup.go:66-131)."""
    total = 0
    for _ in range(max_rounds):
        blob = fetch(dst.last_append_at_ns)
        if not blob:
            break
        applied = replay_appended_bytes(dst, blob)
        if applied == 0:
            break
        total += applied
    return total
