"""Volume superblock (first 8 bytes of every .dat) and replica placement.

Byte layout (weed/storage/super_block/super_block.go:16-30):
  0: version | 1: replica placement | 2-3: TTL | 4-5: compaction revision |
  6-7: extra size (reserved; extra bytes follow when nonzero).

Replica placement "xyz" = DiffDataCenter/DiffRack/SameRack counts
(replica_placement.go:8-56).
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field

from .needle import CURRENT_VERSION
from .ttl import EMPTY_TTL, TTL

SUPER_BLOCK_SIZE = 8


class SuperBlockError(Exception):
    pass


@dataclass(frozen=True)
class ReplicaPlacement:
    same_rack: int = 0
    diff_rack: int = 0
    diff_dc: int = 0

    @classmethod
    def parse(cls, s: str) -> "ReplicaPlacement":
        s = (s or "000").rjust(3, "0")
        vals = []
        for ch in s:
            v = int(ch)
            if not 0 <= v <= 2:
                raise ValueError(f"unknown replication type {s!r}")
            vals.append(v)
        return cls(diff_dc=vals[0], diff_rack=vals[1], same_rack=vals[2])

    @classmethod
    def from_byte(cls, b: int) -> "ReplicaPlacement":
        return cls.parse(f"{b:03d}")

    def to_byte(self) -> int:
        return self.diff_dc * 100 + self.diff_rack * 10 + self.same_rack

    def copy_count(self) -> int:
        return self.diff_dc + self.diff_rack + self.same_rack + 1

    def __str__(self) -> str:
        return f"{self.diff_dc}{self.diff_rack}{self.same_rack}"


@dataclass
class SuperBlock:
    version: int = CURRENT_VERSION
    replica_placement: ReplicaPlacement = field(default_factory=ReplicaPlacement)
    ttl: TTL = EMPTY_TTL
    compaction_revision: int = 0
    extra: bytes = b""

    def to_bytes(self) -> bytes:
        header = bytearray(SUPER_BLOCK_SIZE)
        header[0] = self.version
        header[1] = self.replica_placement.to_byte()
        header[2:4] = self.ttl.to_bytes()
        struct.pack_into(">H", header, 4, self.compaction_revision)
        if self.extra:
            if len(self.extra) > 256 * 256 - 2:
                raise SuperBlockError("super block extra too large")
            struct.pack_into(">H", header, 6, len(self.extra))
            return bytes(header) + self.extra
        return bytes(header)

    @property
    def block_size(self) -> int:
        return SUPER_BLOCK_SIZE + len(self.extra)

    @classmethod
    def from_file(cls, f) -> "SuperBlock":
        """Read from an open binary file positioned anywhere
        (super_block_read.go ReadSuperBlock)."""
        f.seek(0)
        header = f.read(SUPER_BLOCK_SIZE)
        if len(header) != SUPER_BLOCK_SIZE:
            raise SuperBlockError(
                f"cannot read volume super block: got {len(header)} bytes")
        version = header[0]
        if version not in (1, 2, 3):
            raise SuperBlockError(f"unsupported volume version {version}")
        sb = cls(
            version=version,
            replica_placement=ReplicaPlacement.from_byte(header[1]),
            ttl=TTL.from_bytes(header[2:4]),
            compaction_revision=struct.unpack(">H", header[4:6])[0],
        )
        extra_size = struct.unpack(">H", header[6:8])[0]
        if extra_size:
            sb.extra = f.read(extra_size)
            if len(sb.extra) != extra_size:
                raise SuperBlockError("cannot read super block extra")
        return sb
