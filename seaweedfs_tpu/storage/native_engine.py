"""ctypes bindings for the native volume engine (native/vol_native.cpp).

The engine owns the hot data plane of a volume: the needle index, the
.dat append path with its .idx entry log, and a framed-TCP server that
answers read/write/delete requests entirely off the GIL (the reference's
equivalent surface is compiled Go: weed/storage/needle_map,
volume_write.go, and the volume server's handler goroutines).

Python and C++ share one index and one append mutex per volume, so
requests served natively and requests served by the Python HTTP handlers
always see each other's writes.  `NativeNeedleMap` plugs the engine into
`Volume` behind the same interface as the pure-Python map kinds
(needle_map.py BaseNeedleMap).

Set SW_NATIVE=0 to disable the engine even when the library builds.
"""

from __future__ import annotations

import ctypes
import functools
import os
import subprocess
import threading
from typing import Callable, Iterator, Optional

import numpy as np

from . import types as t

_NATIVE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "native",
)
_LIB_PATH = os.path.join(_NATIVE_DIR, "libseaweedvol.so")

_i64 = ctypes.c_int64
_u64 = ctypes.c_uint64
_u32 = ctypes.c_uint32


@functools.lru_cache(maxsize=1)
def lib() -> Optional[ctypes.CDLL]:
    if os.environ.get("SW_NATIVE", "1") == "0":
        return None
    try:
        subprocess.run(["make", "-s"], cwd=_NATIVE_DIR, check=True,
                       capture_output=True, timeout=180)
    except Exception:
        if not os.path.exists(_LIB_PATH):
            return None
    try:
        cdll = ctypes.CDLL(_LIB_PATH)
    except OSError:
        return None
    cdll.svn_register.restype = _i64
    cdll.svn_register.argtypes = [ctypes.c_char_p, ctypes.c_char_p,
                                  ctypes.c_int, ctypes.c_int, ctypes.c_int,
                                  ctypes.c_int]
    cdll.svn_unregister.argtypes = [_i64]
    cdll.svn_set_flags.argtypes = [_i64, ctypes.c_int, ctypes.c_int]
    cdll.svn_serve.argtypes = [_u32, _i64]
    cdll.svn_nm_put.argtypes = [_i64, _u64, _u64, _i64]
    cdll.svn_nm_put_if_newer.argtypes = [_i64, _u64, _u64, _i64]
    cdll.svn_nm_delete.argtypes = [_i64, _u64, _u64]
    cdll.svn_nm_set_memory.argtypes = [_i64, _u64, _u64, _i64]
    cdll.svn_nm_get.argtypes = [_i64, _u64, ctypes.POINTER(_u64),
                                ctypes.POINTER(_i64)]
    cdll.svn_nm_stats.argtypes = [_i64, ctypes.POINTER(_i64)]
    cdll.svn_nm_visit.restype = _i64
    cdll.svn_nm_visit.argtypes = [_i64, ctypes.POINTER(_i64), _i64]
    cdll.svn_append.restype = _i64
    cdll.svn_append.argtypes = [_i64, ctypes.c_char_p, _i64]
    cdll.svn_size.restype = _i64
    cdll.svn_size.argtypes = [_i64]
    cdll.svn_sync.argtypes = [_i64]
    cdll.svn_touch.argtypes = [_i64, _u64, _i64]
    cdll.svn_quiesce.argtypes = [_i64]
    cdll.svn_last_modified.restype = _i64
    cdll.svn_last_modified.argtypes = [_i64]
    cdll.svn_ec_register.restype = _i64
    cdll.svn_ec_register.argtypes = [ctypes.c_char_p, ctypes.c_int, _i64,
                                     _i64]
    cdll.svn_ec_add_shard.argtypes = [_i64, ctypes.c_int, ctypes.c_char_p]
    cdll.svn_ec_remove_shard.argtypes = [_i64, ctypes.c_int]
    cdll.svn_ec_set_recovery.argtypes = [_i64, ctypes.c_int,
                                         ctypes.c_char_p, ctypes.c_char_p,
                                         ctypes.c_int]
    cdll.svn_ec_serve.argtypes = [_u32, _i64]
    cdll.svn_ec_unregister.argtypes = [_i64]
    cdll.svn_ec_refresh.argtypes = [_i64]
    cdll.svn_set_ttl.argtypes = [_i64, _i64, _u32]
    cdll.svn_set_replication.argtypes = [_i64, ctypes.c_int]
    cdll.svn_set_replicas.argtypes = [_u32, ctypes.c_char_p]
    cdll.svn_server_set_jwt.argtypes = [ctypes.c_char_p, ctypes.c_char_p,
                                        ctypes.c_int]
    cdll.svn_server_start.restype = ctypes.c_int
    cdll.svn_server_start.argtypes = [ctypes.c_char_p, ctypes.c_int]
    cdll.svn_server_set_redirect.argtypes = [ctypes.c_char_p]
    cdll.svn_server_port.restype = ctypes.c_int
    cdll.svn_assign_add_lease.argtypes = [_u32, ctypes.c_char_p,
                                          ctypes.c_char_p, _u64, _u64]
    cdll.svn_assign_remaining.restype = _i64
    cdll.svn_assign_remaining.argtypes = [_i64]
    cdll.svn_assign_clear.argtypes = []
    cdll.svn_server_stop.restype = ctypes.c_int
    cdll.svn_server_stats.argtypes = [ctypes.POINTER(_i64)]
    cdll.svn_bench.restype = ctypes.c_double
    cdll.svn_bench.argtypes = [ctypes.c_char_p, ctypes.c_int, ctypes.c_int,
                               ctypes.c_char_p, _i64, _i64, ctypes.c_int,
                               ctypes.c_int, ctypes.POINTER(ctypes.c_float),
                               ctypes.POINTER(_i64)]
    return cdll


def available() -> bool:
    return lib() is not None


class NeedleValue:
    __slots__ = ("offset", "size")

    def __init__(self, offset: int, size: int):
        self.offset = offset
        self.size = size


class NativeNeedleMap:
    """BaseNeedleMap-compatible map whose storage, counters and .idx
    append log live in the native engine (one source of truth shared with
    the native TCP server)."""

    kind = "native"

    def __init__(self, dat_path: str, idx_path: str, version: int,
                 writable: bool, read_only: bool, fsync: bool,
                 ttl_sec: int = 0, extra_copies: int = 0,
                 ttl_raw: int = 0):
        self._lib = lib()
        if self._lib is None:
            raise RuntimeError("native engine unavailable")
        self.index_path = idx_path
        h = self._lib.svn_register(dat_path.encode(), idx_path.encode(),
                                   version, int(writable), int(read_only),
                                   int(fsync))
        if h <= 0:
            raise OSError(-h, f"svn_register({dat_path!r}) failed")
        self.handle = h
        if ttl_sec:
            # ttl_raw = the volume TTL's (count<<8)|unit form: native
            # writes stamp FlagHasTtl + these 2 bytes into each needle
            self._lib.svn_set_ttl(h, int(ttl_sec), int(ttl_raw))
        if extra_copies:
            self._lib.svn_set_replication(h, int(extra_copies))

    # -- mutate --------------------------------------------------------------
    def put(self, nid: int, offset: int, size: int):
        self._lib.svn_nm_put(self.handle, nid, offset, size)

    def put_if_newer(self, nid: int, offset: int, size: int) -> bool:
        """Atomic form of the write path's "newer offset wins" guard
        (volume_write.go:160-165): evaluated under the engine's map lock
        so a racing native-port write cannot be clobbered.  Raises
        OSError when the .idx append failed (ENOSPC/EIO) — the write
        must fail before it is acknowledged, not vanish on restart."""
        rc = self._lib.svn_nm_put_if_newer(self.handle, nid, offset, size)
        if rc < 0:
            raise OSError(-rc, "idx append failed")
        return rc == 1

    def delete(self, nid: int, offset: int):
        rc = self._lib.svn_nm_delete(self.handle, nid, offset)
        if rc < 0:
            raise OSError(-rc, "idx append failed")

    def set_in_memory(self, nid: int, offset: int, size: int):
        self._lib.svn_nm_set_memory(self.handle, nid, offset, size)

    # -- query ---------------------------------------------------------------
    def get(self, nid: int) -> Optional[NeedleValue]:
        off = _u64()
        size = _i64()
        r = self._lib.svn_nm_get(self.handle, nid, ctypes.byref(off),
                                 ctypes.byref(size))
        if r != 1:
            return None
        return NeedleValue(off.value, size.value)

    def __contains__(self, nid: int) -> bool:
        return self.get(nid) is not None

    def _stats(self) -> np.ndarray:
        out = (ctypes.c_int64 * 7)()
        self._lib.svn_nm_stats(self.handle, out)
        return np.ctypeslib.as_array(out).copy()

    @property
    def file_count(self) -> int:
        return int(self._stats()[0])

    @property
    def deleted_count(self) -> int:
        return int(self._stats()[1])

    def content_size(self) -> int:
        return int(self._stats()[2])

    def deleted_size(self) -> int:
        return int(self._stats()[3])

    def max_file_key(self) -> int:
        return int(self._stats()[4])

    def __len__(self) -> int:
        return int(self._stats()[5])

    def last_append_ns(self) -> int:
        return int(self._stats()[6])

    def last_modified(self) -> int:
        return max(0, int(self._lib.svn_last_modified(self.handle)))

    def items_ascending(self) -> Iterator[tuple[int, NeedleValue]]:
        if not self.handle:
            return
        cap = max(len(self), 1)
        while True:
            buf = (ctypes.c_int64 * (cap * 3))()
            n = self._lib.svn_nm_visit(self.handle, buf, cap)
            if n >= 0:
                break
            if n == -(2 ** 63):  # INT64_MIN: handle gone (closed under us)
                return
            cap = -n  # raced a concurrent insert: retry at the new size
        arr = np.ctypeslib.as_array(buf)[: n * 3].reshape(n, 3)
        for nid, off, size in arr:
            yield int(nid), NeedleValue(int(off), int(size))

    def ascending_visit(self, fn: Callable[[int, NeedleValue], None]):
        for nid, nv in self.items_ascending():
            fn(nid, nv)

    # -- append path ---------------------------------------------------------
    def append_dat(self, blob: bytes) -> int:
        """Append a record to the .dat under the engine's shared write
        mutex; returns the landing offset."""
        off = self._lib.svn_append(self.handle, blob, len(blob))
        if off < 0:
            raise OSError(-off, "native append failed")
        return off

    def touch(self, append_ns: int, modified_ts: int):
        self._lib.svn_touch(self.handle, append_ns, modified_ts)

    def set_flags(self, writable: Optional[bool] = None,
                  read_only: Optional[bool] = None):
        self._lib.svn_set_flags(
            self.handle,
            -1 if writable is None else int(writable),
            -1 if read_only is None else int(read_only))

    def quiesce(self):
        """Disable native-path writes and drain any in-flight append."""
        self._lib.svn_quiesce(self.handle)

    # -- durability ----------------------------------------------------------
    def flush(self):
        pass  # idx appends are unbuffered write()s

    def sync(self):
        self._lib.svn_sync(self.handle)

    def close(self):
        if self.handle:
            self._lib.svn_unregister(self.handle)
            self.handle = 0

    def bytes_per_entry(self) -> float:
        return 25.0  # 16B slot + state byte + vector overhead


class NativeEcBinding:
    """Native serving of an EcVolume's local-shard reads: the .ecx and
    every local `.ecNN` open in C++, bound to the vid for the TCP server.
    Reads whose intervals touch a non-local shard answer 307 and fall
    back to the Python ladder (remote fetch / on-the-fly reconstruct)."""

    def __init__(self, ec_volume):
        self._lib = lib()
        if self._lib is None:
            raise RuntimeError("native engine unavailable")
        base = ec_volume.base_file_name()
        h = self._lib.svn_ec_register(
            (base + ".ecx").encode(), ec_volume.version,
            ec_volume.large_block_size, ec_volume.small_block_size)
        if h <= 0:
            raise OSError(-h, f"svn_ec_register({base!r}) failed")
        self.handle = h
        self.shard_ids: frozenset = frozenset()
        self.sync_shards(ec_volume)

    def sync_shards(self, ec_volume):
        current = frozenset(ec_volume.shards)
        for sid in sorted(current - self.shard_ids):
            shard = ec_volume.shards[sid]
            self._lib.svn_ec_add_shard(
                self.handle, sid, shard.file_name().encode())
        for sid in sorted(self.shard_ids - current):
            # unmounted shards must stop serving (and release the fd:
            # ec.balance deletes the file after moving it)
            self._lib.svn_ec_remove_shard(self.handle, sid)
        changed = current != self.shard_ids
        self.shard_ids = current
        if changed:
            # recovery rows depend only on the shard SET; skip the
            # matrix inversions + 14 FFI calls on every unchanged
            # heartbeat resync
            self._sync_recovery(current)
        self._lib.svn_ec_refresh(self.handle)

    def _sync_recovery(self, current: frozenset):
        """Push per-missing-shard reconstruction rows so the engine
        serves DEGRADED reads natively: with >=10 local shards, any
        missing data shard's span is a fixed GF(2^8) combination of the
        survivors' same-offset bytes (rebuild_matrix — the one-matmul
        form of klauspost Reconstruct).  A wrong row cannot serve
        silently: the needle CRC check rejects it."""
        if len(current) >= 10:
            from ..parallel.batched_encode import rebuild_matrix

            present = sorted(current)
            for sid in range(14):
                if sid in current:
                    self._lib.svn_ec_set_recovery(
                        self.handle, sid, b"", b"", 0)
                    continue
                chosen, matrix = rebuild_matrix(present, [sid])
                self._lib.svn_ec_set_recovery(
                    self.handle, sid, bytes(chosen[:10]),
                    bytes(int(c) for c in matrix[0][:10]), 10)
        else:
            for sid in range(14):
                self._lib.svn_ec_set_recovery(self.handle, sid, b"",
                                              b"", 0)

    def close(self):
        if self.handle:
            self._lib.svn_ec_unregister(self.handle)
            self.handle = 0


def serve_ec_volume(vid: int, binding: NativeEcBinding) -> bool:
    cdll = lib()
    if cdll is None:
        return False
    return cdll.svn_ec_serve(vid, binding.handle) == 0


def unserve_ec_volume(vid: int):
    cdll = lib()
    if cdll is not None:
        cdll.svn_ec_serve(vid, 0)


# -- server / serving registry ----------------------------------------------

def serve_volume(vid: int, nm) -> bool:
    """Bind vid -> nm.handle for the native TCP server (0 unbinds)."""
    cdll = lib()
    if cdll is None or not isinstance(nm, NativeNeedleMap):
        return False
    return cdll.svn_serve(vid, nm.handle) == 0


def unserve_volume(vid: int):
    cdll = lib()
    if cdll is not None:
        cdll.svn_serve(vid, 0)


def server_set_redirect(addr: str):
    """Point the native port's HTTP 302 fallback at the full handler
    (the listener may have been started by a daemon that didn't know
    the volume server's address, e.g. the master in a combined
    process)."""
    cdll = lib()
    if cdll is not None:
        cdll.svn_server_set_redirect(addr.encode())


def server_set_jwt(write_key: str | bytes | None = "",
                   read_key: str | bytes | None = "",
                   expire_s: int = 10):
    """Configure HS256 signing keys for the fast-path port (writes
    require fid-scoped tokens; reads too when read_key is set).  The
    'A' assign handler mints matching write tokens.

    The keys are engine-global and shared by every in-process daemon:
    pass None to leave a key untouched, so one owner (e.g. a master
    shutting down) can clear ITS key without clearing the other
    daemon's.  Empty string explicitly disables a key."""
    cdll = lib()
    if cdll is None:
        return

    def enc(k):
        if k is None:
            return None
        return k.encode() if isinstance(k, str) else bytes(k)

    cdll.svn_server_set_jwt(enc(write_key), enc(read_key), int(expire_s))


def set_replicas(vid: int, addrs: list[str]):
    """Publish vid's peer fast-path addresses for native write fan-out
    (empty list clears)."""
    cdll = lib()
    if cdll is not None:
        cdll.svn_set_replicas(vid, ",".join(addrs).encode())


def server_start(host: str, port: int, http_redirect: str = "") -> int:
    """Start the native fast-path server; returns the bound port.
    `http_redirect` is the volume server's full HTTP address — plain
    HTTP requests the native port cannot serve 302 there."""
    cdll = lib()
    if cdll is None:
        raise RuntimeError("native engine unavailable")
    cdll.svn_server_set_redirect(http_redirect.encode())
    bound = cdll.svn_server_start(host.encode(), port)
    if bound < 0:
        raise OSError(-bound, "native server start failed")
    return bound


def server_stop():
    cdll = lib()
    if cdll is not None:
        cdll.svn_server_stop()


def server_port() -> int:
    """Bound port of the process-wide native listener (0 = none)."""
    cdll = lib()
    return cdll.svn_server_port() if cdll is not None else 0


# one volume server per process may own the vid->handle serving registry
# (the listener itself may have been started by the master for assign
# leases in a combined process — serving is a separate claim)
_serving_lock = threading.Lock()
_serving_claimed = False


def claim_serving() -> bool:
    global _serving_claimed
    with _serving_lock:
        if _serving_claimed:
            return False
        _serving_claimed = True
        return True


def release_serving():
    global _serving_claimed
    with _serving_lock:
        _serving_claimed = False


def assign_add_lease(vid: int, url: str, public_url: str,
                     key_start: int, key_end: int) -> bool:
    """Lease [key_start, key_end] (inclusive) of volume vid's key space
    to the native 'A' assign handler."""
    cdll = lib()
    if cdll is None:
        return False
    return cdll.svn_assign_add_lease(
        vid, url.encode(), (public_url or "").encode(),
        key_start, key_end) == 0


def assign_remaining(max_age_ms: int = 0) -> int:
    """Remaining leased keys; prunes exhausted leases and, when
    max_age_ms > 0, leases older than that (per-lease staleness bound)."""
    cdll = lib()
    return (int(cdll.svn_assign_remaining(max_age_ms))
            if cdll is not None else 0)


def assign_clear():
    cdll = lib()
    if cdll is not None:
        cdll.svn_assign_clear()


def server_stats() -> dict:
    """Cumulative native-server request counters (process-wide)."""
    cdll = lib()
    if cdll is None:
        return {}
    out = (ctypes.c_int64 * 7)()
    cdll.svn_server_stats(out)
    keys = ("read", "ec_read", "write", "delete", "http_read",
            "fallback", "error")
    return dict(zip(keys, (int(v) for v in out)))


def bench(host: str, port: int, op: str, fids: list[str], nreqs: int,
          payload_size: int = 0, concurrency: int = 16
          ) -> tuple[float, int, np.ndarray]:
    """Drive the native load generator; returns (seconds, errors,
    latencies_ms ndarray)."""
    cdll = lib()
    if cdll is None:
        raise RuntimeError("native engine unavailable")
    blob = "\n".join(fids).encode()
    lat = (ctypes.c_float * nreqs)()
    errs = _i64()
    seconds = cdll.svn_bench(host.encode(), port, ord(op[0]), blob,
                             len(fids), nreqs, payload_size, concurrency,
                             lat, ctypes.byref(errs))
    lat_ms = np.ctypeslib.as_array(lat).astype(np.float64) / 1000.0
    # request slots never claimed (all workers dead) report latency 0;
    # they are already counted in errs — drop them from the histogram
    lat_ms = lat_ms[lat_ms > 0]
    return seconds, int(errs.value), lat_ms


__all__ = ["lib", "available", "NativeNeedleMap", "serve_volume",
           "unserve_volume", "server_start", "server_stop", "bench"]
