"""Time-to-live encoding: 1 count byte + 1 unit byte on disk.

Wire/disk-compatible with the reference's weed/storage/needle/volume_ttl.go:
units minute/hour/day/week/month/year stored as 1..6, empty as (0, 0).
"""

from __future__ import annotations

from dataclasses import dataclass

EMPTY_BYTES = b"\x00\x00"

_UNITS = {  # stored byte -> (suffix, minutes)
    1: ("m", 1),
    2: ("h", 60),
    3: ("d", 60 * 24),
    4: ("w", 60 * 24 * 7),
    5: ("M", 60 * 24 * 30),
    6: ("y", 60 * 24 * 365),
}
_SUFFIXES = {s: b for b, (s, _) in _UNITS.items()}


@dataclass(frozen=True)
class TTL:
    count: int = 0
    unit: int = 0

    @classmethod
    def parse(cls, s: str) -> "TTL":
        """"3m"/"4h"/"5d"/"6w"/"7M"/"8y"; bare digits mean minutes."""
        if not s:
            return EMPTY_TTL
        unit_ch = s[-1]
        if unit_ch.isdigit():
            return cls(count=int(s), unit=_SUFFIXES["m"])
        if unit_ch not in _SUFFIXES:
            raise ValueError(f"unknown ttl unit {unit_ch!r}")
        return cls(count=int(s[:-1]), unit=_SUFFIXES[unit_ch])

    @classmethod
    def from_bytes(cls, b: bytes) -> "TTL":
        if b[0] == 0 and b[1] == 0:
            return EMPTY_TTL
        return cls(count=b[0], unit=b[1])

    @classmethod
    def from_uint32(cls, v: int) -> "TTL":
        return cls.from_bytes(bytes([(v >> 8) & 0xFF, v & 0xFF]))

    def to_bytes(self) -> bytes:
        return bytes([self.count & 0xFF, self.unit & 0xFF])

    def to_uint32(self) -> int:
        if self.count == 0:
            return 0
        return (self.count << 8) | self.unit

    def minutes(self) -> int:
        if self.unit not in _UNITS:
            return 0
        return self.count * _UNITS[self.unit][1]

    def __str__(self) -> str:
        if self.count == 0 or self.unit not in _UNITS:
            return ""
        return f"{self.count}{_UNITS[self.unit][0]}"

    def __bool__(self) -> bool:
        return self.count != 0 and self.unit in _UNITS


EMPTY_TTL = TTL()
