"""Volume tiering: move a sealed volume's .dat to a remote backend.

Parity with weed/storage/backend/s3_backend + volume_grpc_tier_upload.go /
_download.go and shell volume.tier.{upload,download,move}: the .dat bytes
live on the remote store, the .idx stays local (index lookups stay RAM/
disk-fast), reads issue ranged fetches through a block-cached TieredFile,
and the .vif sidecar records the remote location so a restarted server
re-opens the tier without the .dat present.

Backends are registered process-wide by name (the reference wires them
from master.toml [storage.backend.*]); `register_tier_backend` is called
by the volume server at startup.
"""

from __future__ import annotations

import os
import time
from typing import Optional

from ..remote_storage import (RemoteConf, RemoteLocation,
                              make_remote_client)
from .backend import TieredFile
from .volume_info import RemoteFile, VolumeInfo, load_volume_info, \
    save_volume_info

_BACKENDS: dict[str, RemoteConf] = {}

UPLOAD_CHUNK = 8 << 20


def register_tier_backend(conf: RemoteConf):
    _BACKENDS[conf.name] = conf


def tier_backends() -> dict[str, RemoteConf]:
    return dict(_BACKENDS)


def _client(backend_id: str):
    conf = _BACKENDS.get(backend_id)
    if conf is None:
        raise ValueError(f"tier backend {backend_id!r} not configured")
    return make_remote_client(conf)


def _location(remote: RemoteFile) -> RemoteLocation:
    bucket, _, path = remote.key.partition("/")
    return RemoteLocation(remote.backend_id, bucket, "/" + path)


def open_tiered_dat(vif: VolumeInfo) -> Optional[TieredFile]:
    """Open the remote .dat recorded in a .vif (volume load path)."""
    if not vif.files:
        return None
    remote = vif.files[0]
    client = _client(remote.backend_id)
    loc = _location(remote)
    return TieredFile(
        lambda off, size: client.read_range(loc, off, size),
        remote.file_size, name=f"{remote.backend_id}:{remote.key}")


def tier_upload(volume, backend_id: str, bucket: str,
                keep_local: bool = False) -> RemoteFile:
    """Ship the volume's .dat to the tier; volume turns readonly and
    serves reads through ranged fetches (or the kept local copy).

    The lock is held only to seal the volume and for the final cutover —
    the volume is readonly during the transfer, so reads keep flowing
    while the bytes move."""
    conf = _BACKENDS.get(backend_id)
    if conf is None:
        raise ValueError(f"tier backend {backend_id!r} not configured")
    client = make_remote_client(conf)
    with volume.lock:
        existing = load_volume_info(volume.file_name(".vif"))
        if existing is not None and existing.files:
            raise ValueError(f"volume {volume.id} is already tiered "
                             f"to {existing.files[0].backend_id}")
        was_read_only = volume.read_only
        volume.read_only = True  # seal: the .dat can no longer change
        volume.data.sync()
        size = volume.data.size()
        data_file = volume.data
    base = os.path.basename(volume.file_name(".dat"))
    key = f"{bucket}/{base}"
    loc = RemoteLocation(backend_id, bucket, "/" + base)
    try:
        offset = 0

        def read_chunk():
            nonlocal offset
            chunk = data_file.read_at(
                min(UPLOAD_CHUNK, size - offset), offset)
            offset += len(chunk)
            return chunk

        client.write_file_from(loc, read_chunk, size)
    except Exception:
        with volume.lock:
            volume.read_only = was_read_only
        raise
    with volume.lock:
        remote = RemoteFile(
            backend_type=conf.type, backend_id=backend_id, key=key,
            file_size=size, modified_time=int(time.time()),
            extension=".dat")
        vif = VolumeInfo(
            version=volume.version,
            replica_placement=str(volume.super_block.replica_placement),
            ttl=str(volume.ttl),
            compaction_revision=volume.super_block.compaction_revision,
            files=[remote])
        save_volume_info(volume.file_name(".vif"), vif)
        if not keep_local:
            volume.data.close()
            volume.data = TieredFile(
                lambda off, sz: client.read_range(loc, off, sz),
                size, name=f"{backend_id}:{key}")
            os.remove(volume.file_name(".dat"))
        # keep_local: the sealed local .dat keeps serving reads as a cache
        return remote


def tier_download(volume) -> int:
    """Bring the .dat back local; volume becomes writable again."""
    from .backend import DiskFile, TieredFile as _TieredFile

    vif = load_volume_info(volume.file_name(".vif"))
    if vif is None or not vif.files:
        raise ValueError(f"volume {volume.id} has no tiered files")
    remote = vif.files[0]
    client = _client(remote.backend_id)
    loc = _location(remote)
    dat_path = volume.file_name(".dat")
    if not os.path.exists(dat_path):
        # fetch outside the lock: the tiered volume is readonly so the
        # remote object is stable
        tmp = dat_path + ".tierdl"
        with open(tmp, "wb") as f:
            offset = 0
            while offset < remote.file_size:
                chunk = client.read_range(
                    loc, offset,
                    min(UPLOAD_CHUNK, remote.file_size - offset))
                if not chunk:
                    raise OSError(
                        f"short tier read at {offset} from {remote.key}")
                f.write(chunk)
                offset += len(chunk)
        os.replace(tmp, dat_path)
    # else: keep_local cache IS current (volume was sealed readonly)
    with volume.lock:
        if isinstance(volume.data, _TieredFile):
            volume.data.close()
            volume.data = DiskFile(dat_path)
        volume.read_only = False
        vif.files = []
        save_volume_info(volume.file_name(".vif"), vif)
    client.delete_file(loc)
    return remote.file_size
