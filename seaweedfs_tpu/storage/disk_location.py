"""DiskLocation: one storage directory with its volumes and EC shards.

Parity with weed/storage/disk_location.go: volume discovery/loading from
.dat/.idx pairs, EC shard discovery from .ecx + .ecNN files
(disk_location_ec.go), a persisted directory UUID for duplicate-mount
fencing (disk_location.go:40), and free-space accounting.
"""

from __future__ import annotations

import os
import re
import threading
import uuid as uuid_mod
from typing import Optional

from .erasure_coding import TOTAL_SHARDS_COUNT, to_ext
from .erasure_coding.ec_volume import EcVolume, EcVolumeShard
from .volume import Volume

_DAT_RE = re.compile(r"^(?:(?P<collection>.+)_)?(?P<vid>\d+)\.dat$")
_VIF_RE = re.compile(r"^(?:(?P<collection>.+)_)?(?P<vid>\d+)\.vif$")
_SHARD_RE = re.compile(r"^(?:(?P<collection>.+)_)?(?P<vid>\d+)\.ec(?P<shard>\d{2})$")


class DiskLocation:
    def __init__(self, directory: str, max_volume_count: int = 8,
                 min_free_space_ratio: float = 0.0,
                 needle_map_kind: str = "memory", fsync: bool = False):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self.max_volume_count = max_volume_count
        self.min_free_space_ratio = min_free_space_ratio
        self.needle_map_kind = needle_map_kind
        self.fsync = fsync
        self.volumes: dict[int, Volume] = {}
        self.ec_volumes: dict[int, EcVolume] = {}
        self.lock = threading.RLock()
        self.uuid = self._load_or_create_uuid()

    # -- uuid fencing (disk_location.go:40-59) -------------------------------
    def _load_or_create_uuid(self) -> str:
        path = os.path.join(self.directory, "vol_dir.uuid")
        if os.path.exists(path):
            with open(path) as f:
                return f.read().strip()
        new_uuid = str(uuid_mod.uuid4())
        with open(path, "w") as f:
            f.write(new_uuid)
        return new_uuid

    # -- discovery -----------------------------------------------------------
    def load_existing_volumes(self):
        with self.lock:
            for name in sorted(os.listdir(self.directory)):
                m = _DAT_RE.match(name) or _VIF_RE.match(name)
                if m:
                    vid = int(m.group("vid"))
                    collection = m.group("collection") or ""
                    if name.endswith(".vif") and not os.path.exists(
                            os.path.join(self.directory, name[:-4]
                                         + ".dat")):
                        # .vif without .dat: only a tiered volume (one
                        # recording remote files) is loadable; EC
                        # sidecars and stale .vifs are not
                        from .volume_info import load_volume_info

                        base = self._base_name(collection, vid)
                        if os.path.exists(base + ".ecx"):
                            continue
                        vif = load_volume_info(base + ".vif")
                        if vif is None or not vif.files:
                            continue
                    if vid not in self.volumes:
                        try:
                            self.volumes[vid] = Volume(
                                self.directory, collection, vid,
                                needle_map_kind=self.needle_map_kind,
                                fsync=self.fsync)
                        except Exception:
                            continue  # damaged volume: skip, don't crash
            self.load_all_ec_shards()

    def load_all_ec_shards(self):
        """Discover .ecNN files and mount them (disk_location_ec.go)."""
        with self.lock:
            found: dict[tuple[str, int], list[int]] = {}
            for name in sorted(os.listdir(self.directory)):
                m = _SHARD_RE.match(name)
                if m:
                    key = (m.group("collection") or "", int(m.group("vid")))
                    found.setdefault(key, []).append(int(m.group("shard")))
            for (collection, vid), shard_ids in found.items():
                base = self._base_name(collection, vid)
                if not os.path.exists(base + ".ecx"):
                    continue
                if vid in self.volumes:
                    continue  # normal volume takes precedence
                if os.path.exists(base + ".scl"):
                    # inline EC volume: mounting runs the stripe-commit
                    # replay, so a crashed server comes back consistent
                    if vid in self.ec_volumes:
                        continue
                    from .erasure_coding.inline import InlineEcVolume

                    try:
                        self.ec_volumes[vid] = InlineEcVolume(
                            self.directory, collection, vid)
                    except Exception:
                        pass  # damaged volume: skip, don't crash
                    continue
                for shard_id in shard_ids:
                    try:
                        self.mount_ec_shard(collection, vid, shard_id)
                    except Exception:
                        continue

    def _base_name(self, collection: str, vid: int) -> str:
        base = f"{collection}_{vid}" if collection else str(vid)
        return os.path.join(self.directory, base)

    # -- volumes -------------------------------------------------------------
    def add_volume(self, vid: int, collection: str = "",
                   replica_placement=None, ttl=None) -> Volume:
        from .super_block import ReplicaPlacement
        from .ttl import EMPTY_TTL

        with self.lock:
            if vid in self.volumes:
                raise ValueError(f"volume {vid} already exists")
            v = Volume(self.directory, collection, vid,
                       replica_placement=replica_placement
                       or ReplicaPlacement(), ttl=ttl or EMPTY_TTL,
                       needle_map_kind=self.needle_map_kind,
                       fsync=self.fsync)
            self.volumes[vid] = v
            return v

    def add_inline_volume(self, vid: int, collection: str = "",
                          family: str = None):
        """Create an inline EC volume: shard logs are the primary write
        path, no .dat ever exists (storage/erasure_coding/inline.py)."""
        from .erasure_coding.inline import InlineEcVolume

        with self.lock:
            if vid in self.volumes or vid in self.ec_volumes:
                raise ValueError(f"volume {vid} already exists")
            ev = InlineEcVolume(self.directory, collection, vid,
                                family=family, create=True)
            self.ec_volumes[vid] = ev
            return ev

    def delete_volume(self, vid: int):
        with self.lock:
            v = self.volumes.pop(vid, None)
            if v is not None:
                v.destroy()

    def unload_volume(self, vid: int) -> Optional[Volume]:
        with self.lock:
            v = self.volumes.pop(vid, None)
            if v is not None:
                v.close()
            return v

    # -- EC shards -----------------------------------------------------------
    def mount_ec_shard(self, collection: str, vid: int,
                       shard_id: int) -> EcVolumeShard:
        with self.lock:
            ev = self.ec_volumes.get(vid)
            if ev is None:
                ev = EcVolume(self.directory, collection, vid)
                self.ec_volumes[vid] = ev
            shard = EcVolumeShard(self.directory, collection, vid, shard_id)
            if not ev.add_shard(shard):
                shard.close()
                raise ValueError(f"shard {vid}.{shard_id} already mounted")
            return shard

    def unmount_ec_shard(self, vid: int, shard_id: int) -> bool:
        with self.lock:
            ev = self.ec_volumes.get(vid)
            if ev is None:
                return False
            shard = ev.delete_shard(shard_id)
            if shard is not None:
                shard.close()
            if not ev.shards:
                ev.close()
                del self.ec_volumes[vid]
            return shard is not None

    # -- stats ---------------------------------------------------------------
    def volume_count(self) -> int:
        with self.lock:
            return len(self.volumes)

    def ec_shard_count(self) -> int:
        with self.lock:
            return sum(len(ev.shards) for ev in self.ec_volumes.values())

    def free_slots(self) -> int:
        with self.lock:
            used = len(self.volumes) + self.ec_shard_count() / float(
                TOTAL_SHARDS_COUNT)
            return max(0, int(self.max_volume_count - used))

    def close(self):
        with self.lock:
            for v in self.volumes.values():
                v.close()
            for ev in self.ec_volumes.values():
                ev.close()
            self.volumes.clear()
            self.ec_volumes.clear()
