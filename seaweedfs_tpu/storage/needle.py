"""Needle: one stored object, bit-compatible with the reference's on-disk form.

Layouts (weed/storage/needle/needle_write.go:20-113, needle_read.go:98-177):

  v1: header(16) | data | crc32c(4) | zero-pad to 8
  v2: header(16) | dataSize(4) data flags(1) [nameSize name] [mimeSize mime]
      [lastModified(5)] [ttl(2)] [pairsSize(2) pairs] | crc(4) | pad
  v3: v2 body | crc(4) | appendAtNs(8) | pad

  header = cookie(4) id(8) size(4), all big-endian.
  size (v2/v3) = 4 + len(data) + 1 + optional sections; 0 when no data.
  padding = 8 - ((16 + size + 4 [+ 8]) % 8)  — always 1..8 bytes (the
  reference never emits 0 padding; GetActualSize needle_read.go:299).
  CRC is Castagnoli over `data` only; the raw value is stored (the rotated
  legacy CRC.Value() is accepted on read; needle_read.go:73-80).
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field

from ..ops import crc32c as crc32c_mod
from . import types as t
from .ttl import EMPTY_TTL, TTL

VERSION1, VERSION2, VERSION3 = 1, 2, 3
CURRENT_VERSION = VERSION3

FLAG_IS_COMPRESSED = 0x01
FLAG_HAS_NAME = 0x02
FLAG_HAS_MIME = 0x04
FLAG_HAS_LAST_MODIFIED = 0x08
FLAG_HAS_TTL = 0x10
FLAG_HAS_PAIRS = 0x20
FLAG_IS_CHUNK_MANIFEST = 0x80

LAST_MODIFIED_BYTES = 5
TTL_BYTES = 2

PAIR_NAME_PREFIX = "Seaweed-"


class NeedleError(Exception):
    pass


class SizeMismatchError(NeedleError):
    pass


class CrcError(NeedleError):
    pass


def padding_length(needle_size: int, version: int) -> int:
    base = t.NEEDLE_HEADER_SIZE + needle_size + t.NEEDLE_CHECKSUM_SIZE
    if version == VERSION3:
        base += t.TIMESTAMP_SIZE
    return t.NEEDLE_PADDING_SIZE - (base % t.NEEDLE_PADDING_SIZE)


def needle_body_length(needle_size: int, version: int) -> int:
    body = needle_size + t.NEEDLE_CHECKSUM_SIZE + padding_length(needle_size, version)
    if version == VERSION3:
        body += t.TIMESTAMP_SIZE
    return body


def get_actual_size(size: int, version: int) -> int:
    return t.NEEDLE_HEADER_SIZE + needle_body_length(size, version)


@dataclass
class Needle:
    id: int = 0
    cookie: int = 0
    size: int = 0
    data: bytes = b""
    flags: int = 0
    name: bytes = b""
    mime: bytes = b""
    pairs: bytes = b""
    last_modified: int = 0
    ttl: TTL = EMPTY_TTL
    checksum: int = 0
    append_at_ns: int = 0

    # -- flags ---------------------------------------------------------------
    def _flag(self, mask: int) -> bool:
        return bool(self.flags & mask)

    def _set_flag(self, mask: int, on: bool = True):
        self.flags = self.flags | mask if on else self.flags & ~mask

    @property
    def is_compressed(self):
        return self._flag(FLAG_IS_COMPRESSED)

    @property
    def has_name(self):
        return self._flag(FLAG_HAS_NAME)

    @property
    def has_mime(self):
        return self._flag(FLAG_HAS_MIME)

    @property
    def has_last_modified(self):
        return self._flag(FLAG_HAS_LAST_MODIFIED)

    @property
    def has_ttl(self):
        return self._flag(FLAG_HAS_TTL)

    @property
    def has_pairs(self):
        return self._flag(FLAG_HAS_PAIRS)

    @property
    def is_chunk_manifest(self):
        return self._flag(FLAG_IS_CHUNK_MANIFEST)

    # -- construction --------------------------------------------------------
    @classmethod
    def create(cls, data: bytes, name: bytes = b"", mime: bytes = b"",
               pairs: bytes = b"", last_modified: int = 0, ttl: TTL = EMPTY_TTL,
               is_compressed: bool = False,
               is_chunk_manifest: bool = False) -> "Needle":
        """Build a needle from upload parts, mirroring CreateNeedleFromRequest
        (needle.go:52-124): flags derive from which parts are present."""
        n = cls(data=bytes(data))
        if 0 < len(name) < 256:
            n.name = bytes(name)
            n._set_flag(FLAG_HAS_NAME)
        if 0 < len(mime) < 256:
            n.mime = bytes(mime)
            n._set_flag(FLAG_HAS_MIME)
        if 0 < len(pairs) < 65536:
            n.pairs = bytes(pairs)
            n._set_flag(FLAG_HAS_PAIRS)
        if is_compressed:
            n._set_flag(FLAG_IS_COMPRESSED)
        if is_chunk_manifest:
            n._set_flag(FLAG_IS_CHUNK_MANIFEST)
        if last_modified:
            n.last_modified = last_modified
            n._set_flag(FLAG_HAS_LAST_MODIFIED)
        if ttl:
            n.ttl = ttl
            n._set_flag(FLAG_HAS_TTL)
        n.checksum = crc32c_mod.crc32c(n.data)
        return n

    def parse_path(self, fid: str):
        """Set id/cookie from an "<idhex><cookie8hex>[_delta]" string."""
        delta = 0
        if "_" in fid:
            fid, delta_s = fid.rsplit("_", 1)
            delta = int(delta_s)
        self.id, self.cookie = t.parse_needle_id_cookie(fid)
        self.id += delta

    # -- serialisation --------------------------------------------------------
    def _computed_size(self, version: int) -> int:
        if version == VERSION1:
            return len(self.data)
        if len(self.data) == 0:
            return 0
        size = 4 + len(self.data) + 1
        if self.has_name:
            size += 1 + len(self.name)
        if self.has_mime:
            size += 1 + len(self.mime)
        if self.has_last_modified:
            size += LAST_MODIFIED_BYTES
        if self.has_ttl:
            size += TTL_BYTES
        if self.has_pairs:
            size += 2 + len(self.pairs)
        return size

    def to_bytes(self, version: int = CURRENT_VERSION) -> bytes:
        """Full on-disk record (header..padding); sets self.size."""
        self.size = self._computed_size(version)
        out = bytearray()
        out += t.cookie_to_bytes(self.cookie)
        out += t.needle_id_to_bytes(self.id)
        out += t.size_to_bytes(self.size)
        if version == VERSION1:
            out += self.data
        elif len(self.data) > 0:
            out += struct.pack(">I", len(self.data))
            out += self.data
            out.append(self.flags & 0xFF)
            if self.has_name:
                out.append(len(self.name))
                out += self.name
            if self.has_mime:
                out.append(len(self.mime))
                out += self.mime
            if self.has_last_modified:
                out += struct.pack(">Q", self.last_modified)[8 - LAST_MODIFIED_BYTES:]
            if self.has_ttl:
                out += self.ttl.to_bytes()
            if self.has_pairs:
                out += struct.pack(">H", len(self.pairs))
                out += self.pairs
        out += struct.pack(">I", self.checksum)
        if version == VERSION3:
            out += struct.pack(">Q", self.append_at_ns)
        out += b"\x00" * padding_length(self.size, version)
        return bytes(out)

    # -- parsing --------------------------------------------------------------
    def parse_header(self, b: bytes):
        self.cookie = t.cookie_from_bytes(b[0:4])
        self.id = t.needle_id_from_bytes(b[4:12])
        self.size = t.size_from_bytes(b[12:16])

    def read_bytes(self, blob: bytes, offset: int, size: int, version: int):
        """Hydrate from a full record blob; verifies size + CRC
        (needle_read.go ReadBytes:52-95)."""
        self.parse_header(blob)
        if self.size != size:
            if offset < t.MAX_POSSIBLE_VOLUME_SIZE:
                raise SizeMismatchError(
                    f"entry not found: offset {offset} found id {self.id:x} "
                    f"size {self.size}, expected size {size}")
            raise NeedleError(f"entry not found: size {self.size} != {size}")
        h = t.NEEDLE_HEADER_SIZE
        if version == VERSION1:
            self.data = bytes(blob[h:h + size])
        else:
            self._parse_body_v2(blob[h:h + size])
        if size > 0:
            stored = struct.unpack(">I", blob[h + size:h + size + 4])[0]
            actual = crc32c_mod.crc32c(self.data)
            if stored != actual and stored != crc32c_mod.value(actual):
                raise CrcError("CRC error! Data On Disk Corrupted")
            self.checksum = actual
        if version == VERSION3:
            ts_off = h + size + t.NEEDLE_CHECKSUM_SIZE
            self.append_at_ns = struct.unpack(
                ">Q", blob[ts_off:ts_off + t.TIMESTAMP_SIZE])[0]

    def _parse_body_v2(self, b: bytes):
        idx = 0
        if idx < len(b):
            data_size = struct.unpack(">I", b[idx:idx + 4])[0]
            idx += 4
            if data_size + idx > len(b):
                raise NeedleError("index out of range 1")
            self.data = bytes(b[idx:idx + data_size])
            idx += data_size
        if idx < len(b):
            self.flags = b[idx]
            idx += 1
        if idx < len(b) and self.has_name:
            name_size = b[idx]
            idx += 1
            if name_size + idx > len(b):
                raise NeedleError("index out of range 2")
            self.name = bytes(b[idx:idx + name_size])
            idx += name_size
        if idx < len(b) and self.has_mime:
            mime_size = b[idx]
            idx += 1
            if mime_size + idx > len(b):
                raise NeedleError("index out of range 3")
            self.mime = bytes(b[idx:idx + mime_size])
            idx += mime_size
        if idx < len(b) and self.has_last_modified:
            if LAST_MODIFIED_BYTES + idx > len(b):
                raise NeedleError("index out of range 4")
            self.last_modified = int.from_bytes(
                b[idx:idx + LAST_MODIFIED_BYTES], "big")
            idx += LAST_MODIFIED_BYTES
        if idx < len(b) and self.has_ttl:
            if TTL_BYTES + idx > len(b):
                raise NeedleError("index out of range 5")
            self.ttl = TTL.from_bytes(b[idx:idx + TTL_BYTES])
            idx += TTL_BYTES
        if idx < len(b) and self.has_pairs:
            if 2 + idx > len(b):
                raise NeedleError("index out of range 6")
            pairs_size = struct.unpack(">H", b[idx:idx + 2])[0]
            idx += 2
            if pairs_size + idx > len(b):
                raise NeedleError("index out of range 7")
            self.pairs = bytes(b[idx:idx + pairs_size])
            idx += pairs_size

    def read_needle_body(self, body: bytes, version: int):
        """Hydrate from a body blob following an already-parsed header
        (needle_read.go ReadNeedleBodyBytes:232-255)."""
        if not body:
            return
        if version == VERSION1:
            self.data = bytes(body[: self.size])
        else:
            self._parse_body_v2(body[: self.size])
            if version == VERSION3:
                ts_off = self.size + t.NEEDLE_CHECKSUM_SIZE
                self.append_at_ns = struct.unpack(
                    ">Q", body[ts_off:ts_off + t.TIMESTAMP_SIZE])[0]
        self.checksum = crc32c_mod.crc32c(self.data)

    def etag(self) -> str:
        return struct.pack(">I", self.checksum).hex()


def read_needle_header(blob: bytes) -> tuple["Needle", int]:
    """Parse a 16-byte header; returns (needle, body_length). Caller supplies
    version context for body length (needle_read.go:257-273)."""
    n = Needle()
    n.parse_header(blob)
    return n, n.size
