"""JWT write/read tokens + IP allow-list guard.

The reference mints an HS256 JWT on /dir/assign scoped to one fid and
verifies it on volume-server writes (weed/security/jwt.go: SeaweedFileIdClaims
with "fid"; guard.go:18-50: Guard{whiteList, signingKey, expires}).  Keys and
allow-lists come from security.toml ([jwt.signing] signing_key,
expires_after_seconds; white_list).  Same model here: HS256 via stdlib hmac,
no external jwt dependency.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import ipaddress
import json
import os
import threading
import time
from collections import OrderedDict
from typing import Optional

_DEFAULT_JWT_CACHE = 4096


def jwt_cache_size() -> int:
    """Entries in the signature-verification LRU; 0 disables caching."""
    raw = os.environ.get("WEED_JWT_CACHE_SIZE", "")
    if not raw:
        return _DEFAULT_JWT_CACHE
    try:
        return max(0, int(raw))
    except ValueError:
        return _DEFAULT_JWT_CACHE


def _b64url(data: bytes) -> str:
    return base64.urlsafe_b64encode(data).rstrip(b"=").decode()


def _unb64url(text: str) -> bytes:
    return base64.urlsafe_b64decode(text + "=" * (-len(text) % 4))


# HMAC-SHA256 key schedules, precomputed once per key and copied per
# call: hashing the padded key blocks dominates HMAC cost for the short
# signing inputs JWTs use, and the key set is tiny (one or two per
# daemon)
_mac_lock = threading.Lock()
_mac_templates: dict[bytes, "hmac.HMAC"] = {}


def _sign(key: bytes, msg: bytes) -> bytes:
    with _mac_lock:
        tmpl = _mac_templates.get(key)
        if tmpl is None:
            if len(_mac_templates) >= 64:
                _mac_templates.clear()
            tmpl = _mac_templates[key] = hmac.new(
                key, digestmod=hashlib.sha256)
        mac = tmpl.copy()
    mac.update(msg)
    return mac.digest()


def encode_jwt(key: bytes, claims: dict) -> str:
    header = _b64url(json.dumps(
        {"alg": "HS256", "typ": "JWT"}, separators=(",", ":")).encode())
    payload = _b64url(json.dumps(claims, separators=(",", ":")).encode())
    signing_input = ("%s.%s" % (header, payload)).encode()
    return "%s.%s.%s" % (header, payload, _b64url(_sign(key, signing_input)))


# signature-keyed verification LRU: a count>N assign shares one token
# across N chunk writes, so the volume/filer side re-verifies the same
# (key, token) pair over and over.  Only SUCCESSFUL signature checks are
# cached, and `exp` is re-evaluated on every call, so a cache hit can
# never outlive the token itself.
_verify_lock = threading.Lock()
_verified: "OrderedDict[tuple[bytes, str], dict]" = OrderedDict()


def _jwt_cache_clear():
    with _verify_lock:
        _verified.clear()


def decode_jwt(key: bytes, token: str) -> dict:
    """Verify signature + exp; returns claims. Raises ValueError on failure."""
    size = jwt_cache_size()
    claims = None
    ck = (key, token)
    if size > 0:
        with _verify_lock:
            claims = _verified.get(ck)
            if claims is not None:
                _verified.move_to_end(ck)
        from ..stats.metrics import JwtCacheCounter

        JwtCacheCounter.labels("hit" if claims is not None else "miss").inc()
    if claims is None:
        try:
            header_b64, payload_b64, sig_b64 = token.split(".")
        except ValueError:
            raise ValueError("malformed token")
        header = json.loads(_unb64url(header_b64))
        if header.get("alg") != "HS256":
            raise ValueError("unexpected algorithm %r" % header.get("alg"))
        signing_input = ("%s.%s" % (header_b64, payload_b64)).encode()
        if not hmac.compare_digest(_sign(key, signing_input),
                                   _unb64url(sig_b64)):
            raise ValueError("bad signature")
        claims = json.loads(_unb64url(payload_b64))
        if size > 0:
            with _verify_lock:
                _verified[ck] = claims
                while len(_verified) > size:
                    _verified.popitem(last=False)
    exp = claims.get("exp")
    if exp is not None and time.time() > float(exp):
        raise ValueError("token expired")
    return claims


class SigningKey:
    def __init__(self, key: str | bytes, expires_after_seconds: int = 10):
        self.key = key.encode() if isinstance(key, str) else bytes(key)
        self.expires_after_seconds = expires_after_seconds

    def __bool__(self) -> bool:
        return len(self.key) > 0


def gen_write_jwt(signing: SigningKey, fid: str) -> str:
    """Token scoped to one file id, as minted on assign
    (weed/security/jwt.go GenJwtForVolumeServer)."""
    if not signing:
        return ""
    claims = {"fid": fid}
    if signing.expires_after_seconds > 0:
        claims["exp"] = int(time.time()) + signing.expires_after_seconds
    return encode_jwt(signing.key, claims)


def gen_read_jwt(signing: SigningKey, fid: str) -> str:
    if not signing:
        return ""
    claims = {"fid": fid}
    if signing.expires_after_seconds > 0:
        claims["exp"] = int(time.time()) + signing.expires_after_seconds
    return encode_jwt(signing.key, claims)


class Guard:
    """Combines an IP allow-list with JWT verification
    (weed/security/guard.go:18-50)."""

    def __init__(self, white_list: Optional[list[str]] = None,
                 signing_key: str | bytes = b"",
                 expires_after_seconds: int = 10,
                 read_signing_key: str | bytes = b"",
                 read_expires_after_seconds: int = 60):
        self.white_list = [w for w in (white_list or []) if w]
        self.signing = SigningKey(signing_key, expires_after_seconds)
        self.read_signing = SigningKey(read_signing_key,
                                       read_expires_after_seconds)

    @property
    def is_active(self) -> bool:
        return bool(self.white_list) or bool(self.signing)

    def check_white_list(self, peer_ip: str) -> bool:
        if not self.white_list:
            return True
        try:
            peer = ipaddress.ip_address(peer_ip)
        except ValueError:
            return False
        for entry in self.white_list:
            try:
                if "/" in entry:
                    if peer in ipaddress.ip_network(entry, strict=False):
                        return True
                elif peer == ipaddress.ip_address(entry):
                    return True
            except ValueError:
                continue
        return False

    def verify_write(self, token: str, fid: str) -> None:
        """Raises PermissionError unless the token authorizes writing fid."""
        if not self.signing:
            return
        if not token:
            raise PermissionError("missing jwt")
        try:
            claims = decode_jwt(self.signing.key, token)
        except ValueError as e:
            raise PermissionError("jwt: %s" % e)
        claimed = claims.get("fid", "")
        # a count>1 assign returns one token for fid plus fid_1..fid_N
        # (the reference's file-id delta convention), so compare the base;
        # volume-level tokens ("3,") authorize any fid in the volume
        if claimed != fid.split("_")[0] and not (
                claimed.endswith(",") and fid.startswith(claimed)):
            raise PermissionError("jwt fid mismatch")

    def verify_read(self, token: str, fid: str) -> None:
        if not self.read_signing:
            return
        if not token:
            raise PermissionError("missing read jwt")
        try:
            claims = decode_jwt(self.read_signing.key, token)
        except ValueError as e:
            raise PermissionError("jwt: %s" % e)
        if claims.get("fid", "") != fid:
            raise PermissionError("jwt fid mismatch")


def token_from_request(headers, query: dict) -> str:
    """Authorization: BEARER <t> header, else ?jwt= query param
    (weed/security/jwt.go GetJwt)."""
    auth = headers.get("Authorization", "") if headers is not None else ""
    if auth.upper().startswith("BEARER "):
        return auth[7:].strip()
    return query.get("jwt", "")
