from .jwt_auth import (Guard, SigningKey, decode_jwt, encode_jwt,
                       gen_write_jwt, gen_read_jwt, token_from_request)

__all__ = ["Guard", "SigningKey", "decode_jwt", "encode_jwt",
           "gen_write_jwt", "gen_read_jwt", "token_from_request"]
