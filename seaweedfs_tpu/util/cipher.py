"""AES-256-GCM chunk encryption for filer encrypt-at-rest.

Parity with weed/util/cipher.go: a fresh random 32-byte key per chunk
(stored on the chunk record in filer metadata, never on the volume
server), ciphertext laid out nonce || sealed-data || tag — the same
framing Go's gcm.Seal(nonce, nonce, plaintext, nil) produces.  Volume
servers only ever see ciphertext; whoever holds the filer metadata holds
the keys (filer_server_handlers_write_cipher.go).
"""

from __future__ import annotations

import os

try:
    from cryptography.hazmat.primitives.ciphers.aead import AESGCM
except ImportError:  # pragma: no cover - baked into the image
    AESGCM = None

KEY_SIZE = 32
NONCE_SIZE = 12  # GCM standard nonce


def cipher_available() -> bool:
    return AESGCM is not None


def gen_cipher_key() -> bytes:
    """Random 256-bit per-chunk key (cipher.go GenCipherKey)."""
    return os.urandom(KEY_SIZE)


def encrypt(plaintext: bytes, key: bytes) -> bytes:
    """nonce || AES-256-GCM(plaintext) (cipher.go Encrypt)."""
    if AESGCM is None:
        raise RuntimeError("cryptography library unavailable; "
                           "cannot encrypt chunk data")
    nonce = os.urandom(NONCE_SIZE)
    return nonce + AESGCM(key).encrypt(nonce, bytes(plaintext), None)


def decrypt(ciphertext: bytes, key: bytes) -> bytes:
    """Inverse of encrypt; raises ValueError on truncation or a bad tag
    (cipher.go Decrypt)."""
    if AESGCM is None:
        raise RuntimeError("cryptography library unavailable; "
                           "cannot decrypt chunk data")
    if len(ciphertext) < NONCE_SIZE:
        raise ValueError("ciphertext shorter than its nonce")
    try:
        return AESGCM(key).decrypt(ciphertext[:NONCE_SIZE],
                                   bytes(ciphertext[NONCE_SIZE:]), None)
    except Exception as e:  # InvalidTag and friends
        raise ValueError(f"chunk decrypt failed: {e}") from e
