"""Small platform probes shared across modules.

All probes are TIMED and run OUT OF PROCESS: a daemon must never hang (or
leak a GIL-holding stuck thread) because the TPU transport (the axon relay
tunnel) is wedged.  Device enumeration is attempted in a subprocess with a
deadline; on timeout the caller falls back to host codecs (the reference's
only mode, so behaviour degrades to reference parity, never to a hang).
Negative answers are cached with a TTL so a wedged transport costs one
probe per window, not one per operation.
"""

from __future__ import annotations

import os
import subprocess
import sys
import threading
import time

_INIT_TIMEOUT_S = 30.0
_NEGATIVE_TTL_S = 300.0
_lock = threading.Lock()
_cache: dict = {}  # {"ready": bool, "platform": str, "at": monotonic}


def available_cpu_count() -> int:
    """Cores THIS process may run on: the scheduling affinity mask when
    the platform exposes it (cgroup cpusets, taskset, k8s cpu-manager
    pins all shrink it below os.cpu_count()), else os.cpu_count().
    Worker-pool sizing must use this — spawning os.cpu_count() workers
    onto an affinity-restricted box just convoys them on the GIL."""
    try:
        return len(os.sched_getaffinity(0)) or 1
    except (AttributeError, OSError):
        return os.cpu_count() or 1


def _parent_platforms() -> str:
    """The platform set the parent process would use: the live jax config
    if jax is already imported (tests pin it to cpu after import), else
    the environment."""
    import os

    mod = sys.modules.get("jax")
    if mod is not None:
        try:
            value = mod.config.jax_platforms
            if value:
                return value
        except Exception:
            pass
    return os.environ.get("JAX_PLATFORMS", "")


def _probe(timeout: float) -> tuple[bool, str]:
    """(devices_ready, platform_name) via a subprocess with a deadline."""
    with _lock:
        if _cache:
            fresh = (_cache["ready"]
                     or time.monotonic() - _cache["at"] < _NEGATIVE_TTL_S)
            if fresh:
                return _cache["ready"], _cache["platform"]
    plat = _parent_platforms()
    pin = (f"jax.config.update('jax_platforms', {plat!r}); "
           if plat else "")
    try:
        out = subprocess.run(
            [sys.executable, "-c",
             f"import jax; {pin}print(jax.devices()[0].platform)"],
            capture_output=True, timeout=timeout, text=True)
        ready = out.returncode == 0
        platform = out.stdout.strip().splitlines()[-1] if ready else ""
    except (subprocess.TimeoutExpired, OSError):
        ready, platform = False, ""
    with _lock:
        _cache.update(ready=ready, platform=platform, at=time.monotonic())
    return ready, platform


def jax_usable(timeout: float = _INIT_TIMEOUT_S) -> bool:
    """True when the JAX backend answered device enumeration in time."""
    ready, _ = _probe(timeout)
    return ready


def on_tpu(timeout: float = _INIT_TIMEOUT_S) -> bool:
    """True when the default JAX backend is a real TPU (never hangs)."""
    ready, platform = _probe(timeout)
    return ready and platform == "tpu"


# -- host<->device link throughput + encode-backend auto-selection -----------
#
# "Matching or beating" the host codec must hold on the hardware actually
# present: behind a slow relay tunnel the device may do 49 GiB/s on-chip
# while the LINK caps disk->shards end-to-end far below the host codec.
# The selection below predicts the batched pipeline's achievable rate
# from a measured link probe and picks the faster backend (BASELINE's
# -ec.backend contract: "tpu" forces the device path, None auto-selects).

_LINK_TTL_S = 600.0
_LINK_PROBE_BYTES = 4 << 20
_link_cache: dict = {}  # {"h2d": MB/s, "d2h": MB/s, "at": monotonic}
# fraction of bytes that must come BACK over the link per input byte
# (4 parity shards per 10 data shards)
_PARITY_RATIO = 0.4
# pipeline efficiency vs the raw link numbers (dispatch gaps, framing)
_LINK_EFFICIENCY = 0.85


def link_throughput(probe_bytes: int = _LINK_PROBE_BYTES,
                    ttl: float = _LINK_TTL_S) -> tuple[float, float]:
    """(h2d_MBps, d2h_MBps) of the host<->device link, EWMA-cached with a
    TTL.  Returns (0, 0) when the backend is unreachable.  Call only
    after jax_usable() — a wedged transport would hang the transfer."""
    with _lock:
        cached = dict(_link_cache)
    if cached and time.monotonic() - cached["at"] < ttl:
        return cached["h2d"], cached["d2h"]
    if not jax_usable():
        return 0.0, 0.0
    try:
        import jax
        import numpy as np

        buf = np.zeros(probe_bytes, dtype=np.uint8)
        dev = jax.device_put(buf)
        np.asarray(dev[:4])  # warm the path end to end
        t0 = time.monotonic()
        dev = jax.device_put(buf)
        np.asarray(dev[:4])
        h2d = probe_bytes / (1 << 20) / max(time.monotonic() - t0, 1e-6)
        t0 = time.monotonic()
        np.asarray(dev)
        d2h = probe_bytes / (1 << 20) / max(time.monotonic() - t0, 1e-6)
    except Exception:
        return 0.0, 0.0
    with _lock:
        if _link_cache:  # EWMA: smooth transient relay hiccups
            h2d = 0.5 * h2d + 0.5 * _link_cache["h2d"]
            d2h = 0.5 * d2h + 0.5 * _link_cache["d2h"]
        _link_cache.update(h2d=h2d, d2h=d2h, at=time.monotonic())
    return h2d, d2h


def predicted_batched_gibps() -> float:
    """Predicted disk->shards rate of the batched device pipeline in
    GiB/s: every input byte crosses the link up and 0.4 bytes of parity
    come back, with a fixed efficiency factor."""
    h2d, d2h = link_throughput()
    if h2d <= 0 or d2h <= 0:
        return 0.0
    mbps = _LINK_EFFICIENCY / (1.0 / h2d + _PARITY_RATIO / d2h)
    return mbps / 1024.0


_host_codec_cache: list = []


def host_codec_gibps() -> float:
    """Measured host EC codec kernel rate (GiB/s), derated to an e2e
    estimate; cached per process."""
    if _host_codec_cache:
        return _host_codec_cache[0]
    try:
        import numpy as np

        from ..ops import codec as codec_mod

        enc = codec_mod.new_host_encoder(10, 4)
        data = np.zeros((10, 4 << 20), dtype=np.uint8)
        matrix = np.asarray(enc.matrix[10:])
        enc._apply(matrix, data[:, :1 << 20])  # warm
        t0 = time.monotonic()
        enc._apply(matrix, data)
        dt = max(time.monotonic() - t0, 1e-6)
        kernel = data.nbytes / float(1 << 30) / dt
        # e2e is the smaller of the codec and the host pipeline's I/O
        # side: ~1.2 GiB/s of read+write per I/O-overlapping worker
        # (measured: single-core tmpfs page-allocation bound), scaling
        # with the worker fan-out on multi-core hosts
        workers = int(os.environ.get("WEED_EC_HOST_WORKERS", "0") or 0) \
            or max(1, min(16, available_cpu_count()))
        rate = min(kernel * 0.75, 1.2 * workers)
    except Exception:
        rate = 0.05  # pure-python/numpy fallback territory
    _host_codec_cache.append(rate)
    return rate


def prefer_batched_encode() -> bool:
    """True when the batched device pipeline is predicted to beat the
    synchronous host codec end to end on THIS machine's link."""
    ready, plat = _probe(_INIT_TIMEOUT_S)
    if not ready:
        return False
    if plat != "tpu":
        # CPU/virtual-mesh backend: the "device" shares host memory, so
        # there is no link to lose on — keep the batched pipeline (the
        # surface the multi-chip dryrun and tests exercise)
        return True
    predicted = predicted_batched_gibps()
    host = host_codec_gibps()
    if predicted <= 0:
        return False
    if predicted < host:
        from . import glog

        glog.infof(
            "ec encode auto-backend: host codec (link-capped device "
            "path predicted %.3f GiB/s < host %.3f GiB/s)",
            predicted, host)
        return False
    return True
