"""Small platform probes shared across modules."""

from __future__ import annotations

import functools


@functools.lru_cache(maxsize=1)
def on_tpu() -> bool:
    """True when the default JAX backend is a real TPU."""
    try:
        import jax

        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False
