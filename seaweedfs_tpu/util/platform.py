"""Small platform probes shared across modules.

All probes are TIMED and run OUT OF PROCESS: a daemon must never hang (or
leak a GIL-holding stuck thread) because the TPU transport (the axon relay
tunnel) is wedged.  Device enumeration is attempted in a subprocess with a
deadline; on timeout the caller falls back to host codecs (the reference's
only mode, so behaviour degrades to reference parity, never to a hang).
Negative answers are cached with a TTL so a wedged transport costs one
probe per window, not one per operation.
"""

from __future__ import annotations

import subprocess
import sys
import threading
import time

_INIT_TIMEOUT_S = 30.0
_NEGATIVE_TTL_S = 300.0
_lock = threading.Lock()
_cache: dict = {}  # {"ready": bool, "platform": str, "at": monotonic}


def _parent_platforms() -> str:
    """The platform set the parent process would use: the live jax config
    if jax is already imported (tests pin it to cpu after import), else
    the environment."""
    import os

    mod = sys.modules.get("jax")
    if mod is not None:
        try:
            value = mod.config.jax_platforms
            if value:
                return value
        except Exception:
            pass
    return os.environ.get("JAX_PLATFORMS", "")


def _probe(timeout: float) -> tuple[bool, str]:
    """(devices_ready, platform_name) via a subprocess with a deadline."""
    with _lock:
        if _cache:
            fresh = (_cache["ready"]
                     or time.monotonic() - _cache["at"] < _NEGATIVE_TTL_S)
            if fresh:
                return _cache["ready"], _cache["platform"]
    plat = _parent_platforms()
    pin = (f"jax.config.update('jax_platforms', {plat!r}); "
           if plat else "")
    try:
        out = subprocess.run(
            [sys.executable, "-c",
             f"import jax; {pin}print(jax.devices()[0].platform)"],
            capture_output=True, timeout=timeout, text=True)
        ready = out.returncode == 0
        platform = out.stdout.strip().splitlines()[-1] if ready else ""
    except (subprocess.TimeoutExpired, OSError):
        ready, platform = False, ""
    with _lock:
        _cache.update(ready=ready, platform=platform, at=time.monotonic())
    return ready, platform


def jax_usable(timeout: float = _INIT_TIMEOUT_S) -> bool:
    """True when the JAX backend answered device enumeration in time."""
    ready, _ = _probe(timeout)
    return ready


def on_tpu(timeout: float = _INIT_TIMEOUT_S) -> bool:
    """True when the default JAX backend is a real TPU (never hangs)."""
    ready, platform = _probe(timeout)
    return ready and platform == "tpu"
