"""In-RAM change-log buffer with time-windowed flushing.

Parity with weed/util/log_buffer/log_buffer.go:24-50: mutations append
timestamped entries to a memory buffer; a flush function persists the
buffered window (start_ts, stop_ts, entries) either when the flush
interval elapses or on demand.  Readers tail the in-RAM buffer for events
newer than what has been flushed.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Callable, Optional

FlushFn = Callable[[int, int, list], None]


class LogBuffer:
    def __init__(self, flush_fn: Optional[FlushFn] = None,
                 flush_interval: float = 60.0,
                 max_entries: Optional[int] = None):
        self.flush_fn = flush_fn
        self.flush_interval = flush_interval
        self.max_entries = max_entries  # ring-buffer cap when not flushing
        # (ts_ns, payload), ts_ns ascending.  A deque, NOT a list: the
        # ring-buffer trim on a full list costs a full copy per append
        # (O(cap) on every filer mutation once the buffer fills)
        self._entries: deque = deque()
        self._flushing: list = []  # batch being persisted, still readable
        self._lock = threading.Lock()
        self._flush_gate = threading.Lock()  # serializes flushers
        self._last_flushed_ns = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def add(self, ts_ns: int, payload) -> None:
        with self._lock:
            entries = self._entries
            entries.append((ts_ns, payload))
            if self.max_entries is not None \
                    and len(entries) > self.max_entries:
                entries.popleft()

    def read_since(self, since_ns: int = 0) -> list:
        """In-RAM entries strictly newer than since_ns.  Entries mid-flush
        stay visible until the flush function has persisted them, so a
        cursoring subscriber never observes a gap."""
        with self._lock:
            return [p for ts, p in self._flushing + list(self._entries)
                    if ts > since_ns]

    @property
    def last_flushed_ns(self) -> int:
        return self._last_flushed_ns

    def flush(self) -> int:
        """Persist and drop everything buffered; returns entry count."""
        with self._flush_gate:
            with self._lock:
                if not self._entries:
                    return 0
                batch, self._entries = list(self._entries), deque()
                self._flushing = batch
            try:
                if self.flush_fn is not None:
                    self.flush_fn(batch[0][0], batch[-1][0],
                                  [p for _, p in batch])
                self._last_flushed_ns = batch[-1][0]
            except Exception:
                with self._lock:  # persist failed: keep entries buffered
                    self._entries = deque(batch + list(self._entries))
                    self._flushing = []
                raise
            with self._lock:
                self._flushing = []
            return len(batch)

    # -- background flusher (filer_notify loopFlush analogue) ---------------
    def start(self):
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        try:
            self.flush()
        except Exception:
            pass  # entries stay buffered; caller is shutting down anyway

    def _loop(self):
        while not self._stop.wait(self.flush_interval):
            try:
                self.flush()
            except Exception:
                # transient persist failure: entries were re-queued by
                # flush(); keep the flusher alive for the next interval
                pass
