"""TOML configuration with WEED_* environment overrides + scaffold.

The reference loads {security,filer,master,replication,notification}.toml
via viper from ., ~/.seaweedfs/, /etc/seaweedfs/ with env-var overrides of
the form WEED_SECTION_KEY (weed/command/scaffold.go:15-60,
weed/util/config.go).  Python 3.11+ ships tomllib, so parsing is stdlib.
``scaffold(name)`` emits a commented template like `weed scaffold`.
"""

from __future__ import annotations

import os
from typing import Any, Optional

try:
    import tomllib
except ModuleNotFoundError:  # tomllib is 3.11+; tomli is its backport
    try:
        import tomli as tomllib
    except ModuleNotFoundError:
        tomllib = None

_SEARCH_DIRS = [".", os.path.expanduser("~/.seaweedfs"), "/etc/seaweedfs"]


class Configuration:
    """Nested-dict TOML view with dotted-key access and env overrides:
    get('jwt.signing.key') checks WEED_JWT_SIGNING_KEY first."""

    def __init__(self, data: Optional[dict] = None, prefix: str = "WEED"):
        self.data = data or {}
        self.prefix = prefix

    def get(self, dotted: str, default: Any = None) -> Any:
        env_key = "%s_%s" % (self.prefix,
                             dotted.upper().replace(".", "_").replace("-", "_"))
        if env_key in os.environ:
            return os.environ[env_key]
        node: Any = self.data
        for part in dotted.split("."):
            if not isinstance(node, dict) or part not in node:
                return default
            node = node[part]
        return node

    def get_bool(self, dotted: str, default: bool = False) -> bool:
        v = self.get(dotted, default)
        if isinstance(v, str):
            return v.lower() in ("1", "true", "yes", "on")
        return bool(v)

    def get_int(self, dotted: str, default: int = 0) -> int:
        v = self.get(dotted, default)
        return int(v)

    def sub(self, dotted: str) -> "Configuration":
        node = self.get(dotted, {})
        return Configuration(node if isinstance(node, dict) else {},
                             self.prefix)


def load_configuration(name: str, required: bool = False,
                       search_dirs: Optional[list[str]] = None
                       ) -> Configuration:
    """Load <name>.toml from the search path (util.LoadConfiguration)."""
    for d in search_dirs or _SEARCH_DIRS:
        path = os.path.join(d, name + ".toml")
        if os.path.isfile(path):
            if tomllib is None:
                # env overrides still apply via Configuration.get
                return Configuration({})
            with open(path, "rb") as f:
                return Configuration(tomllib.load(f))
    if required:
        raise FileNotFoundError(
            "%s.toml not found in %s" % (name, search_dirs or _SEARCH_DIRS))
    return Configuration({})


_SCAFFOLDS = {
    "security": '''\
# Put this file to one of:
# ./security.toml, $HOME/.seaweedfs/security.toml, /etc/seaweedfs/security.toml
# this file is read by master, volume server, and filer

[jwt.signing]
# generate a 32-byte random key and set it on master + volume servers to
# require a signed token for every write
key = ""
expires_after_seconds = 10

[jwt.signing.read]
key = ""
expires_after_seconds = 60

[access]
# comma-separated IPs/CIDRs allowed to use the admin UI and APIs
ui = ""
''',
    "master": '''\
[master.maintenance]
# periodically run these scripts like a cron job
scripts = """
  ec.encode -fullPercent=95 -quietFor=1h
  ec.rebuild -force
  ec.balance -force
  volume.balance -force
"""
sleep_minutes = 17

[master.sequencer]
type = "raft"  # raft | snowflake
sequencer_snowflake_id = 0

[master.volume_growth]
copy_1 = 7
copy_2 = 6
copy_3 = 3
copy_other = 1
''',
    "filer": '''\
# Filer store configuration. Exactly one store should be enabled.

[leveldb]
# embedded sorted-key store (sqlite-backed in this implementation)
enabled = true
dir = "./filerldb"

[memory]
# in-RAM store for tests
enabled = false

[redis]
enabled = false
address = "localhost:6379"
''',
    "replication": '''\
[source.filer]
enabled = true
grpcAddress = "localhost:18888"
directory = "/buckets"

[sink.filer]
enabled = false
grpcAddress = "localhost:18888"
directory = "/backup"
replication = ""
collection = ""
ttlSec = 0

[sink.local]
enabled = false
directory = "/data"
''',
    "notification": '''\
[notification.log]
# this is only for debugging purpose and does not work with "weed filer.replicate"
enabled = false

[notification.file]
# append every filer change event as a JSON line to a local file
enabled = false
path = "filer_events.jsonl"

[notification.kafka]
enabled = false
hosts = "kafka1:9092"
topic = "seaweedfs_filer"
''',
}


def scaffold(name: str) -> str:
    if name not in _SCAFFOLDS:
        raise KeyError("unknown config %r (choose from %s)" % (
            name, ", ".join(sorted(_SCAFFOLDS))))
    return _SCAFFOLDS[name]
