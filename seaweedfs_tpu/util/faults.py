"""Deterministic fault injection: the chaos substrate for robustness
tests and live incident drills.

A process-wide registry of rules, each matched against an RPC or disk
event by (side, dst, route) globs and fired with a configured
probability.  Determinism is the whole point: the fire/no-fire decision
for the k-th event matching a rule is a pure hash of
(seed, rule_id, k) — NOT a shared RNG stream — so the injected sequence
per rule is identical across runs regardless of thread interleaving
between rules.  Re-running a test with the same WEED_FAULTS spec and
seed replays the same faults.

Spec syntax (WEED_FAULTS env var, also accepted by POST /debug/faults):

    rule[;rule...]
    rule  = kind,key=value[,key=value...]
    kind  = latency | error | reset | short_read | disk_error
    keys  = pct=<float 0..100>   fire probability (default 100)
            ms=<float>           latency to inject (latency kind)
            status=<int>         HTTP status to inject (error kind,
                                 default 503)
            dst=<glob>           destination "host:port" filter
            route=<glob>         request path filter
            side=<client|server|disk|any>  hook side (default any)
            times=<int>          stop after N fires (0 = unlimited)
            id=<name>            stable rule id (default kind#index)

Example — 5% 503s to one volume server plus 50 ms on every lookup:

    WEED_FAULTS='error,status=503,pct=5,dst=127.0.0.1:8080;latency,ms=50,route=/dir/lookup*'
    WEED_FAULTS_SEED=42

Hook points (all no-ops while no rules are loaded — a single module
bool guards the hot path):

  * rpc/http_rpc.py call()/call_stream()  -> on_rpc("client", dst, route)
  * RpcServer._dispatch                   -> on_rpc("server", dst, route)
  * storage/backend.py DiskFile           -> on_disk(path, op)

Every daemon mounts GET/POST /debug/faults (debug_handler) to inspect
counters and flip rules live.
"""

from __future__ import annotations

import fnmatch
import hashlib
import os
import threading
import time
from typing import Callable, List, Optional


class FaultInjected(Exception):
    """Raised by the hooks for error/reset/short_read/disk_error kinds;
    carries the HTTP status the fault should surface as.  Converted to
    RpcError (rpc layer) or OSError (disk layer) at the hook site."""

    def __init__(self, rule_id: str, kind: str, status: int = 503):
        super().__init__(f"injected fault [{rule_id}] kind={kind}")
        self.rule_id = rule_id
        self.kind = kind
        self.status = status


KINDS = ("latency", "error", "reset", "short_read", "disk_error")


class FaultRule:
    __slots__ = ("id", "kind", "pct", "ms", "status", "dst", "route",
                 "side", "times", "nbytes", "matches", "fires")

    def __init__(self, kind: str, id: str = "", pct: float = 100.0,
                 ms: float = 0.0, status: int = 503, dst: str = "*",
                 route: str = "*", side: str = "any", times: int = 0,
                 nbytes: int = 0):
        if kind not in KINDS:
            raise ValueError(f"unknown fault kind {kind!r}")
        self.kind = kind
        self.id = id or kind
        self.pct = pct
        self.ms = ms
        self.status = status
        self.dst = dst
        self.route = route
        self.side = side
        self.times = times
        self.nbytes = nbytes  # short_read cut point (0 = half the body)
        self.matches = 0  # events that matched the filters
        self.fires = 0    # events where the hash said "fire"

    def accepts(self, side: str, dst: str, route: str) -> bool:
        if self.side not in ("any", side):
            return False
        if self.times and self.fires >= self.times:
            return False
        return fnmatch.fnmatchcase(dst, self.dst) and \
            fnmatch.fnmatchcase(route, self.route)

    def to_dict(self) -> dict:
        return {"id": self.id, "kind": self.kind, "pct": self.pct,
                "ms": self.ms, "status": self.status, "dst": self.dst,
                "route": self.route, "side": self.side,
                "times": self.times, "bytes": self.nbytes,
                "matches": self.matches, "fires": self.fires}


def _decision(seed: int, rule_id: str, n: int) -> float:
    """Pure [0,1) decision value for the n-th event matching a rule.
    blake2b of (seed, rule_id, n): replayable independently of thread
    scheduling across rules, unlike a shared RNG stream."""
    h = hashlib.blake2b(f"{seed}:{rule_id}:{n}".encode(),
                        digest_size=8).digest()
    return int.from_bytes(h, "big") / float(1 << 64)


def parse_spec(spec: str) -> List[FaultRule]:
    rules: List[FaultRule] = []
    for i, part in enumerate(p.strip() for p in spec.split(";")):
        if not part:
            continue
        tokens = [t.strip() for t in part.split(",") if t.strip()]
        kind, kv = tokens[0], {}
        for tok in tokens[1:]:
            k, _, v = tok.partition("=")
            kv[k.strip()] = v.strip()
        rules.append(FaultRule(
            kind,
            id=kv.get("id", f"{kind}#{i}"),
            pct=float(kv.get("pct", 100)),
            ms=float(kv.get("ms", 0)),
            status=int(kv.get("status", 503)),
            dst=kv.get("dst", "*"),
            route=kv.get("route", "*"),
            side=kv.get("side", "any"),
            times=int(kv.get("times", 0)),
            nbytes=int(kv.get("bytes", 0))))
    return rules


class FaultRegistry:
    """Process-wide rule set + deterministic decision log."""

    LOG_MAX = 4096

    def __init__(self):
        self._lock = threading.Lock()
        self.rules: List[FaultRule] = []
        self.seed = 0
        self.log: List[tuple] = []  # (rule_id, n, side, dst, route, kind)
        # injectable so tests drive latency with a fake clock
        self.sleep: Callable[[float], None] = time.sleep
        self._loaded_env = False

    # -- configuration ---------------------------------------------------

    def configure(self, spec: str, seed: int = 0):
        rules = parse_spec(spec)
        with self._lock:
            self.rules = rules
            self.seed = seed
            self.log = []
        _set_active(bool(rules))
        if rules:
            from ..stats import events as _events

            _events.emit(_events.FAULTS_ACTIVE, service="faults",
                         detail={"rules": len(rules), "seed": seed})

    def add_rule(self, spec: str):
        rules = parse_spec(spec)
        with self._lock:
            self.rules.extend(rules)
        _set_active(True)

    def clear(self):
        with self._lock:
            self.rules = []
            self.log = []
        _set_active(False)

    def reset_counters(self):
        """Rewind match/fire counters + log so the same rule set replays
        the identical sequence (decisions are f(seed, rule, n))."""
        with self._lock:
            for r in self.rules:
                r.matches = r.fires = 0
            self.log = []

    def load_env(self, force: bool = False):
        """Pick up WEED_FAULTS/WEED_FAULTS_SEED once per process (or
        again with force=True after the env changed)."""
        if self._loaded_env and not force:
            return
        self._loaded_env = True
        spec = os.environ.get("WEED_FAULTS", "")
        if spec:
            self.configure(spec,
                           int(os.environ.get("WEED_FAULTS_SEED", "0")))

    # -- event evaluation ------------------------------------------------

    def _fired(self, side: str, dst: str, route: str
               ) -> List[FaultRule]:
        fired = []
        with self._lock:
            for rule in self.rules:
                if not rule.accepts(side, dst, route):
                    continue
                rule.matches += 1
                n = rule.matches
                if _decision(self.seed, rule.id, n) * 100.0 < rule.pct:
                    rule.fires += 1
                    fired.append(rule)
                    if len(self.log) < self.LOG_MAX:
                        self.log.append((rule.id, n, side, dst, route,
                                         rule.kind))
        for rule in fired:
            _count(rule.kind, rule.id)
        return fired

    def on_rpc(self, side: str, dst: str, route: str):
        """RPC hook: sleeps for latency rules, raises FaultInjected for
        error/reset kinds, returns a short-read byte cap (or None)."""
        short_read = None
        for rule in self._fired(side, dst, route):
            if rule.kind == "latency":
                self.sleep(rule.ms / 1000.0)
            elif rule.kind == "error":
                raise FaultInjected(rule.id, "error", rule.status)
            elif rule.kind == "reset":
                raise FaultInjected(rule.id, "reset", 503)
            elif rule.kind == "short_read":
                short_read = rule
        return short_read

    def on_disk(self, path: str, op: str):
        """Disk-I/O hook: dst = file path, route = op (read/write/sync).
        disk_error raises OSError; latency rules with side=disk sleep."""
        for rule in self._fired("disk", path, op):
            if rule.kind == "latency":
                self.sleep(rule.ms / 1000.0)
            elif rule.kind in ("error", "disk_error"):
                raise OSError(
                    5, f"injected disk fault [{rule.id}] on {op}")

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "seed": self.seed,
                "rules": [r.to_dict() for r in self.rules],
                "log": [{"rule": rid, "n": n, "side": side, "dst": dst,
                         "route": route, "kind": kind}
                        for rid, n, side, dst, route, kind in self.log],
            }


REGISTRY = FaultRegistry()

# hot-path guard: call()/dispatch/disk writes check this single bool
# before paying any lock or match cost
ACTIVE = False


def _set_active(value: bool):
    global ACTIVE
    ACTIVE = value


def _count(kind: str, rule_id: str):
    from ..stats import metrics as stats

    stats.FaultsInjectedCounter.labels(kind, rule_id).inc()


def on_rpc(side: str, dst: str, route: str):
    """Cheap front door for the rpc layer (no-op unless rules loaded)."""
    if not ACTIVE:
        return None
    return REGISTRY.on_rpc(side, dst, route)


def on_disk(path: str, op: str):
    if not ACTIVE:
        return
    REGISTRY.on_disk(path, op)


def load_env():
    REGISTRY.load_env()


def debug_handler(req):
    """GET/POST /debug/faults — mounted on every daemon.

    GET returns {seed, rules[], log[]}.  POST accepts JSON:
      {"spec": "...", "seed": N}  replace the rule set
      {"add": "rule[;rule]"}      append rules
      {"clear": true}             drop all rules
      {"reset": true}             rewind counters/log for replay
    """
    if req.handler.command == "GET":
        return REGISTRY.snapshot()
    body = req.json()
    if body.get("clear"):
        REGISTRY.clear()
    if body.get("reset"):
        REGISTRY.reset_counters()
    if "spec" in body:
        REGISTRY.configure(body["spec"], int(body.get("seed", 0)))
    elif "add" in body:
        REGISTRY.add_rule(body["add"])
    return REGISTRY.snapshot()


def mount(server):
    """Register the /debug/faults routes on an RpcServer."""
    server.add("GET", "/debug/faults", debug_handler)
    server.add("POST", "/debug/faults", debug_handler)
