"""Leveled verbose logging, modeled on the reference's vendored glog
(/root/reference/weed/glog: leveled V(n) guards, vmodule per-file
overrides, severity thresholds, optional file rotation).

Idiomatic-Python shape: module-level severity functions plus a ``v(n)``
guard that is cheap when disabled.  Verbosity is configured globally
(``set_verbosity``) or per-module (``set_vmodule("volume*=3")``), matching
the reference's ``-v`` and ``-vmodule`` flags (glog.go).
"""

from __future__ import annotations

import fnmatch
import os
import sys
import threading
import time

_lock = threading.Lock()
_verbosity = 0
_vmodule: list[tuple[str, int]] = []  # (pattern, level)
_min_severity = 0  # 0=INFO 1=WARNING 2=ERROR 3=FATAL
_out = sys.stderr
_SEVERITIES = "IWEF"


def set_verbosity(level: int) -> None:
    global _verbosity
    _verbosity = int(level)


def set_vmodule(spec: str) -> None:
    """"volume*=3,needle=1" — per-module verbosity overrides."""
    global _vmodule
    mods = []
    for part in spec.split(","):
        if not part.strip():
            continue
        pattern, _, level = part.partition("=")
        mods.append((pattern.strip(), int(level or 0)))
    with _lock:
        _vmodule = mods


def set_severity_threshold(severity: str) -> None:
    global _min_severity
    _min_severity = _SEVERITIES.index(severity[0].upper())


def set_output(stream) -> None:
    global _out
    _out = stream


def _caller_module(depth: int = 3) -> str:
    frame = sys._getframe(depth)
    return os.path.splitext(
        os.path.basename(frame.f_code.co_filename))[0]


class _VLog:
    """Result of v(n): truthy if enabled; .info() emits at INFO."""

    __slots__ = ("enabled",)

    def __init__(self, enabled: bool):
        self.enabled = enabled

    def __bool__(self) -> bool:
        return self.enabled

    def info(self, *args) -> None:
        if self.enabled:
            _emit(0, " ".join(str(a) for a in args), depth=2)

    def infof(self, fmt: str, *args) -> None:
        if self.enabled:
            _emit(0, fmt % args if args else fmt, depth=2)


def v(level: int) -> _VLog:
    if level <= _verbosity:
        return _VLog(True)
    if _vmodule:
        mod = _caller_module(depth=2)
        with _lock:
            for pattern, lvl in _vmodule:
                if fnmatch.fnmatch(mod, pattern):
                    return _VLog(level <= lvl)
    return _VLog(False)


_tracing = None


def _trace_prefix() -> str:
    """"[trace_id] " when the calling thread carries a SAMPLED span, so
    slow-trace promotion (WEED_TRACE_SLOW_MS) cross-references straight
    into daemon logs.  Unsampled spans stay silent: the id would never
    appear in /debug/traces, so it is noise."""
    global _tracing
    if _tracing is None:
        try:
            from .. import tracing as _t
        except ImportError:  # pragma: no cover - partial teardown
            return ""
        _tracing = _t
    sp = _tracing.current()
    if sp is not None and sp.sampled:
        return "[%s] " % sp.trace_id
    return ""


def _emit(severity: int, message: str, depth: int = 3) -> None:
    if severity < _min_severity:
        return
    now = time.time()
    tm = time.localtime(now)
    frame = sys._getframe(depth)
    where = "%s:%d" % (os.path.basename(frame.f_code.co_filename),
                       frame.f_lineno)
    line = "%s%02d%02d %02d:%02d:%02d.%06d %5d %s] %s%s\n" % (
        _SEVERITIES[severity], tm.tm_mon, tm.tm_mday, tm.tm_hour, tm.tm_min,
        tm.tm_sec, int((now % 1) * 1e6), threading.get_ident() % 100000,
        where, _trace_prefix(), message)
    with _lock:
        _out.write(line)
        _out.flush()


def info(*args) -> None:
    _emit(0, " ".join(str(a) for a in args), depth=2)


def infof(fmt: str, *args) -> None:
    _emit(0, fmt % args if args else fmt, depth=2)


def warning(*args) -> None:
    _emit(1, " ".join(str(a) for a in args), depth=2)


def warningf(fmt: str, *args) -> None:
    _emit(1, fmt % args if args else fmt, depth=2)


def error(*args) -> None:
    _emit(2, " ".join(str(a) for a in args), depth=2)


def errorf(fmt: str, *args) -> None:
    _emit(2, fmt % args if args else fmt, depth=2)


def fatal(*args) -> None:
    _emit(3, " ".join(str(a) for a in args), depth=2)
    raise SystemExit(255)


def fatalf(fmt: str, *args) -> None:
    _emit(3, fmt % args if args else fmt, depth=2)
    raise SystemExit(255)
