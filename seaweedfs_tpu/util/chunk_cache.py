"""Tiered chunk cache: RAM LRU plus size-classed on-disk FIFO layers.

The implementation moved into the unified read-through cache package
(`seaweedfs_tpu/cache/` — HBM -> host RAM -> disk, shared by the volume
server, filer and s3api GET paths).  This module keeps the historical
import surface: `CacheVolume` and `OnDiskCacheLayer` re-export the disk
tier, and `TieredChunkCache` preserves the old positional-`directory`
constructor over `cache.TieredReadCache`.
"""

from __future__ import annotations

from ..cache.disk import CacheVolume, OnDiskCacheLayer  # noqa: F401
from ..cache.read_cache import TieredReadCache


class TieredChunkCache(TieredReadCache):
    """RAM LRU + three size-classed disk layers (chunk_cache.go)."""

    def __init__(self, directory: str, mem_bytes: int = 64 << 20,
                 disk_bytes: int = 1 << 30, unit_size: int = 1 << 20):
        super().__init__(mem_bytes=mem_bytes, directory=directory,
                         disk_bytes=disk_bytes, unit_size=unit_size)
