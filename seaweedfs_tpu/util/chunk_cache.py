"""Tiered chunk cache: RAM LRU plus size-classed on-disk FIFO layers.

Parity with weed/util/chunk_cache (chunk_cache.go TieredChunkCache,
on_disk_cache_layer.go, chunk_cache_on_disk.go): small chunks live in an
in-memory LRU AND the small disk layer; medium and large chunks go to
their own disk layers.  Each disk layer is a ring of append-only cache
volumes — a flat data file plus an in-RAM fid index — and when the front
volume fills, the oldest volume is reset and rotated to the front, giving
FIFO eviction in volume-sized steps with no per-entry bookkeeping on
disk.  Restarts rebuild nothing: cache volumes restart empty (the index
is RAM-only), which is correct for a cache and avoids the reference's
leveldb sidecar.
"""

from __future__ import annotations

import os
import threading
from typing import Optional

from ..filer.reader_cache import ChunkCache as MemoryChunkCache


class CacheVolume:
    """One append-only cache segment: flat file + RAM index."""

    def __init__(self, file_name: str, size_limit: int):
        self.file_name = file_name
        self.size_limit = size_limit
        self._index: dict[str, tuple[int, int]] = {}  # fid -> (off, len)
        # unbuffered: reads go through os.pread, which sees only what has
        # actually reached the fd
        self._file = open(file_name, "wb+", buffering=0)
        self.file_size = 0

    def get(self, fid: str) -> Optional[bytes]:
        loc = self._index.get(fid)
        if loc is None:
            return None
        return os.pread(self._file.fileno(), loc[1], loc[0])

    def has_room(self, n: int) -> bool:
        return self.file_size + n <= self.size_limit

    def put(self, fid: str, data: bytes):
        off = self.file_size
        self._file.seek(off)
        self._file.write(data)
        self.file_size = off + len(data)
        self._index[fid] = (off, len(data))

    def reset(self):
        self._file.truncate(0)
        self._index.clear()
        self.file_size = 0

    def close(self):
        try:
            self._file.close()
            os.unlink(self.file_name)
        except OSError:
            pass


class OnDiskCacheLayer:
    """Ring of cache volumes with rotate-on-full FIFO eviction
    (on_disk_cache_layer.go setChunk)."""

    def __init__(self, directory: str, prefix: str, total_bytes: int,
                 segments: int):
        self.seg_size = max(1, total_bytes // segments)
        self.volumes = [
            CacheVolume(os.path.join(directory, f"{prefix}_{i}.dat"),
                        self.seg_size)
            for i in range(segments)]
        self._lock = threading.Lock()  # per-layer, not cache-global

    def get(self, fid: str) -> Optional[bytes]:
        with self._lock:
            for v in self.volumes:
                data = v.get(fid)
                if data is not None:
                    return data
            return None

    def put(self, fid: str, data: bytes):
        if len(data) > self.seg_size:
            return  # can never fit; don't wipe a segment discovering that
        with self._lock:
            if not self.volumes[0].has_room(len(data)):
                oldest = self.volumes.pop()
                oldest.reset()
                self.volumes.insert(0, oldest)
            self.volumes[0].put(fid, data)

    def close(self):
        with self._lock:
            for v in self.volumes:
                v.close()


class TieredChunkCache:
    """RAM LRU + three size-classed disk layers (chunk_cache.go)."""

    def __init__(self, directory: str, mem_bytes: int = 64 << 20,
                 disk_bytes: int = 1 << 30, unit_size: int = 1 << 20):
        os.makedirs(directory, exist_ok=True)
        self.limit0 = unit_size          # small
        self.limit1 = 4 * unit_size      # medium
        self.mem = MemoryChunkCache(mem_bytes)
        # same 1/8 : 3/8 : 1/2 split and segment counts as the reference
        self.layers = [
            OnDiskCacheLayer(directory, "c0_2", disk_bytes // 8, 2),
            OnDiskCacheLayer(directory, "c1_3", disk_bytes * 3 // 8, 3),
            OnDiskCacheLayer(directory, "c2_2", disk_bytes // 2, 2),
        ]
        # layers lock themselves; this guards only the counters
        self._stat_lock = threading.Lock()
        self.hits = 0
        self.misses = 0

    def _count(self, hit: bool):
        with self._stat_lock:
            if hit:
                self.hits += 1
            else:
                self.misses += 1

    def get(self, fid: str) -> Optional[bytes]:
        data = self.mem.get(fid)
        if data is not None:
            self._count(True)
            return data
        for layer in self.layers:
            data = layer.get(fid)
            if data is not None:
                self._count(True)
                return data
        self._count(False)
        return None

    def put(self, fid: str, data: bytes):
        if len(data) <= self.limit0:
            self.mem.put(fid, data)
            layer = self.layers[0]
        elif len(data) <= self.limit1:
            layer = self.layers[1]
        else:
            layer = self.layers[2]
        layer.put(fid, data)

    def close(self):
        for layer in self.layers:
            layer.close()
