"""Graceful shutdown + profiling hooks (weed/util/grace).

Parity with grace.OnInterrupt / grace.SetupProfiling (util/grace/
signal_handling.go, pprof.go): daemons register cleanup hooks that run
exactly once on SIGINT/SIGTERM or normal exit, and -cpuprofile /
-memprofile flags dump a cProfile trace / tracemalloc snapshot on
shutdown — the Python equivalents of Go's pprof cpu/heap profiles.
"""

from __future__ import annotations

import atexit
import signal
import sys
import threading
from typing import Callable, Optional

_hooks: list[Callable[[], None]] = []
# RLock: a signal can land while the main thread holds the lock in
# on_interrupt/_run_hooks; the handler re-enters on the same thread
_hook_lock = threading.RLock()
_installed = False
_ran = False

_cpu_profiler = None
_cpu_profile_path = ""
_mem_profile_path = ""


class SamplingProfiler:
    """pprof-style sampling CPU profiler covering ALL threads.

    cProfile only traces the thread that enabled it — useless for a
    daemon whose work happens on HTTP worker threads while main sits in
    signal.pause().  This delegates to profiling.StackSampler (the same
    folded-stack engine behind /debug/pprof/profile), so the shutdown
    dump is collapsed-stack text that feeds straight into flamegraph.pl
    or speedscope — the old flat leaf-frame report carried no caller
    context."""

    def __init__(self, interval: float = 0.005):
        from .. import profiling

        self.interval = interval
        self._sampler = profiling.StackSampler(hz=1.0 / interval)

    @property
    def total(self) -> int:
        return self._sampler.total

    @property
    def samples(self) -> dict:
        return self._sampler.samples

    def start(self):
        self._sampler.start()

    def stop_and_dump(self, path: str):
        if not self._sampler.stop():
            # the sampler thread is daemonized so it cannot hang exit,
            # but a dump racing one last tick deserves a trace, not
            # silence (the old implementation leaked the thread quietly)
            from . import glog

            glog.warningf("cpu profile sampler did not join in time; "
                          "dump may miss the final tick")
        with open(path, "w") as f:
            f.write(f"# sampling cpu profile: {self.total} samples "
                    f"@ {self.interval * 1000:.1f}ms "
                    f"(collapsed stacks — flamegraph.pl/speedscope)\n")
            f.write(self._sampler.folded())


def on_interrupt(hook: Callable[[], None]):
    """Register a cleanup hook (grace.OnInterrupt); installs the signal
    handlers on first use."""
    global _installed
    with _hook_lock:
        _hooks.append(hook)
        if not _installed:
            _installed = True
            for sig in (signal.SIGINT, signal.SIGTERM):
                try:
                    signal.signal(sig, _handle_signal)
                except ValueError:
                    pass  # not the main thread (tests): atexit covers it
            atexit.register(_run_hooks)


def _run_hooks():
    global _ran
    with _hook_lock:
        if _ran:
            return
        _ran = True
        hooks, _hooks[:] = list(_hooks), []
    _stop_profiling()
    for hook in reversed(hooks):
        try:
            hook()
        except Exception:
            pass


def _handle_signal(signum, frame):
    _run_hooks()
    sys.exit(0)


def setup_profiling(cpu_profile: str = "", mem_profile: str = ""):
    """grace.SetupProfiling: start CPU/heap profiling now, dump on
    shutdown.  The CPU profile samples every thread (flat text report,
    hottest lines first)."""
    global _cpu_profiler, _cpu_profile_path, _mem_profile_path
    if cpu_profile:
        _cpu_profile_path = cpu_profile
        _cpu_profiler = SamplingProfiler()
        _cpu_profiler.start()
    if mem_profile:
        import tracemalloc

        _mem_profile_path = mem_profile
        tracemalloc.start(10)
    if cpu_profile or mem_profile:
        on_interrupt(lambda: None)  # ensure handlers are installed


def _stop_profiling():
    global _cpu_profiler
    if _cpu_profiler is not None:
        _cpu_profiler.stop_and_dump(_cpu_profile_path)
        _cpu_profiler = None
    if _mem_profile_path:
        import tracemalloc

        if tracemalloc.is_tracing():
            snapshot = tracemalloc.take_snapshot()
            with open(_mem_profile_path, "w") as f:
                for stat in snapshot.statistics("lineno")[:100]:
                    f.write(f"{stat}\n")
            tracemalloc.stop()


def _reset_for_tests():
    global _ran, _installed
    with _hook_lock:
        _hooks.clear()
        _ran = False
