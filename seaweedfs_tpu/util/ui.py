"""Minimal server status pages (weed/server/{master,volume_server,
filer}_ui): one self-contained HTML page per daemon showing identity,
counters, and topology tables — no external assets."""

from __future__ import annotations

import html
import time
from typing import Iterable

_PAGE = """<!DOCTYPE html>
<html><head><title>{title}</title><style>
body {{ font-family: -apple-system, 'Segoe UI', sans-serif; margin: 2em;
       color: #1a2b33; }}
h1 {{ font-size: 1.4em; }} h2 {{ font-size: 1.1em; margin-top: 1.5em; }}
table {{ border-collapse: collapse; min-width: 30em; }}
th, td {{ border: 1px solid #cdd7db; padding: .35em .7em;
          text-align: left; font-size: .92em; }}
th {{ background: #eef3f5; }}
.footer {{ margin-top: 2em; color: #7a8a92; font-size: .8em; }}
</style></head><body>
<h1>{title}</h1>
{body}
<div class="footer">seaweedfs_tpu &middot; rendered {now}</div>
</body></html>"""


def _esc(v) -> str:
    return html.escape(str(v))


def table(headers: Iterable[str], rows: Iterable[Iterable]) -> str:
    head = "".join(f"<th>{_esc(h)}</th>" for h in headers)
    body = "".join(
        "<tr>" + "".join(f"<td>{_esc(c)}</td>" for c in row) + "</tr>"
        for row in rows)
    return f"<table><tr>{head}</tr>{body}</table>"


def kv_table(pairs: dict) -> str:
    return table(("property", "value"), pairs.items())


def section(title: str, content: str) -> str:
    return f"<h2>{_esc(title)}</h2>\n{content}"


def page(title: str, *sections: str) -> bytes:
    return _PAGE.format(
        title=_esc(title), body="\n".join(sections),
        now=time.strftime("%Y-%m-%d %H:%M:%S")).encode()
