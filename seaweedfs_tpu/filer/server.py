"""Filer HTTP server: path-addressed files over the volume store.

Parity with weed/server/filer_server_handlers_*.go:
  * POST/PUT /path: auto-chunked upload — split body into chunks, assign a
    fid per chunk from the master, upload to volume servers, save the entry
    (filer_server_handlers_write_autochunk.go:23-130); small files inline
    into the entry
  * GET /path: entry resolution -> chunk fetches -> reassembled body with
    Range support (filer_server_handlers_read.go); directories return JSON
    listings (?limit=&lastFileName=)
  * DELETE /path?recursive=true: recursive delete + chunk reclamation
  * POST /path?mv.from=/src: rename
  * GET /metadata/subscribe?since=: change-log tail (SubscribeMetadata)
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Iterator, Optional

from .. import profiling, qos, tracing
from ..rpc import policy
from ..rpc.http_rpc import (FileSlice, Request, Response, RpcError,
                            RpcServer, call, sendfile_enabled)
from ..util import faults
from ..security import Guard, gen_read_jwt, gen_write_jwt
from ..stats import access
from ..stats import events as events_mod
from ..stats import healthz
from ..stats import metrics as stats
from ..storage.needle import PAIR_NAME_PREFIX
from .entry import Attr, Entry, FileChunk, total_size
from .filechunk_manifest import (MANIFEST_BATCH, has_chunk_manifest,
                                 maybe_manifestize, resolve_chunk_manifest)
from .filechunks import etag_of_chunks, read_chunk_views
from ..wdclient.masterclient import MasterClient
from .filer import Filer
from .filer_conf import FilerConf
from .filer_store import FilerStore, NotFoundError
from .meta_aggregator import MetaAggregator
from ..cache import TieredReadCache

DEFAULT_CHUNK_SIZE = 4 * 1024 * 1024  # filer -maxMB default (4MB)
INLINE_LIMIT = 2048  # small-content inlining threshold
_DEFAULT_PREFETCH = 4


def prefetch_chunks() -> int:
    """Streaming-GET look-ahead window K; 0 disables streaming."""
    raw = os.environ.get("WEED_FILER_PREFETCH_CHUNKS", "")
    if not raw:
        return _DEFAULT_PREFETCH
    try:
        return max(0, int(raw))
    except ValueError:
        return _DEFAULT_PREFETCH


class FilerServer:
    def __init__(self, master_address: str, host: str = "127.0.0.1",
                 port: int = 0, store: Optional[FilerStore] = None,
                 chunk_size: int = DEFAULT_CHUNK_SIZE,
                 replication: str = "", collection: str = "",
                 guard: Optional[Guard] = None,
                 peers: Optional[list[str]] = None,
                 persist_meta_log: bool = False,
                 chunk_cache_bytes: Optional[int] = None,
                 manifest_batch: int = MANIFEST_BATCH,
                 cipher: bool = False,
                 cache_dir: str = "",
                 cache_disk_bytes: int = 1 << 30):
        # -master may name the whole raft trio ("a,b,c"): every
        # master call then fails over through the MasterClient (leader
        # hints, per-master breakers) instead of pinning one address
        self.masters = [m.strip() for m in master_address.split(",")
                        if m.strip()]
        self.master_address = self.masters[0]
        self._master_client = MasterClient(self.masters, name="filer")
        self.chunk_size = chunk_size
        self.replication = replication
        self.collection = collection
        # encrypt-at-rest: every uploaded chunk gets a fresh AES-256-GCM
        # key stored on its chunk record (-encryptVolumeData,
        # filer_server_handlers_write_cipher.go)
        if cipher:
            from ..util.cipher import cipher_available

            if not cipher_available():
                raise RuntimeError(
                    "-encryptVolumeData needs the cryptography library; "
                    "refusing to start a filer that would fail every "
                    "write")
        self.cipher = cipher
        self.guard = guard or Guard()
        self.filer = Filer(store)
        self.filer.on_delete_chunks = self._delete_chunks
        if persist_meta_log:
            self.filer.enable_meta_log()
        # unified tiered read-through cache (cache/): host RAM LRU,
        # optional HBM pinning (WEED_READ_CACHE_HBM_MB), and with a
        # -cacheDir the size-classed on-disk FIFO layers
        self.chunk_cache = TieredReadCache(
            mem_bytes=chunk_cache_bytes, directory=cache_dir,
            disk_bytes=cache_disk_bytes)
        self.manifest_batch = manifest_batch
        self.meta_aggregator: Optional[MetaAggregator] = None
        if peers:
            self.meta_aggregator = MetaAggregator(
                [p for p in peers if p])
        self._conf_cache: tuple[float, FilerConf] = (0.0, FilerConf())
        self._prefetch_lock = threading.Lock()
        self._prefetching: set[str] = set()
        # chunk fetches prefer the volume servers' TCP fast path (native
        # engine); servers without one are negative-cached per URL
        from ..wdclient.volume_tcp_client import VolumeTcpClient

        self._tcp_client = VolumeTcpClient()
        self._tcp_bad: dict[str, float] = {}
        # amortized fid leasing: one /dir/assign?count=N master call
        # hands out N fids locally (WEED_FILER_ASSIGN_LEASE)
        from ..wdclient import fid_lease

        self._fid_lease = fid_lease.FidLeaseCache(
            lambda n, repl, coll, t: self._assign(
                count=n, replication=repl, collection=coll, ttl=t),
            name=f"filer:{port}")
        # shared chunk I/O pool: upload fan-out, buffered-read fan-in and
        # the streaming-GET prefetch window all ride these threads instead
        # of paying a ThreadPoolExecutor spin-up per request
        self._io_pool = ThreadPoolExecutor(
            max_workers=16, thread_name_prefix="filer-io")
        self.server = RpcServer(host, port, service_name="filer")
        # prefork workers must not touch a sqlite connection that was
        # opened before the fork; new serve threads reopen lazily
        self.server.on_worker_start(
            lambda wid: self.filer.store.forget_connections())
        # observability mounts shadow the matching user paths, like the
        # /metadata/, /remote/ and /kv/ prefixes below
        self.server.add("GET", "/metrics", stats.metrics_handler)
        self.server.add("GET", "/debug/traces", tracing.traces_handler)
        faults.mount(self.server)
        profiling.mount(self.server)
        # weighted-fair front-end admission (WEED_QOS_FILER_LIMIT; 0 =
        # classify/count only, never queue)
        self.qos_gate = qos.AdmissionGate("filer",
                                          limit_env="WEED_QOS_FILER_LIMIT")
        # workload analytics sketches for this filer's chunk traffic
        self.access_recorder = access.AccessRecorder(node="filer")
        qos.mount(self.server, gate=self.qos_gate)
        events_mod.mount(self.server)
        access.mount(self.server, self.access_recorder)
        healthz.mount_health(self.server, ready=self._ready_checks)
        self.server.add("GET", "/metadata/subscribe", self._h_subscribe)
        self.server.add("GET", "/metadata/aggregate", self._h_aggregate)
        self.server.add("POST", "/remote/configure", self._h_remote_configure)
        self.server.add("GET", "/remote/list", self._h_remote_list)
        self.server.add("POST", "/remote/mount", self._h_remote_mount)
        self.server.add("POST", "/remote/unmount", self._h_remote_unmount)
        self.server.add("POST", "/remote/meta_sync", self._h_remote_meta_sync)
        self.server.add("POST", "/remote/cache", self._h_remote_cache)
        self.server.add("POST", "/remote/uncache", self._h_remote_uncache)
        # generic KV (the HTTP/JSON face of filer_grpc_server_kv.go)
        self.server.add("GET", "/kv/get", self._h_kv_get)
        self.server.add("POST", "/kv/put", self._h_kv_put)
        self.server.add("POST", "/kv/delete", self._h_kv_delete)
        self.server.default_route = self._handle
        self._stop_event = threading.Event()
        self._register_thread: Optional[threading.Thread] = None

    @property
    def address(self) -> str:
        return self.server.address

    def _ready_checks(self):
        return [("master", bool(self.masters),
                 f"masters={','.join(self.masters) or 'unknown'}"),
                ("store", self.filer.store is not None,
                 type(self.filer.store).__name__
                 if self.filer.store is not None else "no store"),
                healthz.gate_check(self.qos_gate)]

    def start(self):
        self.server.start()
        if self.meta_aggregator is not None:
            self.meta_aggregator.start()
        self._register_thread = threading.Thread(
            target=self._register_loop, daemon=True)
        self._register_thread.start()

    def stop(self):
        self._stop_event.set()
        if self.meta_aggregator is not None:
            self.meta_aggregator.stop()
        self.server.stop()
        self.filer.close()  # flush buffered change-log events
        self.filer.store.close()
        self.chunk_cache.close()  # tiered cache drops its disk segments
        self._tcp_client.close()
        self._io_pool.shutdown(wait=False)

    # -- per-path configuration (filer_conf.go, 1s refresh) ------------------
    def filer_conf(self) -> FilerConf:
        ts, conf = self._conf_cache
        now = time.time()
        if now - ts > 1.0:
            conf = FilerConf.load(self.filer)
            self._conf_cache = (now, conf)
        return conf

    def _register_loop(self):
        """Announce this filer in the master's cluster registry
        (cluster.go KeepConnected membership).  The refresh interval tracks
        the master's pulse so liveness cutoffs (pulse*3) always see us."""
        interval = 5.0
        while not self._stop_event.is_set():
            try:
                # every master keeps its own in-memory membership
                # registry, so announce to all of them — the one that
                # wins the next election must already know this filer
                reachable = 0
                for m in self.masters:
                    try:
                        r = call(m, "/cluster/register",
                                 {"type": "filer",
                                  "address": self.address}, timeout=10)
                        reachable += 1
                        interval = min(5.0,
                                       float(r.get("pulse_seconds", 5.0)))
                    except RpcError:
                        continue
                if not reachable:
                    raise RpcError("no master reachable", 503)
            except RpcError:
                pass
            self._stop_event.wait(interval)

    # -- volume cluster plumbing ---------------------------------------------
    def _assign(self, count: int = 1, replication: str = "",
                collection: str = "", ttl: str = "") -> dict:
        query = f"count={count}"
        if replication or self.replication:
            query += f"&replication={replication or self.replication}"
        if collection or self.collection:
            query += f"&collection={collection or self.collection}"
        if ttl:
            # per-path TTL rules land chunks on TTL volume layouts the
            # master expires wholesale (filer_conf.go -> assign ttl)
            query += f"&ttl={ttl}"
        return self._master_client.call(f"/dir/assign?{query}",
                                        timeout=30)

    def _lookup_urls(self, fid: str) -> list[str]:
        """All replica holders of a fid's volume, via the policy layer
        (lookup GETs retry with jittered backoff on a flaky master)."""
        vid = fid.split(",")[0]
        found = self._master_client.call(
            f"/dir/lookup?volumeId={vid}", timeout=10)
        return [l["url"] for l in found["locations"]]

    def _lookup_url(self, fid: str) -> str:
        return self._lookup_urls(fid)[0]

    def _delete_chunks(self, chunks: list[FileChunk],
                       exclude_fids: Optional[set] = None):
        # expand manifest chunks so the data chunks they list are deleted
        # too (manifest blobs themselves, at every level, are also chunks
        # to reclaim); exclude_fids applies AFTER expansion so chunks a
        # manifest lists but another entry now owns survive (multipart
        # complete hands part data chunks to the final entry)
        if has_chunk_manifest(chunks):
            try:
                chunks = resolve_chunk_manifest(
                    self._fetch_chunk, chunks, keep_manifests=True)
            except (RpcError, ValueError):
                pass  # a manifest blob is already gone; delete what we have
        if exclude_fids:
            chunks = [c for c in chunks if c.fid not in exclude_fids]
        for chunk in chunks:
            # a deleted fid must never serve stale bytes out of the
            # read cache, even if a later write reuses the fid
            self.chunk_cache.invalidate(chunk.fid, reason="delete")
            headers = {}
            if self.guard.signing:
                # filer shares security.toml; sign its own delete token
                headers["Authorization"] = "BEARER " + gen_write_jwt(
                    self.guard.signing, chunk.fid)
            try:
                call(self._lookup_url(chunk.fid), f"/{chunk.fid}",
                     method="DELETE", headers=headers, timeout=10)
            except RpcError:
                pass  # chunk may already be gone; vacuum reclaims the rest

    # -- request routing -----------------------------------------------------
    def _handle(self, method: str, req: Request):
        if qos.enabled():
            cls = qos.current_class()
            if qos.QOS_HEADER not in req.headers:
                # unclassified gateway traffic: reads are interactive,
                # writes standard; the collection is the tenant key
                cls = qos.INTERACTIVE if method in ("GET", "HEAD") \
                    else qos.STANDARD
            # an upstream gateway's X-QoS-Tenant (the S3 layer sends
            # its sigv4-derived key) wins over the collection fallback
            # so usage accounting and the token buckets agree on who
            # the tenant is, whichever door the request came through
            tenant = (req.headers.get(qos.TENANT_HEADER)
                      or req.param("collection") or self.collection or "")
            cls = qos.class_for_tenant(tenant, cls)
            release = self.qos_gate.admit(cls, tenant)
            prev = qos.set_qos(cls, tenant)
            try:
                return self._handle_inner(method, req)
            finally:
                qos.set_qos(*prev)
                release()
        return self._handle_inner(method, req)

    def _handle_inner(self, method: str, req: Request):
        path = req.path or "/"
        if method in ("GET", "HEAD"):
            stats.FilerRequestCounter.labels("read").inc()
            with stats.FilerRequestHistogram.labels("read").time():
                return self._h_read(path, req, method)
        # mutations: stamp the caller's replication signature (if any) onto
        # the resulting metadata events so sync loops can break cycles
        sig_header = req.headers.get("X-Sw-Signature", "")
        try:
            sigs = [int(s) for s in sig_header.split(",") if s.strip()] \
                if sig_header else None
        except ValueError:
            raise RpcError("malformed X-Sw-Signature header", 400)
        self.filer.set_event_signatures(sigs)
        try:
            if method in ("POST", "PUT"):
                stats.FilerRequestCounter.labels("write").inc()
                with stats.FilerRequestHistogram.labels("write").time():
                    return self._h_write(path, req)
            if method == "DELETE":
                stats.FilerRequestCounter.labels("delete").inc()
                with stats.FilerRequestHistogram.labels("delete").time():
                    return self._h_delete(path, req)
        finally:
            self.filer.set_event_signatures(None)
        raise RpcError(f"unsupported method {method}", 405)

    def _check_writable(self, path: str):
        """Reject mutation of a read-only prefix (filer_conf.go rules)."""
        if self.filer_conf().match_path(self.filer._norm(path)).read_only:
            raise RpcError(f"{path} is read-only", 403)

    # -- write (auto-chunk) --------------------------------------------------
    def _h_write(self, path: str, req: Request):
        if "tagging" in req.query:
            # add/replace Seaweed- prefixed attributes from headers
            # (PutTaggingHandler, filer_server_handlers_tagging.go:16-54)
            return self._h_put_tagging(path, req)
        move_from = req.param("mv.from")
        if move_from:
            self._check_writable(move_from)
            self._check_writable(path)
            try:
                self.filer.rename(move_from, path)
            except NotFoundError:
                raise RpcError(f"{move_from} not found", 404)
            return {"from": move_from, "to": path}

        if path.endswith("/"):
            # mkdir-style: create the directory entry
            from .entry import new_directory_entry

            self._check_writable(path)
            self.filer.create_entry(new_directory_entry(
                self.filer._norm(path)))
            return {"name": path}

        if req.param("meta") == "true":
            # metadata-only restore (fs.meta.load): recreate the entry
            # record verbatim — chunk fids must still be resolvable
            self._check_writable(path)
            entry = Entry.from_dict(req.json())
            entry.full_path = self.filer._norm(path)
            self.filer.create_entry(entry)
            return {"name": entry.name, "size": entry.size()}

        body = req.body
        mime = req.headers.get("Content-Type") or ""
        entry = self.save_bytes(path, body, mime,
                                extended=self._seaweed_headers(req))
        return {"name": entry.name, "size": len(body),
                "md5": entry.attr.md5}

    @staticmethod
    def _is_tag(name) -> bool:
        """Case-insensitive Seaweed- prefix test, used consistently by
        the write, read, response-header, and delete paths (clients and
        HTTP/2 intermediaries may lowercase header names)."""
        return isinstance(name, str) and \
            name.lower().startswith(PAIR_NAME_PREFIX.lower())

    @staticmethod
    def _seaweed_headers(req: Request) -> dict:
        """Seaweed- prefixed request headers become extended attributes
        (needle.PairNamePrefix pass-through, the tagging surface)."""
        out = {}
        for name in req.headers:
            if FilerServer._is_tag(name):
                out[name] = req.headers[name]
        return out

    def _h_put_tagging(self, path: str, req: Request):
        self._check_writable(path)
        try:
            entry = self.filer.find_entry(path)
        except NotFoundError:
            raise RpcError(f"{path} not found", 404)
        entry.extended = dict(entry.extended or {})
        entry.extended.update(self._seaweed_headers(req))
        self.filer.update_entry(entry)
        return Response(b"", 202)

    def _h_delete_tagging(self, path: str, req: Request):
        """Remove all (or the listed) Seaweed- attributes
        (DeleteTaggingHandler: ?tagging=tag1,tag2 picks specific tags)."""
        self._check_writable(path)
        try:
            entry = self.filer.find_entry(path)
        except NotFoundError:
            raise RpcError(f"{path} not found", 404)
        wanted = {t.strip().lower() for t in
                  (req.param("tagging") or "").split(",") if t.strip()}
        kept, dropped = {}, False
        for k, v in (entry.extended or {}).items():
            if self._is_tag(k) and (
                    not wanted
                    or k[len(PAIR_NAME_PREFIX):].lower() in wanted):
                dropped = True
                continue
            kept[k] = v
        if not dropped:
            return Response(b"", 304)
        entry.extended = kept
        self.filer.update_entry(entry)
        return Response(b"", 202)

    def _proxy_chunk(self, file_id: str, req: Request):
        """Relay one chunk through the filer
        (filer_server_handlers_proxy.go proxyToVolumeServer).  Range
        requests fetch the whole chunk and slice locally so the reply
        carries a correct 206 + Content-Range (forwarding the Range and
        rewrapping as 200 would mislabel a partial body as complete)."""
        url = self._lookup_url(file_id)
        try:
            data = call(url, f"/{file_id}", timeout=30)
        except RpcError as e:
            raise RpcError(f"proxy chunk {file_id}: {e}", e.status or 502)
        if not isinstance(data, (bytes, bytearray)):
            import json as _json

            data = _json.dumps(data).encode()
        data = bytes(data)
        range_header = req.headers.get("Range", "")
        if range_header.startswith("bytes="):
            size = len(data)
            spec = range_header[6:].split(",")[0]
            lo_s, _, hi_s = spec.partition("-")
            if lo_s:
                start = int(lo_s)
                stop = min(int(hi_s), size - 1) + 1 if hi_s else size
            else:  # suffix range
                start = max(0, size - int(hi_s or 0))
                stop = size
            if start >= size or stop <= start:
                raise RpcError("range not satisfiable", 416)
            return Response(
                data[start:stop], 206, "application/octet-stream",
                {"Content-Range": f"bytes {start}-{stop - 1}/{size}"})
        return Response(data, 200, "application/octet-stream")

    def _assign_leased(self, replication: str = "", collection: str = "",
                       ttl: str = "") -> dict:
        """Assign one fid, preferring the lease cache (batched master
        calls); the cache keys on the EFFECTIVE placement parameters so
        per-path rules and server defaults cannot alias."""
        from ..wdclient import fid_lease

        repl = replication or self.replication
        coll = collection or self.collection
        if fid_lease.lease_count() <= 1:
            return self._assign(replication=repl, collection=coll, ttl=ttl)
        return self._fid_lease.get(replication=repl, collection=coll,
                                   ttl=ttl)

    def _upload_assigned(self, assign: dict, payload: bytes) -> dict:
        """Push one blob at its assigned fid; TCP fast path when the
        cluster is unauthenticated, HTTP otherwise/on fallback."""
        fid, url = assign["fid"], assign["url"]
        up = None
        if not assign.get("auth"):
            # unauthenticated cluster: chunk uploads ride the native
            # fast path (the W protocol carries no JWT; the native
            # server is only up when signing is off). 307/absence falls
            # back to HTTP below.
            up = self._upload_chunk_tcp(url, fid, payload)
        if up is None:
            headers = {"Content-Type": "application/octet-stream"}
            if assign.get("auth"):
                # forward the assign-minted write JWT (jwt-enabled
                # cluster)
                headers["Authorization"] = "BEARER " + assign["auth"]
            # re-POSTing the same fid+payload dedups on the volume
            # server (unchanged-content check), so the chunk upload is
            # safely retryable and rides the breaker for its target
            up = policy.call_policy(
                url, f"/{fid}", raw=payload, method="POST",
                headers=headers, timeout=60, idempotent=True)
        return up

    def _upload_blob(self, piece: bytes, replication: str = "",
                     collection: str = "", ttl: str = "") -> FileChunk:
        """Assign a fid and upload one blob to the volume cluster; with
        -encryptVolumeData the volume only ever sees AES-GCM ciphertext
        and the per-chunk key rides the chunk record (fs.encrypt,
        filer_server_handlers_write_cipher.go)."""
        key = b""
        payload = piece
        if self.cipher:
            from ..util.cipher import encrypt, gen_cipher_key

            key = gen_cipher_key()
            payload = encrypt(piece, key)
        with tracing.span("filer.assign"):
            assign = self._assign_leased(replication=replication,
                                         collection=collection, ttl=ttl)
        try:
            up = self._upload_assigned(assign, payload)
        except RpcError as e:
            # a leased fid can go stale between master calls (volume
            # recycled/full, expired write JWT): drop the batch and
            # retry exactly once with a fresh direct assign
            if not assign.get("leased") or \
                    e.status not in (401, 403, 404, 500, 503):
                raise
            stats.FilerFidLeaseCounter.labels("stale_retry").inc()
            self._fid_lease.invalidate(reason=f"upload {e.status}")
            with tracing.span("filer.assign"):
                assign = self._assign(replication=replication,
                                      collection=collection, ttl=ttl)
            up = self._upload_assigned(assign, payload)
        # size is the PLAINTEXT length: interval math over the logical
        # file must not see the nonce/tag overhead
        return FileChunk(fid=assign["fid"], offset=0, size=len(piece),
                         etag=up.get("eTag", ""),
                         modified_ts_ns=time.time_ns(),
                         cipher_key=key)

    def save_bytes(self, path: str, body: bytes, mime: str = "",
                   extended: Optional[dict] = None) -> Entry:
        """Auto-chunked write used by both the filer HTTP API and the S3
        gateway: small bodies inline, larger ones chunk to the volume
        cluster (doPutAutoChunk, _write_upload.go); per-path rules from
        /etc/seaweedfs/filer.conf pick collection/replication and enforce
        read-only prefixes."""
        with tracing.span("filer.save", tags={"bytes": len(body)}):
            return self._save_bytes(path, body, mime, extended)

    def _save_bytes(self, path: str, body: bytes, mime: str = "",
                    extended: Optional[dict] = None) -> Entry:
        path = self.filer._norm(path)
        rule = self.filer_conf().match_path(path)
        if rule.read_only:
            raise RpcError(f"{rule.location_prefix} is read-only", 403)
        if rule.max_file_name_length and \
                len(path.rsplit("/", 1)[-1]) > rule.max_file_name_length:
            raise RpcError("file name too long", 400)
        now = time.time()
        md5 = hashlib.md5(body).hexdigest()
        ttl_sec = 0
        rule_ttl = rule.ttl
        if rule_ttl:
            from ..storage.ttl import TTL

            try:
                ttl_sec = TTL.parse(rule_ttl).minutes() * 60
            except ValueError:
                # a malformed rule must fail the SAME way for inline and
                # chunked writes: drop it everywhere, don't ship the raw
                # string to /dir/assign where parsing would 500 — but
                # say so, or 'temporary' data quietly becomes permanent
                from ..util import glog

                glog.warningf("ignoring malformed ttl %r on rule %s",
                              rule_ttl, rule.location_prefix)
                ttl_sec, rule_ttl = 0, ""
        entry = Entry(
            full_path=path,
            attr=Attr(mtime=now, crtime=now, mime=mime, md5=md5,
                      file_size=len(body), ttl_sec=ttl_sec),
            extended=extended or {})
        if len(body) <= INLINE_LIMIT:
            entry.content = body
        else:
            offsets = list(range(0, len(body), self.chunk_size))
            failed = threading.Event()
            # chunk uploads run on pool threads that do not inherit this
            # thread's trace context: hand them the parent explicitly
            parent_span = tracing.current()

            def upload(off: int) -> FileChunk:
                if failed.is_set():
                    # a sibling chunk already failed: do not keep
                    # uploading thousands of soon-to-be-orphaned blobs
                    raise RpcError("aborted: sibling chunk failed", 500)
                try:
                    piece = body[off:off + self.chunk_size]
                    with tracing.span("filer.chunk_upload",
                                      parent=parent_span,
                                      tags={"offset": off,
                                            "bytes": len(piece)}):
                        chunk = self._upload_blob(piece, rule.replication,
                                                  rule.collection, rule_ttl)
                except Exception:
                    failed.set()
                    raise
                chunk.offset = off
                return chunk

            if len(offsets) == 1:
                entry.chunks = [upload(0)]
            else:
                # upload chunks concurrently (the reference fans chunk
                # uploads out per goroutine, _write_upload.go): a large
                # body otherwise pays one serial assign+POST round trip
                # per chunk.  The shared I/O pool overlaps the
                # slice/encrypt work of later chunks with the uploads of
                # earlier ones.  On failure the fan-out aborts and the
                # already-uploaded siblings are best-effort DELETEd:
                # vacuum only compacts deleted needles, so a
                # never-referenced upload would otherwise leak until its
                # volume is removed
                futures = [self._io_pool.submit(upload, off)
                           for off in offsets]
                uploaded, first_err = [], None
                for f in futures:
                    try:
                        uploaded.append(f.result())
                    except Exception as e:  # noqa: BLE001 — re-raised
                        if first_err is None:
                            first_err = e
                if first_err is not None:
                    try:
                        self._delete_chunks(uploaded)
                    except Exception:  # noqa: BLE001 — reclamation only
                        pass
                    raise first_err
                entry.chunks = uploaded
            entry.chunks = maybe_manifestize(
                lambda blob: self._upload_blob(blob, rule.replication,
                                               rule.collection, rule_ttl),
                entry.chunks, self.manifest_batch)
        with tracing.span("filer.meta_save"):
            self.filer.create_entry(entry)
        return entry

    def _fetch_chunk(self, fid: str) -> bytes:
        """Whole-chunk fetch through the LRU chunk cache
        (reader_cache.go)."""
        from ..stats.metrics import FilerChunkCacheCounter

        t0 = time.monotonic()
        cached = self.chunk_cache.get(fid)
        if cached is not None:
            FilerChunkCacheCounter.inc(labels=("hit",))
            self._record_chunk(fid, len(cached),
                               time.monotonic() - t0, "ram")
            return cached
        FilerChunkCacheCounter.inc(labels=("miss",))
        urls = self._lookup_urls(fid)
        if not urls:
            raise RpcError(f"chunk {fid} has no locations", 404)
        jwt = (gen_read_jwt(self.guard.read_signing, fid)
               if self.guard.read_signing else "")
        data = self._fetch_chunk_tcp(urls[0], fid, jwt) if urls else None
        if data is None:
            headers = {"Authorization": "BEARER " + jwt} if jwt else {}

            def fetch(url):
                def attempt():
                    got = call(url, f"/{fid}", headers=headers,
                               timeout=60)
                    if isinstance(got, dict):
                        raise RpcError(f"chunk {fid} fetch failed", 500,
                                       addr=url, route=f"/{fid}")
                    return bytes(got)
                return attempt

            # hedged replica read: when the volume is replicated, a slow
            # holder is raced by the next replica after the adaptive p95
            # delay; on single-copy volumes this degenerates to one call
            data = policy.hedged(
                "/chunk_fetch", [fetch(u) for u in urls])
        self.chunk_cache.put(fid, data)
        self._record_chunk(fid, len(data), time.monotonic() - t0, "miss")
        return data

    def _record_chunk(self, fid: str, nbytes: int, latency_s: float,
                      tier: str):
        """Workload analytics: every chunk fetch (cache hit or volume
        round trip) heats the fid's sketch entry under the tenant the
        QoS layer attributed (X-QoS-Tenant / collection)."""
        try:
            vid = int(fid.split(",", 1)[0])
        except (ValueError, AttributeError):
            vid = 0
        self.access_recorder.record(
            "chunk", collection=self.collection or "",
            tenant=qos.current_tenant(), volume=vid, fid=fid,
            nbytes=nbytes, latency_s=latency_s,
            qos_class=qos.current_class(), cache_tier=tier)

    def _upload_chunk_tcp(self, url: str, fid: str, payload: bytes):
        """Write one chunk over the fast-path port; None to fall back
        to HTTP (no native port, replicated/TTL volume, error)."""
        import json as _json

        from ..wdclient.volume_tcp_client import VolumeTcpError

        now = time.time()
        if now < self._tcp_bad.get(url, 0.0):
            return None
        try:
            raw = self._tcp_client.write_needle(url, fid, payload)
            return _json.loads(raw)
        except VolumeTcpError as e:
            if e.status == 404:
                # the fid itself is bad (stale lease / recycled volume):
                # the port works fine — raise so the lease retry path
                # can re-assign instead of blacklisting the fast path
                raise RpcError(f"chunk {fid} upload: volume gone",
                               404) from None
            self._tcp_bad[url] = now + 60.0
            return None
        except Exception:
            # 307 already fell back to HTTP inside the client; anything
            # surfacing here means the port itself is unusable
            self._tcp_bad[url] = now + 60.0
            return None

    def _fetch_chunk_tcp(self, url: str, fid: str, jwt: str):
        """Try the volume server's TCP fast path for the chunk fetch
        (served off-GIL by the native engine when built).  Servers
        without a fast-path port — or answering 307 for this volume —
        are negative-cached so the filer pays one probe per minute, not
        two RPCs per chunk.  Returns None to fall back to HTTP; raises
        for a real miss (the chunk is gone either way)."""
        from ..wdclient.volume_tcp_client import VolumeTcpError

        now = time.time()
        if now < self._tcp_bad.get(url, 0.0):
            return None
        try:
            return self._tcp_client.read_needle(url, fid, jwt=jwt,
                                                http_fallback=False)
        except VolumeTcpError as e:
            if e.status == 404:
                raise RpcError(f"chunk {fid} not found", 404) from None
            self._tcp_bad[url] = now + 60.0
            return None
        except Exception:
            self._tcp_bad[url] = now + 60.0
            return None

    def read_bytes(self, entry: Entry, start: int = 0,
                   length: Optional[int] = None) -> bytes:
        """Reassemble [start, start+length) of an entry's content."""
        with tracing.span("filer.read",
                          tags={"bytes": length if length is not None
                                else entry.size() - start}):
            return b"".join(self._read_parts(entry, start, length))

    def read_view(self, entry: Entry, start: int = 0,
                  length: Optional[int] = None):
        """Zero-copy buffered read: ``(parts, n)`` where `parts` is a
        list of buffers (`memoryview` slices over cached chunk bytes)
        covering [start, start+n) — written straight into the socket
        send with no intermediate `bytes` concatenation."""
        with tracing.span("filer.read",
                          tags={"bytes": length if length is not None
                                else entry.size() - start}):
            parts = self._read_parts(entry, start, length)
        return parts, sum(len(p) for p in parts)

    def _read_parts(self, entry: Entry, start: int = 0,
                    length: Optional[int] = None) -> list:
        size = entry.size()
        if length is None:
            length = size - start
        if entry.content:
            return [memoryview(entry.content)[start:start + length]]
        if entry.remote_entry and not entry.chunks:
            # metadata-only remote mount entry: read through to the
            # remote object (read_remote.go; remote.cache materialises)
            from .remote_storage import read_through

            return [memoryview(read_through(self.filer, entry))
                    [start:start + length]]
        chunks = entry.chunks
        if has_chunk_manifest(chunks):
            chunks = resolve_chunk_manifest(self._fetch_chunk, chunks)
        views = read_chunk_views(chunks, start, length)
        # fetch+decrypt once per UNIQUE chunk (overwrites can split one
        # chunk into several views), concurrently like the write fan-out
        # (stream.go reads chunk views in parallel goroutines); the
        # first failure short-circuits the queued fetches
        keys = {v.fid: v.cipher_key for v in views}
        fids = list(keys)
        failed = threading.Event()
        # pool threads lack the request thread's trace AND QoS context:
        # hand both over explicitly so chunk fetches keep the caller's
        # tenant attribution (access records, outbound QoS headers)
        parent_span = tracing.current()
        qos_cls, qos_tenant = qos.current_class(), qos.current_tenant()

        def fetch(fid: str) -> bytes:
            if failed.is_set():
                raise RpcError("aborted: sibling chunk fetch failed", 500)
            try:
                with qos.qos_scope(qos_cls, qos_tenant), \
                        tracing.span("filer.chunk_fetch",
                                     parent=parent_span,
                                     tags={"fid": fid}):
                    data = self._fetch_chunk(fid)
                if keys[fid]:
                    # cache holds what the volume stores (ciphertext);
                    # plaintext exists only in flight
                    from ..util.cipher import decrypt

                    data = decrypt(data, keys[fid])
            except Exception:
                failed.set()
                raise
            return data

        if len(fids) <= 1:
            blobs = {fid: fetch(fid) for fid in fids}
        else:
            blobs = dict(zip(fids, self._io_pool.map(fetch, fids)))
        # memoryview slices over the (immutable) fetched chunk bytes:
        # the socket writes them directly, so a GET never copies the
        # payload after the fetch/decrypt step
        parts = [memoryview(blobs[v.fid])[v.offset_in_chunk:
                                          v.offset_in_chunk + v.size]
                 for v in views]
        self._maybe_prefetch(chunks, start + length)
        return parts

    def _maybe_prefetch(self, chunks, next_offset: int):
        """Sequential read-ahead (reader_cache.go MaybeCache +
        reader_pattern.go): warm the chunk that starts where this read
        ended, in the background, so streaming readers never stall on
        the next fetch."""
        nxt = next((c for c in chunks if c.offset == next_offset), None)
        if nxt is None or self.chunk_cache.get(nxt.fid) is not None:
            return
        with self._prefetch_lock:
            if nxt.fid in self._prefetching or \
                    len(self._prefetching) >= 4:  # bounded look-ahead
                return
            self._prefetching.add(nxt.fid)
        qos_cls, qos_tenant = qos.current_class(), qos.current_tenant()

        def fetch():
            try:
                # the read-ahead is caused by this reader: bill it to
                # the same tenant the triggering request carried
                with qos.qos_scope(qos_cls, qos_tenant):
                    self._fetch_chunk(nxt.fid)
            except RpcError:
                pass  # a miss here is only a lost optimisation
            finally:
                with self._prefetch_lock:
                    self._prefetching.discard(nxt.fid)

        threading.Thread(target=fetch, daemon=True,
                         name=f"prefetch-{nxt.fid}").start()

    # -- streamed read -------------------------------------------------------
    def read_stream(self, entry: Entry, start: int = 0,
                    length: Optional[int] = None
                    ) -> Optional[tuple[Iterator[bytes], int]]:
        """Bounded-window streaming read: a (chunk iterator, byte count)
        pair for [start, start+length), or None when the buffered path
        is the right answer (inline content, remote mounts, single-chunk
        bodies, or streaming disabled via WEED_FILER_PREFETCH_CHUNKS=0).

        Up to K chunk fetches run ahead of the reply cursor on the
        shared I/O pool — chunks complete out of order, bytes are
        yielded in order — so first-byte latency is one chunk fetch
        regardless of object size.  The first chunk is fetched before
        this returns: common failures (missing chunk, no locations)
        still surface as a proper error status instead of a truncated
        200."""
        if prefetch_chunks() <= 0:
            return None
        size = entry.size()
        if length is None:
            length = size - start
        if entry.content or not entry.chunks or \
                (entry.remote_entry and not entry.chunks):
            return None
        chunks = entry.chunks
        if has_chunk_manifest(chunks):
            chunks = resolve_chunk_manifest(self._fetch_chunk, chunks)
        views = read_chunk_views(chunks, start, length)
        if len({v.fid for v in views}) <= 1:
            return None  # nothing to pipeline; buffered path is simpler
        span = tracing.start("filer.stream", tags={"bytes": length})
        gen = self._stream_views(views, span)
        try:
            first = next(gen)
        except StopIteration:
            span.finish()
            return iter(()), 0
        except BaseException:
            span.finish(status="error")
            raise

        def run():
            try:
                yield first
                yield from gen
            finally:
                span.finish()

        return run(), length

    def _stream_views(self, views, parent_span) -> Iterator[bytes]:
        keys = {v.fid: v.cipher_key for v in views}
        order = list(keys)  # unique fids in first-use order
        pos = {fid: i for i, fid in enumerate(order)}
        last_use: dict[str, int] = {}
        for i, v in enumerate(views):
            last_use[v.fid] = i
        window = max(1, prefetch_chunks())
        # captured at generator start (inside the request's QoS scope);
        # window fetches run on pool threads after the dispatch scope
        # has been restored, so they need the pair pinned explicitly
        qos_cls, qos_tenant = qos.current_class(), qos.current_tenant()

        def fetch(fid: str) -> bytes:
            with qos.qos_scope(qos_cls, qos_tenant), \
                    tracing.span("filer.chunk_fetch", parent=parent_span,
                                 tags={"fid": fid}):
                data = self._fetch_chunk(fid)
            if keys[fid]:
                from ..util.cipher import decrypt

                data = decrypt(data, keys[fid])
            return data

        futures: dict[str, object] = {}
        submitted = 0

        def pump(cursor: int):
            # keep fetches in flight for the window ahead of the cursor
            nonlocal submitted
            while submitted < len(order) and submitted <= cursor + window:
                fid = order[submitted]
                futures[fid] = self._io_pool.submit(fetch, fid)
                submitted += 1

        blobs: dict[str, bytes] = {}
        try:
            for i, v in enumerate(views):
                cursor = pos[v.fid]
                pump(cursor)
                stats.FilerPrefetchWindowGauge.set(
                    submitted - cursor - 1)
                blob = blobs.get(v.fid)
                if blob is None:
                    blob = futures.pop(v.fid).result()
                    blobs[v.fid] = blob
                yield blob[v.offset_in_chunk:v.offset_in_chunk + v.size]
                if last_use[v.fid] == i:
                    blobs.pop(v.fid, None)  # free as the cursor passes
        finally:
            stats.FilerPrefetchWindowGauge.set(0)
            for f in futures.values():
                f.cancel()

    # -- read ----------------------------------------------------------------
    def _h_read(self, path: str, req: Request, method: str):
        proxy_chunk = req.param("proxyChunkId")
        if proxy_chunk:
            # direct filer->volume chunk relay for clients that cannot
            # reach volume servers (filer_server_handlers_proxy.go)
            return self._proxy_chunk(proxy_chunk, req)
        try:
            with tracing.span("filer.lookup"):
                entry = self.filer.find_entry(path)
        except NotFoundError:
            raise RpcError(f"{path} not found", 404)
        if "tagging" in req.query:
            # object tags as JSON (the Seaweed- extended attributes;
            # write with PUT ?tagging, remove with DELETE ?tagging)
            return {k: v for k, v in (entry.extended or {}).items()
                    if self._is_tag(k)}
        if entry.is_directory:
            if "text/html" in (req.headers.get("Accept") or ""):
                return self._render_ui(entry)  # browser surface
            return self._list_directory(entry, req)

        size = entry.size()
        start, length = 0, size
        status = 200
        headers = {}
        range_header = req.headers.get("Range")
        if range_header and range_header.startswith("bytes="):
            spec = range_header[6:].split(",")[0]
            lo_s, _, hi_s = spec.partition("-")
            lo = int(lo_s) if lo_s else None
            hi = int(hi_s) if hi_s else None
            if lo is None:  # suffix range: last N bytes
                start = max(0, size - (hi or 0))
                length = size - start
            else:
                start = lo
                length = (min(hi, size - 1) - lo + 1) if hi is not None \
                    else size - lo
            if start >= size or length <= 0:
                raise RpcError("range not satisfiable", 416)
            status = 206
            headers["Content-Range"] = \
                f"bytes {start}-{start + length - 1}/{size}"

        if entry.attr.mime:
            content_type = entry.attr.mime
        else:
            content_type = "application/octet-stream"
        headers["Etag"] = f'"{entry.attr.md5 or etag_of_chunks(entry.chunks)}"'
        headers["Accept-Ranges"] = "bytes"
        for k, v in (entry.extended or {}).items():
            # tags ride responses as Seaweed- headers (the reference's
            # read path exposes PairNamePrefix attributes this way)
            if self._is_tag(k) and isinstance(v, str):
                headers[k] = v
        if method == "HEAD":
            headers["Content-Length"] = str(length)
            return Response(b"", status, content_type, headers)

        zero = self._sendfile_read(entry, start, length, status,
                                   content_type, headers)
        if zero is not None:
            return zero
        streamed = self.read_stream(entry, start, length)
        if streamed is not None:
            body_iter, n = streamed
            # a known length keeps the reply on raw writes (no chunked
            # framing) while _reply_stream flushes chunk by chunk
            headers["Content-Length"] = str(n)
            stats.FilerStreamedReadCounter.labels("streamed").inc()
            return Response(body_iter, status, content_type, headers)
        # buffered path: memoryview parts over cached chunk bytes go
        # straight into the socket send — no b"".join copy
        parts, n = self.read_view(entry, start, length)
        headers["Content-Length"] = str(n)
        stats.FilerStreamedReadCounter.labels("zero_copy").inc()
        body = parts[0] if len(parts) == 1 else iter(parts)
        return Response(body, status, content_type, headers)

    def _sendfile_read(self, entry: Entry, start: int, length: int,
                       status: int, content_type: str, headers: dict):
        """Zero-copy GET for the common hot case: a single-chunk,
        cipher-free entry whose chunk sits in the on-disk cache tier —
        the bytes go disk cache -> socket via sendfile without ever
        entering Python.  Returns None to fall back to the streamed /
        buffered paths (RAM-cached chunks stay on those: an in-memory
        memoryview write is already zero-copy for them)."""
        if not sendfile_enabled() or entry.content \
                or len(entry.chunks) != 1:
            return None
        c = entry.chunks[0]
        if c.cipher_key or c.is_chunk_manifest or c.offset != 0:
            return None
        if start < 0 or start + length > c.size:
            return None
        sl = self.chunk_cache.get_slice(c.fid)
        if sl is None:
            return None
        fd, off, ln = sl
        if ln != c.size:  # cached bytes disagree with metadata: stale
            os.close(fd)
            return None
        headers["Content-Length"] = str(length)
        stats.FilerStreamedReadCounter.labels("sendfile").inc()
        return Response(FileSlice(fd, off + start, length, close_fd=True),
                        status, content_type, headers)

    def _list_directory(self, entry: Entry, req: Request):
        limit = int(req.param("limit", "100"))
        last = req.param("lastFileName", "") or ""
        entries = self.filer.list_directory(
            entry.full_path, start_file=last, limit=limit,
            prefix=req.param("prefix", "") or "",
            name_pattern=req.param("namePattern", "") or "",
            name_pattern_exclude=req.param("namePatternExclude", "") or "")
        if req.param("metadata") == "true":
            # full entry dicts incl. chunks (fs.meta.cat / fsck surface)
            rendered = [e.to_dict() for e in entries]
        else:
            rendered = [
                {
                    "FullPath": e.full_path,
                    "Mtime": e.attr.mtime,
                    "Mode": e.attr.mode,
                    "Mime": e.attr.mime,
                    "FileSize": e.size(),
                    "IsDirectory": e.is_directory,
                } for e in entries
            ]
        return {
            "Path": entry.full_path,
            "Entries": rendered,
            "Limit": limit,
            "LastFileName": entries[-1].name if entries else "",
            "ShouldDisplayLoadMore": len(entries) == limit,
        }

    # -- delete --------------------------------------------------------------
    def _h_delete(self, path: str, req: Request):
        if "tagging" in req.query:
            return self._h_delete_tagging(path, req)
        self._check_writable(path)
        recursive = req.param("recursive") == "true"
        try:
            self.filer.delete_entry(
                path, recursive=recursive,
                delete_chunks=req.param("skipChunkDelete") != "true")
        except NotFoundError:
            raise RpcError(f"{path} not found", 404)
        except ValueError as e:
            raise RpcError(str(e), 400)
        return Response(b"", 204)

    def _render_ui(self, entry: Entry) -> Response:
        """Browser UI (server/filer_ui): served when a directory GET asks
        for text/html — a dedicated /ui route would shadow a stored file
        at that path, so content negotiation picks the surface instead."""
        from . import remote_storage as rs
        from ..util import ui

        entries = self.filer.list_directory(entry.full_path, limit=1000)
        prefix = entry.full_path.rstrip("/")
        listing = ui.table(
            ("name", "type", "size"),
            [(f"{prefix}/{e.name}",
              "dir" if e.is_directory else (e.attr.mime or "file"),
              "-" if e.is_directory else e.size()) for e in entries])
        mappings = rs.read_mount_mappings(self.filer)
        body = ui.page(
            f"SeaweedFS-TPU Filer {self.address} — {entry.full_path}",
            ui.section("Filer", ui.kv_table({
                "master": self.master_address,
                "store": type(self.filer.store).__name__,
                "chunk size": self.chunk_size,
                "metadata log": "persisted"
                if self.filer.meta_log_enabled else "in-memory",
                "peers": ", ".join(self.meta_aggregator.peers)
                if self.meta_aggregator else "-",
            })),
            ui.section(f"Listing of {entry.full_path}", listing),
            ui.section("Remote mounts", ui.table(
                ("directory", "remote"), sorted(mappings.items()))),
        )
        return Response(body, content_type="text/html; charset=utf-8")

    # -- remote storage mounts (weed/filer/remote_storage.go; shell
    # remote.* commands drive these endpoints) -------------------------------
    def _h_remote_configure(self, req: Request):
        from ..remote_storage import RemoteConf
        from . import remote_storage as rs

        p = req.json()
        if p.get("delete"):
            rs.delete_remote_conf(self.filer, p["name"])
            return {}
        conf = RemoteConf.from_dict(p)
        if conf.type not in ("s3", "local"):
            raise RpcError(f"unknown remote type {conf.type!r}", 400)
        rs.save_remote_conf(self.filer, conf)
        return conf.to_dict()

    def _h_remote_list(self, req: Request):
        from . import remote_storage as rs

        return {
            "storages": [c.to_dict()
                         for c in rs.list_remote_confs(self.filer)],
            "mappings": rs.read_mount_mappings(self.filer),
        }

    def _h_remote_mount(self, req: Request):
        from ..remote_storage import RemoteLocation
        from . import remote_storage as rs

        p = req.json()
        directory, remote = p["dir"], p["remote"]
        try:  # validate the storage name before touching any state
            rs.load_remote_conf(self.filer,
                                RemoteLocation.parse(remote).name)
        except NotFoundError as e:
            raise RpcError(str(e), 404)
        self.filer._ensure_parents(directory.rstrip("/") or "/")
        from .entry import new_directory_entry

        try:
            self.filer.find_entry(directory.rstrip("/"))
        except NotFoundError:
            self.filer.create_entry(
                new_directory_entry(directory.rstrip("/")))
        rs.insert_mount_mapping(self.filer, directory, remote)
        synced = rs.sync_metadata(self.filer, directory)
        return {"dir": directory, "remote": remote, "synced": synced}

    def _h_remote_unmount(self, req: Request):
        from . import remote_storage as rs

        directory = req.json()["dir"].rstrip("/") or "/"
        if directory not in rs.read_mount_mappings(self.filer):
            raise RpcError(f"{directory} is not mounted", 404)
        rs.delete_mount_mapping(self.filer, directory)
        try:
            self.filer.delete_entry(directory, recursive=True)
        except NotFoundError:
            pass
        return {}

    def _h_remote_meta_sync(self, req: Request):
        from . import remote_storage as rs

        directory = req.json()["dir"]
        try:
            return {"synced": rs.sync_metadata(self.filer, directory)}
        except NotFoundError as e:
            raise RpcError(str(e), 404)

    def _walk_remote_entries(self, directory: str):
        stack = [directory.rstrip("/") or "/"]
        while stack:
            d = stack.pop()
            for e in self.filer.list_directory(d, limit=100000):
                if e.is_directory:
                    stack.append(e.full_path)
                elif e.remote_entry:
                    yield e

    def _h_remote_cache(self, req: Request):
        """Materialise remote objects locally (command_remote_cache.go).

        Large objects are fetched BY THE VOLUME SERVER (the
        FetchAndWriteNeedle analogue, /admin/remote/fetch_write —
        volume_grpc_remote.go:16-83): the filer assigns fids and sends
        the remote conf+location+range; object bytes flow external
        store -> volume server, never through this process.  Small
        objects (inline threshold) and volume servers without the RPC
        fall back to filer-transit."""
        from . import remote_storage as rs
        from ..storage.types import parse_file_id

        directory = req.json()["dir"]
        cached = 0
        for entry in self._walk_remote_entries(directory):
            if entry.chunks or entry.content:
                continue  # already cached
            size = int((entry.remote_entry or {}).get("remote_size", 0))
            # cipher-enabled filers keep the transit path: volumes must
            # only ever see ciphertext, which the volume server cannot
            # produce from the plaintext remote object
            mapped = rs.mapped_location(self.filer, entry.full_path) \
                if size > INLINE_LIMIT and not self.cipher else None
            if mapped is not None:
                _, loc = mapped
                conf = rs.load_remote_conf(self.filer, loc.name)
                chunks = []
                try:
                    for off in range(0, size, self.chunk_size):
                        clen = min(self.chunk_size, size - off)
                        assign = self._assign()
                        vid, nid, cookie = parse_file_id(assign["fid"])
                        up = call(
                            assign["url"], "/admin/remote/fetch_write",
                            {"volume": vid, "needle_id": nid,
                             "cookie": cookie,
                             "remote_conf": conf.to_dict(),
                             "remote_location": str(loc),
                             "offset": off, "size": clen}, timeout=300)
                        chunks.append(FileChunk(
                            fid=assign["fid"], offset=off,
                            size=int(up["size"]),
                            etag=up.get("eTag", ""),
                            modified_ts_ns=time.time_ns()))
                    entry.chunks = chunks
                    entry.attr.file_size = size
                    # no whole-object md5: the bytes never transited
                    # this process — readers fall back to the chunk
                    # etags (etag_of_chunks), like any chunked upload
                    self.filer.create_entry(entry)
                    cached += 1
                    continue
                except RpcError:
                    # older volume server / transient failure: reclaim
                    # the needles already written, then fall back to
                    # filer-transit for this entry
                    if chunks:
                        try:
                            self._delete_chunks(chunks)
                        except Exception:
                            pass
            data = rs.read_through(self.filer, entry)
            entry.attr.file_size = len(data)
            entry.attr.md5 = hashlib.md5(data).hexdigest()
            if len(data) <= INLINE_LIMIT:
                entry.content = data
            else:
                offset = 0
                while offset < len(data):
                    piece = data[offset:offset + self.chunk_size]
                    chunk = self._upload_blob(piece)
                    chunk.offset = offset
                    entry.chunks.append(chunk)
                    offset += len(piece)
            self.filer.create_entry(entry)
            cached += 1
        return {"cached": cached}

    def _h_remote_uncache(self, req: Request):
        """Drop local copies, keep remote metadata
        (command_remote_uncache.go)."""
        directory = req.json()["dir"]
        uncached = 0
        for entry in self._walk_remote_entries(directory):
            if not entry.chunks and not entry.content:
                continue
            if entry.chunks:
                self._delete_chunks(entry.chunks)
            entry.chunks = []
            entry.content = b""
            self.filer.create_entry(entry)
            uncached += 1
        return {"uncached": uncached}

    # -- metadata subscription ----------------------------------------------
    # -- generic KV (filer_grpc_server_kv.go over the HTTP substrate) --------
    @staticmethod
    def _b64(value: str, urlsafe: bool = False) -> bytes:
        import base64
        import binascii

        try:
            decode = base64.urlsafe_b64decode if urlsafe \
                else base64.b64decode
            return decode(value or "")
        except (binascii.Error, ValueError):
            raise RpcError("malformed base64", 400)

    def _h_kv_get(self, req: Request):
        import base64

        key = self._b64(req.param("key", "") or "", urlsafe=True)
        if not key:
            raise RpcError("missing key", 400)
        value = self.filer.kv_get(key)
        return {"value": base64.b64encode(value).decode()
                if value is not None else None}

    def _h_kv_put(self, req: Request):
        body = req.json()
        key = self._b64(body.get("key", ""))
        if not key:
            raise RpcError("missing key", 400)
        self.filer.kv_put(key, self._b64(body.get("value", "")))
        return {}

    def _h_kv_delete(self, req: Request):
        key = self._b64(req.json().get("key", ""))
        if not key:
            raise RpcError("missing key", 400)
        self.filer.kv_delete(key)
        return {}

    def _h_subscribe(self, req: Request):
        since = int(req.param("since", "0"))
        prefix = req.param("pathPrefix", "/") or "/"
        return {"events": self.filer.subscribe_metadata(since, prefix)}

    def _h_aggregate(self, req: Request):
        """Merged peer feed (meta_aggregator.go MetaAggregator)."""
        since = int(req.param("since", "0"))
        events = self.filer.subscribe_metadata(since)
        if self.meta_aggregator is not None:
            events = sorted(events + self.meta_aggregator.events(since),
                            key=lambda e: e["ts_ns"])
        return {"events": events}
