"""Remote-storage mounts on the filer namespace.

Parity with weed/filer/remote_storage.go + remote_mapping.go +
read_remote.go and the shell's remote.* commands: storage configurations
and the dir->remote-location mapping persist inside the filer under
/etc/remote/, mounted directories hold metadata-only entries stamped
with a remote_entry, reads through such an entry proxy to the remote
object, and cache/uncache materialise or drop local chunk copies.
"""

from __future__ import annotations

import json
import time
from typing import Optional

from ..remote_storage import (RemoteConf, RemoteLocation, RemoteObject,
                              RemoteStorageClient, make_remote_client)
from .entry import Attr, Entry
from .filer import Filer
from .filer_store import NotFoundError

REMOTE_CONF_DIR = "/etc/remote"
MOUNT_MAPPING_PATH = f"{REMOTE_CONF_DIR}/mount.mapping"


def _read_json(filer: Filer, path: str) -> dict:
    try:
        entry = filer.find_entry(path)
    except NotFoundError:
        return {}
    try:
        return json.loads(entry.content.decode())
    except ValueError:
        return {}


def _write_json(filer: Filer, path: str, doc: dict):
    now = time.time()
    filer.create_entry(Entry(
        full_path=path,
        attr=Attr(mtime=now, crtime=now, mime="application/json",
                  file_size=0),
        content=json.dumps(doc, indent=2).encode()))


# -- storage configurations (remote.configure) -------------------------------

def save_remote_conf(filer: Filer, conf: RemoteConf):
    _write_json(filer, f"{REMOTE_CONF_DIR}/{conf.name}.conf",
                conf.to_dict())


def load_remote_conf(filer: Filer, name: str) -> RemoteConf:
    doc = _read_json(filer, f"{REMOTE_CONF_DIR}/{name}.conf")
    if not doc:
        raise NotFoundError(f"remote storage {name!r} not configured")
    return RemoteConf.from_dict(doc)


def delete_remote_conf(filer: Filer, name: str):
    try:
        filer.delete_entry(f"{REMOTE_CONF_DIR}/{name}.conf")
    except NotFoundError:
        pass


def list_remote_confs(filer: Filer) -> list[RemoteConf]:
    try:
        entries = filer.list_directory(REMOTE_CONF_DIR, limit=1000)
    except NotFoundError:
        return []
    out = []
    for e in entries:
        if e.full_path.endswith(".conf"):
            doc = _read_json(filer, e.full_path)
            if doc:
                out.append(RemoteConf.from_dict(doc))
    return out


def client_for(filer: Filer, name: str) -> RemoteStorageClient:
    return make_remote_client(load_remote_conf(filer, name))


# -- mount mapping (remote.mount / remote.unmount) ---------------------------

def read_mount_mappings(filer: Filer) -> dict[str, str]:
    """dir -> 'name/bucket/path'."""
    return _read_json(filer, MOUNT_MAPPING_PATH).get("mappings", {})


def insert_mount_mapping(filer: Filer, directory: str, remote: str):
    mappings = read_mount_mappings(filer)
    mappings[directory.rstrip("/") or "/"] = remote
    _write_json(filer, MOUNT_MAPPING_PATH, {"mappings": mappings})


def delete_mount_mapping(filer: Filer, directory: str):
    mappings = read_mount_mappings(filer)
    mappings.pop(directory.rstrip("/") or "/", None)
    _write_json(filer, MOUNT_MAPPING_PATH, {"mappings": mappings})


def mapped_location(filer: Filer,
                    path: str) -> Optional[tuple[str, RemoteLocation]]:
    """Find the mount covering `path`; returns (mount_dir, remote loc of
    this exact path) or None."""
    mappings = read_mount_mappings(filer)
    best = ""
    for directory in mappings:
        if (path == directory or path.startswith(
                directory.rstrip("/") + "/")) and \
                len(directory) > len(best):
            best = directory
    if not best:
        return None
    root = RemoteLocation.parse(mappings[best])
    rel = path[len(best):].lstrip("/")
    loc = RemoteLocation(root.name, root.bucket,
                         (root.path.rstrip("/") + "/" + rel)
                         if rel else root.path)
    return best, loc


# -- metadata sync (remote.mount initial pull, remote.meta.sync) -------------

def sync_metadata(filer: Filer, directory: str) -> int:
    """Pull the remote listing into metadata-only entries under the
    mount (remote.meta.sync / the pull phase of remote.mount)."""
    directory = directory.rstrip("/") or "/"
    mappings = read_mount_mappings(filer)
    if directory not in mappings:
        raise NotFoundError(f"{directory} is not a remote mount")
    loc = RemoteLocation.parse(mappings[directory])
    client = client_for(filer, loc.name)
    count = 0
    now = time.time()
    seen: set[str] = set()
    for obj in client.traverse(loc):
        full = f"{directory}/{obj.key}"
        seen.add(full)
        try:
            existing = filer.find_entry(full)
            remote = existing.remote_entry
            if remote and remote.get("remote_e_tag") == obj.etag \
                    and remote.get("remote_size") == obj.size:
                continue  # unchanged
        except NotFoundError:
            existing = None
        entry = Entry(
            full_path=full,
            attr=Attr(mtime=obj.mtime or now, crtime=obj.mtime or now,
                      file_size=obj.size),
            remote_entry=obj.to_remote_entry(loc.name))
        if existing is not None and existing.chunks:
            # local cache out of date relative to the remote: drop it
            entry.chunks = []
        filer.create_entry(entry)
        count += 1
    # reconcile deletions: a metadata-only entry (never locally written)
    # whose remote object vanished must go too, or reads through it 404
    stack = [directory]
    while stack:
        d = stack.pop()
        try:
            children = filer.list_directory(d, limit=100000)
        except NotFoundError:
            continue
        for child in children:
            if child.is_directory:
                stack.append(child.full_path)
            elif child.remote_entry and not child.chunks \
                    and not child.content and child.full_path not in seen:
                filer.delete_entry(child.full_path)
                count += 1
    return count


def read_through(filer: Filer, entry: Entry) -> bytes:
    """Serve a metadata-only remote entry by fetching the remote object
    (read_remote.go ReadRemote)."""
    found = mapped_location(filer, entry.full_path)
    if found is None:
        raise NotFoundError(f"{entry.full_path} has no remote mount")
    _, loc = found
    return client_for(filer, loc.name).read_file(loc)
