"""Pluggable filer metadata stores.

Parity with weed/filer/filerstore.go:21-44: insert/update/find/delete/
delete-children/list-directory over Entries.  The reference ships leveldb
(3 variants) and redis backends; here the in-process equivalents are a
dict-backed MemoryStore and a persistent SqliteStore (stdlib sqlite3 —
this image has no leveldb binding), both behind the same interface and
exercised by the shared conformance tests (tests/test_filer.py), matching
the reference's per-store test harness (filer/store_test/)."""

from __future__ import annotations

import json
import sqlite3
import threading
from typing import Iterator, Optional

from .entry import Entry


class FilerStoreError(Exception):
    pass


class NotFoundError(FilerStoreError):
    pass


class FilerStore:
    """Interface — all paths are absolute, "/"-separated, no trailing "/"."""

    def insert_entry(self, entry: Entry):
        raise NotImplementedError

    def update_entry(self, entry: Entry):
        raise NotImplementedError

    def find_entry(self, path: str) -> Entry:
        raise NotImplementedError

    def delete_entry(self, path: str):
        raise NotImplementedError

    def delete_folder_children(self, path: str):
        raise NotImplementedError

    def list_directory(self, dir_path: str, start_file: str = "",
                       include_start: bool = False, limit: int = 1024,
                       prefix: str = "") -> list[Entry]:
        raise NotImplementedError

    def close(self):
        pass


class MemoryStore(FilerStore):
    def __init__(self):
        # dir path -> {name -> Entry}
        self._dirs: dict[str, dict[str, Entry]] = {}
        self._lock = threading.RLock()

    def insert_entry(self, entry: Entry):
        with self._lock:
            self._dirs.setdefault(entry.parent, {})[entry.name] = entry

    update_entry = insert_entry

    def find_entry(self, path: str) -> Entry:
        if path == "/":
            from .entry import new_directory_entry

            return new_directory_entry("/")
        parent, name = path.rsplit("/", 1)
        with self._lock:
            entry = self._dirs.get(parent or "/", {}).get(name)
            if entry is None:
                raise NotFoundError(path)
            return entry

    def delete_entry(self, path: str):
        parent, name = path.rsplit("/", 1)
        with self._lock:
            self._dirs.get(parent or "/", {}).pop(name, None)

    def delete_folder_children(self, path: str):
        with self._lock:
            for d in [d for d in self._dirs
                      if d == path or d.startswith(path.rstrip("/") + "/")]:
                del self._dirs[d]

    def list_directory(self, dir_path: str, start_file: str = "",
                       include_start: bool = False, limit: int = 1024,
                       prefix: str = "") -> list[Entry]:
        with self._lock:
            names = sorted(self._dirs.get(dir_path, {}))
            out = []
            for name in names:
                if prefix and not name.startswith(prefix):
                    continue
                if start_file:
                    if name < start_file:
                        continue
                    if name == start_file and not include_start:
                        continue
                out.append(self._dirs[dir_path][name])
                if len(out) >= limit:
                    break
            return out


class SqliteStore(FilerStore):
    """Persistent store: one table keyed by (dir, name)."""

    def __init__(self, path: str):
        self._path = path
        self._local = threading.local()
        with self._conn() as c:
            c.execute(
                "CREATE TABLE IF NOT EXISTS filemeta ("
                " dir TEXT NOT NULL, name TEXT NOT NULL,"
                " meta TEXT NOT NULL, PRIMARY KEY (dir, name))")
            c.execute("CREATE INDEX IF NOT EXISTS idx_dir"
                      " ON filemeta (dir, name)")

    def _conn(self) -> sqlite3.Connection:
        conn = getattr(self._local, "conn", None)
        if conn is None:
            conn = sqlite3.connect(self._path)
            conn.isolation_level = None  # autocommit
            self._local.conn = conn
        return conn

    def insert_entry(self, entry: Entry):
        self._conn().execute(
            "INSERT OR REPLACE INTO filemeta (dir, name, meta)"
            " VALUES (?, ?, ?)",
            (entry.parent, entry.name, json.dumps(entry.to_dict())))

    update_entry = insert_entry

    def find_entry(self, path: str) -> Entry:
        if path == "/":
            from .entry import new_directory_entry

            return new_directory_entry("/")
        parent, name = path.rsplit("/", 1)
        row = self._conn().execute(
            "SELECT meta FROM filemeta WHERE dir = ? AND name = ?",
            (parent or "/", name)).fetchone()
        if row is None:
            raise NotFoundError(path)
        return Entry.from_dict(json.loads(row[0]))

    def delete_entry(self, path: str):
        parent, name = path.rsplit("/", 1)
        self._conn().execute(
            "DELETE FROM filemeta WHERE dir = ? AND name = ?",
            (parent or "/", name))

    @staticmethod
    def _escape_like(s: str) -> str:
        return (s.replace("\\", "\\\\").replace("%", "\\%")
                .replace("_", "\\_"))

    def delete_folder_children(self, path: str):
        base = path.rstrip("/")
        self._conn().execute(
            "DELETE FROM filemeta WHERE dir = ? OR dir LIKE ? ESCAPE '\\'",
            (base or "/", self._escape_like(base + "/") + "%"))

    def list_directory(self, dir_path: str, start_file: str = "",
                       include_start: bool = False, limit: int = 1024,
                       prefix: str = "") -> list[Entry]:
        op = ">=" if include_start else ">"
        sql = (f"SELECT meta FROM filemeta WHERE dir = ? AND name {op} ?")
        args: list = [dir_path, start_file]
        if prefix:
            sql += " AND name LIKE ? ESCAPE '\\'"
            args.append(self._escape_like(prefix) + "%")
        sql += " ORDER BY name LIMIT ?"
        args.append(limit)
        rows = self._conn().execute(sql, args).fetchall()
        return [Entry.from_dict(json.loads(r[0])) for r in rows]

    def close(self):
        conn = getattr(self._local, "conn", None)
        if conn is not None:
            conn.close()
            self._local.conn = None
