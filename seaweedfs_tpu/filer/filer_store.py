"""Pluggable filer metadata stores.

Parity with weed/filer/filerstore.go:21-44: insert/update/find/delete/
delete-children/list-directory over Entries.  The reference ships leveldb
(3 variants) and redis backends; here the in-process equivalents are a
dict-backed MemoryStore and a persistent SqliteStore (stdlib sqlite3 —
this image has no leveldb binding), both behind the same interface and
exercised by the shared conformance tests (tests/test_filer.py), matching
the reference's per-store test harness (filer/store_test/)."""

from __future__ import annotations

import json
import sqlite3
import threading
from typing import Iterator, Optional

from .entry import Entry


class FilerStoreError(Exception):
    pass


class NotFoundError(FilerStoreError):
    pass


class FilerStore:
    """Interface — all paths are absolute, "/"-separated, no trailing "/"."""

    def insert_entry(self, entry: Entry):
        raise NotImplementedError

    def update_entry(self, entry: Entry):
        raise NotImplementedError

    def find_entry(self, path: str) -> Entry:
        raise NotImplementedError

    def delete_entry(self, path: str):
        raise NotImplementedError

    def delete_folder_children(self, path: str):
        raise NotImplementedError

    def list_directory(self, dir_path: str, start_file: str = "",
                       include_start: bool = False, limit: int = 1024,
                       prefix: str = "") -> list[Entry]:
        raise NotImplementedError

    def close(self):
        pass

    def forget_connections(self):
        """Drop (without closing) any backend handle opened before a
        prefork fork().  Sqlite connections must not be used from two
        processes; serving threads in the child are brand-new threads
        that lazily open their own, so dropping the reference suffices.
        Closing the inherited handle from the child would run sqlite
        shutdown against the parent's live database, so leak it."""


class MemoryStore(FilerStore):
    def __init__(self):
        # dir path -> {name -> Entry}
        self._dirs: dict[str, dict[str, Entry]] = {}
        self._lock = threading.RLock()

    def insert_entry(self, entry: Entry):
        with self._lock:
            self._dirs.setdefault(entry.parent, {})[entry.name] = entry

    update_entry = insert_entry

    def find_entry(self, path: str) -> Entry:
        if path == "/":
            from .entry import new_directory_entry

            return new_directory_entry("/")
        parent, name = path.rsplit("/", 1)
        with self._lock:
            entry = self._dirs.get(parent or "/", {}).get(name)
            if entry is None:
                raise NotFoundError(path)
            return entry

    def delete_entry(self, path: str):
        parent, name = path.rsplit("/", 1)
        with self._lock:
            self._dirs.get(parent or "/", {}).pop(name, None)

    def delete_folder_children(self, path: str):
        with self._lock:
            for d in [d for d in self._dirs
                      if d == path or d.startswith(path.rstrip("/") + "/")]:
                del self._dirs[d]

    def list_directory(self, dir_path: str, start_file: str = "",
                       include_start: bool = False, limit: int = 1024,
                       prefix: str = "") -> list[Entry]:
        with self._lock:
            names = sorted(self._dirs.get(dir_path, {}))
            out = []
            for name in names:
                if prefix and not name.startswith(prefix):
                    continue
                if start_file:
                    if name < start_file:
                        continue
                    if name == start_file and not include_start:
                        continue
                out.append(self._dirs[dir_path][name])
                if len(out) >= limit:
                    break
            return out


class SqliteStore(FilerStore):
    """Persistent store: one table keyed by (dir, name)."""

    def __init__(self, path: str):
        self._path = path
        self._local = threading.local()
        with self._conn() as c:
            c.execute(
                "CREATE TABLE IF NOT EXISTS filemeta ("
                " dir TEXT NOT NULL, name TEXT NOT NULL,"
                " meta TEXT NOT NULL, PRIMARY KEY (dir, name))")
            c.execute("CREATE INDEX IF NOT EXISTS idx_dir"
                      " ON filemeta (dir, name)")

    def _conn(self) -> sqlite3.Connection:
        conn = getattr(self._local, "conn", None)
        if conn is None:
            conn = sqlite3.connect(self._path)
            conn.isolation_level = None  # autocommit
            self._local.conn = conn
        return conn

    def forget_connections(self):
        self._local = threading.local()

    def insert_entry(self, entry: Entry):
        self._conn().execute(
            "INSERT OR REPLACE INTO filemeta (dir, name, meta)"
            " VALUES (?, ?, ?)",
            (entry.parent, entry.name, json.dumps(entry.to_dict())))

    update_entry = insert_entry

    def find_entry(self, path: str) -> Entry:
        if path == "/":
            from .entry import new_directory_entry

            return new_directory_entry("/")
        parent, name = path.rsplit("/", 1)
        row = self._conn().execute(
            "SELECT meta FROM filemeta WHERE dir = ? AND name = ?",
            (parent or "/", name)).fetchone()
        if row is None:
            raise NotFoundError(path)
        return Entry.from_dict(json.loads(row[0]))

    def delete_entry(self, path: str):
        parent, name = path.rsplit("/", 1)
        self._conn().execute(
            "DELETE FROM filemeta WHERE dir = ? AND name = ?",
            (parent or "/", name))

    @staticmethod
    def _escape_like(s: str) -> str:
        return (s.replace("\\", "\\\\").replace("%", "\\%")
                .replace("_", "\\_"))

    def delete_folder_children(self, path: str):
        base = path.rstrip("/")
        self._conn().execute(
            "DELETE FROM filemeta WHERE dir = ? OR dir LIKE ? ESCAPE '\\'",
            (base or "/", self._escape_like(base + "/") + "%"))

    def list_directory(self, dir_path: str, start_file: str = "",
                       include_start: bool = False, limit: int = 1024,
                       prefix: str = "") -> list[Entry]:
        op = ">=" if include_start else ">"
        sql = (f"SELECT meta FROM filemeta WHERE dir = ? AND name {op} ?")
        args: list = [dir_path, start_file]
        if prefix:
            sql += " AND name LIKE ? ESCAPE '\\'"
            args.append(self._escape_like(prefix) + "%")
        sql += " ORDER BY name LIMIT ?"
        args.append(limit)
        rows = self._conn().execute(sql, args).fetchall()
        return [Entry.from_dict(json.loads(r[0])) for r in rows]

    def close(self):
        conn = getattr(self._local, "conn", None)
        if conn is not None:
            conn.close()
            self._local.conn = None


class ShardedSqliteStore(FilerStore):
    """Directory-hashed shards, one sqlite file each.

    The analogue of the reference's leveldb2 store (filer/leveldb2: 256
    hashed sub-DBs) — spreading directories over independent databases
    keeps per-file lock contention and compaction local to a shard."""

    def __init__(self, directory: str, shard_count: Optional[int] = None):
        import os

        from .shard_map import default_slots

        os.makedirs(directory, exist_ok=True)
        self.directory = directory
        self.shard_count = shard_count or default_slots()
        self._shards = [
            SqliteStore(os.path.join(directory, f"meta_{i:02x}.db"))
            for i in range(self.shard_count)]

    def _shard(self, dir_path: str) -> SqliteStore:
        from .shard_map import slot_of

        return self._shards[slot_of(dir_path, self.shard_count)]

    # -- slot-level access (cluster mode: handover + dump) --------------------
    def slot_store(self, slot: int) -> SqliteStore:
        return self._shards[slot % self.shard_count]

    def dump_slot(self, slot: int, limit: int = 100_000) -> list[dict]:
        """Every entry in one shard slot, for lease handover to the next
        holder.  Slot i is exactly the local meta_{i:02x}.db file, since
        the cluster shard map hashes with the same function."""
        rows = self.slot_store(slot)._conn().execute(
            "SELECT meta FROM filemeta ORDER BY dir, name LIMIT ?",
            (limit,)).fetchall()
        return [json.loads(r[0]) for r in rows]

    def load_slot(self, slot: int, entries: list[dict]):
        store = self.slot_store(slot)
        for d in entries:
            store.insert_entry(Entry.from_dict(d))

    def insert_entry(self, entry: Entry):
        self._shard(entry.parent).insert_entry(entry)

    update_entry = insert_entry

    def find_entry(self, path: str) -> Entry:
        if path == "/":
            from .entry import new_directory_entry

            return new_directory_entry("/")
        parent = path.rsplit("/", 1)[0] or "/"
        return self._shard(parent).find_entry(path)

    def delete_entry(self, path: str):
        parent = path.rsplit("/", 1)[0] or "/"
        self._shard(parent).delete_entry(path)

    def delete_folder_children(self, path: str):
        # children may hash to any shard (each child dir hashes by its
        # own parent path): fan the prefix delete out to all shards
        for shard in self._shards:
            shard.delete_folder_children(path)

    def list_directory(self, dir_path: str, start_file: str = "",
                       include_start: bool = False, limit: int = 1024,
                       prefix: str = "") -> list[Entry]:
        return self._shard(dir_path).list_directory(
            dir_path, start_file=start_file,
            include_start=include_start, limit=limit, prefix=prefix)

    def close(self):
        for shard in self._shards:
            shard.close()

    def forget_connections(self):
        for shard in self._shards:
            shard.forget_connections()


class PerBucketStoreRouter(FilerStore):
    """Route /buckets/<name>/ subtrees to dedicated stores.

    The analogue of the reference's leveldb3 (per-bucket DBs,
    filer/leveldb3): dropping a bucket is dropping its store, and one
    bucket's scan load cannot slow another's."""

    def __init__(self, directory: str, buckets_root: str = "/buckets"):
        import os

        os.makedirs(directory, exist_ok=True)
        self.directory = directory
        self.buckets_root = buckets_root.rstrip("/")
        self.default = SqliteStore(os.path.join(directory, "default.db"))
        self._buckets: dict[str, SqliteStore] = {}
        self._lock = threading.Lock()
        for name in sorted(os.listdir(directory)):
            if name.startswith("bucket_") and name.endswith(".db"):
                bucket = name[len("bucket_"):-3]
                self._buckets[bucket] = SqliteStore(
                    os.path.join(directory, name))

    def _bucket_of(self, path: str) -> Optional[str]:
        if not path.startswith(self.buckets_root + "/"):
            return None
        rest = path[len(self.buckets_root) + 1:]
        return rest.split("/", 1)[0] if rest else None

    def _store_for(self, path: str) -> SqliteStore:
        import os

        bucket = self._bucket_of(path)
        if not bucket:
            return self.default
        with self._lock:
            store = self._buckets.get(bucket)
            if store is None:
                store = SqliteStore(os.path.join(
                    self.directory, f"bucket_{bucket}.db"))
                self._buckets[bucket] = store
            return store

    def insert_entry(self, entry: Entry):
        self._store_for(entry.full_path).insert_entry(entry)

    update_entry = insert_entry

    def find_entry(self, path: str) -> Entry:
        return self._store_for(path).find_entry(path)

    def delete_entry(self, path: str):
        self._store_for(path).delete_entry(path)
        # deleting a bucket root drops its whole store file
        bucket = self._bucket_of(path)
        if bucket and path == f"{self.buckets_root}/{bucket}":
            self._drop_bucket(bucket)

    def forget_connections(self):
        self.default.forget_connections()
        with self._lock:
            stores = list(self._buckets.values())
        for store in stores:
            store.forget_connections()

    def _drop_bucket(self, bucket: str):
        import os

        with self._lock:
            store = self._buckets.pop(bucket, None)
        if store is not None:
            store.close()
            try:
                os.remove(os.path.join(self.directory,
                                       f"bucket_{bucket}.db"))
            except FileNotFoundError:
                pass

    def delete_folder_children(self, path: str):
        bucket = self._bucket_of(path)
        if bucket and path.rstrip("/") == f"{self.buckets_root}/{bucket}":
            # whole-bucket delete: clear the dedicated store
            self._store_for(path + "/x").delete_folder_children(path)
            return
        self._store_for(path).delete_folder_children(path)
        if path.rstrip("/") in ("", "/", self.buckets_root):
            for b in list(self._buckets):
                self._drop_bucket(b)

    def list_directory(self, dir_path: str, start_file: str = "",
                       include_start: bool = False, limit: int = 1024,
                       prefix: str = "") -> list[Entry]:
        if dir_path.rstrip("/") == self.buckets_root:
            # bucket roots live in their own stores; merge their REAL
            # stored entries with default-store entries (a fabricated
            # listing would lose attributes and misreport plain files)
            out = [e for e in self.default.list_directory(
                dir_path, start_file=start_file,
                include_start=include_start, limit=limit, prefix=prefix)]
            have = {e.name for e in out}
            for b in sorted(self._buckets):
                if b in have or (prefix and not b.startswith(prefix)):
                    continue
                if start_file and (b < start_file or
                                   (b == start_file
                                    and not include_start)):
                    continue
                try:
                    out.append(self._buckets[b].find_entry(
                        f"{self.buckets_root}/{b}"))
                except NotFoundError:
                    continue  # store file exists but root entry gone
            out.sort(key=lambda e: e.name)
            return out[:limit]
        return self._store_for(dir_path + "/x").list_directory(
            dir_path, start_file=start_file,
            include_start=include_start, limit=limit, prefix=prefix)

    def close(self):
        self.default.close()
        for store in self._buckets.values():
            store.close()
