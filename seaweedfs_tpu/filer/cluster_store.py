"""Cluster-distributed filer store client.

`-store cluster`: the filer keeps no local metadata; it fetches the
slot→holder shard map from the masters (`/filer/shards`, served from
the replicated FSM so any master answers identically) and routes each
operation straight to the holder's store server.  On a routing miss —
holder gone, map rotated, lease moved — the map is refreshed once and
the operation retried; the store servers themselves proxy one hop, so
a slightly stale map still lands (filer/store_server.py).

This is the lease-based metadata partitioning of the "decoupled
metadata" lineage in PAPERS.md: the map is tiny and replicated, the
metadata bytes stay sharded on the holders.
"""

from __future__ import annotations

import threading
import urllib.parse
from typing import Optional

from ..rpc.http_rpc import RpcError, call
from .entry import Entry
from .filer_store import FilerStore, NotFoundError
from .shard_map import default_slots, slot_of


class ClusterStore(FilerStore):
    def __init__(self, masters: list[str] | str, timeout: float = 20.0):
        self.masters = ([masters] if isinstance(masters, str)
                        else list(masters))
        self.timeout = timeout
        self._lock = threading.Lock()
        self._map: dict[int, str] = {}
        self._slots = default_slots()
        self._epoch = -1

    # -- shard map ------------------------------------------------------------
    def _refresh_map(self):
        last: Optional[RpcError] = None
        for addr in self.masters:
            try:
                r = call(addr, "/filer/shards", timeout=5)
            except RpcError as e:
                last = e
                continue
            with self._lock:
                self._slots = int(r.get("slots") or self._slots)
                self._map = {int(k): v
                             for k, v in (r.get("map") or {}).items()}
                self._epoch = int(r.get("epoch", 0))
            return
        raise last or RpcError("no master reachable for shard map", 503)

    def _holder(self, dir_path: str, refresh: bool = False) -> str:
        with self._lock:
            empty = not self._map
        if refresh or empty:
            self._refresh_map()
        with self._lock:
            holder = self._map.get(slot_of(dir_path, self._slots), "")
        if not holder:
            raise RpcError(
                f"no store server holds the shard for {dir_path!r}", 503)
        return holder

    def _call(self, dir_path: str, path: str, payload=None,
              method: str = "GET"):
        """Route to the slot holder; one refresh+retry on failure (the
        holder may have crashed or the lease moved since our map read)."""
        refreshed = False
        while True:
            holder = self._holder(dir_path, refresh=refreshed)
            try:
                return call(holder, path, payload=payload,
                            method=method, timeout=self.timeout)
            except RpcError as e:
                if e.status == 404:
                    raise NotFoundError(str(e))
                if refreshed:
                    raise
                refreshed = True

    # -- FilerStore interface -------------------------------------------------
    def insert_entry(self, entry: Entry):
        self._call(entry.parent, "/store/insert",
                   payload=entry.to_dict(), method="POST")

    update_entry = insert_entry

    def find_entry(self, path: str) -> Entry:
        parent = path.rsplit("/", 1)[0] or "/"
        return Entry.from_dict(self._call(
            parent,
            "/store/find?path=" + urllib.parse.quote(path, safe="/")))

    def delete_entry(self, path: str):
        parent = path.rsplit("/", 1)[0] or "/"
        self._call(parent, "/store/delete", payload={"path": path},
                   method="POST")

    def delete_folder_children(self, path: str):
        # descendants hash anywhere: every holder prunes its local
        # shards (the hop guard keeps holders from re-broadcasting)
        try:
            self._refresh_map()
        except RpcError:
            pass
        with self._lock:
            holders = sorted(set(self._map.values()))
        errs = []
        for holder in holders:
            try:
                call(holder, "/store/delete_children",
                     payload={"path": path}, method="POST",
                     timeout=self.timeout,
                     headers={"X-Shard-Hop": "1"})
            except RpcError as e:
                errs.append(e)
        if errs and len(errs) == len(holders):
            raise errs[0]

    def rename_entry(self, path: str, new_path: str):
        parent = path.rsplit("/", 1)[0] or "/"
        self._call(parent, "/store/rename",
                   payload={"path": path, "new_path": new_path},
                   method="POST")

    def list_directory(self, dir_path: str, start_file: str = "",
                       include_start: bool = False, limit: int = 1024,
                       prefix: str = "") -> list[Entry]:
        q = urllib.parse.urlencode({
            "dir": dir_path, "start": start_file,
            "include_start": "true" if include_start else "false",
            "limit": str(limit), "prefix": prefix})
        out = self._call(dir_path, "/store/list?" + q)
        return [Entry.from_dict(d) for d in out.get("entries", [])]
