"""Replicated directory-prefix shard map for filer metadata.

The filer's ShardedSqliteStore hashes each directory into one of N
slots (md5(dir)[0] % N).  To scale that across machines, the master FSM
holds this map: slot -> lease holder, with per-holder fair-share
acquisition and lease expiry.  Store servers renew through the raft log
(`filer.lease` commands), so a failed-over master serves the exact same
assignment and two holders can never both believe they own a slot
beyond one lease TTL.

Deterministic by construction: every input (holder, now, ttl) rides in
the replicated command; no wall-clock or RNG reads happen here.
"""

from __future__ import annotations

import hashlib
import os
from typing import Optional


def default_slots() -> int:
    try:
        return int(os.environ.get("WEED_FILER_SHARDS", "") or 8)
    except ValueError:
        return 8


def slot_of(dir_path: str, slots: int) -> int:
    """Same hash the ShardedSqliteStore uses for its local files, so
    slot i of the map is exactly the holder's local meta_{i:02x}.db."""
    return hashlib.md5(dir_path.encode()).digest()[0] % slots


class ShardMap:
    def __init__(self, slots: Optional[int] = None):
        self.slots = int(slots) if slots else default_slots()
        # slot -> {"holder": addr, "expires": epoch-seconds}
        self.holders: dict[int, dict] = {}
        # slot -> last holder that gave it up (handover source)
        self.prev: dict[int, str] = {}
        # holder -> lease expiry; the membership that fair shares are
        # computed over (a newly-joined holder must count toward the
        # divisor BEFORE it owns any slot, or incumbents never shed)
        self.members: dict[str, float] = {}
        self.epoch = 0

    # -- lease protocol (applied under the master FSM) ------------------------
    def _drop(self, slot: int):
        entry = self.holders.pop(slot, None)
        if entry is not None:
            self.prev[slot] = entry["holder"]

    def _expire(self, now: float) -> bool:
        changed = False
        for slot in [s for s, h in self.holders.items()
                     if h["expires"] <= now]:
            self._drop(slot)
            changed = True
        for m in [m for m, exp in self.members.items() if exp <= now]:
            del self.members[m]
        return changed

    def lease(self, holder: str, now: float, ttl: float) -> dict:
        """Renew the holder's fair share and grant free slots up to it.
        Slots over the fair share are shed at renewal (recorded in
        `prev` for handover) — the response tells the holder exactly
        what it still owns, so there is never a moment with two live
        owners; membership churn converges within ~one lease TTL."""
        changed = self._expire(now)
        self.members[holder] = now + ttl
        active = ({h["holder"] for h in self.holders.values()}
                  | set(self.members))
        fair = -(-self.slots // max(1, len(active)))  # ceil
        held = sorted(s for s, h in self.holders.items()
                      if h["holder"] == holder)
        keep, shed = held[:fair], held[fair:]
        for slot in keep:
            self.holders[slot]["expires"] = now + ttl
        for slot in shed:
            self._drop(slot)
            changed = True
        for slot in range(self.slots):
            if len(keep) >= fair:
                break
            if slot not in self.holders:
                self.holders[slot] = {"holder": holder,
                                      "expires": now + ttl}
                keep.append(slot)
                changed = True
        if changed:
            self.epoch += 1
        return {"epoch": self.epoch, "slots": sorted(keep), "ttl": ttl,
                "prev": {str(s): self.prev.get(s, "") for s in keep},
                "map": self.assignments()}

    def release(self, holder: str, now: float) -> dict:
        """Graceful departure: free every slot immediately (the holder
        stays up long enough for successors to pull a handover dump)."""
        freed = [s for s, h in self.holders.items()
                 if h["holder"] == holder]
        for slot in freed:
            self._drop(slot)
        self.members.pop(holder, None)
        if freed:
            self.epoch += 1
        return {"epoch": self.epoch, "released": sorted(freed),
                "map": self.assignments()}

    # -- views ----------------------------------------------------------------
    def assignments(self) -> dict:
        return {str(s): h["holder"]
                for s, h in sorted(self.holders.items())}

    def holder_of(self, dir_path: str) -> str:
        entry = self.holders.get(slot_of(dir_path, self.slots))
        return entry["holder"] if entry else ""

    def to_dict(self) -> dict:
        return {"slots": self.slots, "epoch": self.epoch,
                "holders": {str(s): dict(h)
                            for s, h in sorted(self.holders.items())},
                "prev": {str(s): p
                         for s, p in sorted(self.prev.items())},
                "members": {m: exp
                            for m, exp in sorted(self.members.items())}}

    @classmethod
    def from_dict(cls, d: dict) -> "ShardMap":
        d = d or {}
        m = cls(slots=d.get("slots") or None)
        m.epoch = int(d.get("epoch", 0))
        m.holders = {int(s): {"holder": h["holder"],
                              "expires": float(h["expires"])}
                     for s, h in d.get("holders", {}).items()}
        m.prev = {int(s): p for s, p in d.get("prev", {}).items()}
        m.members = {k: float(v)
                     for k, v in d.get("members", {}).items()}
        return m
