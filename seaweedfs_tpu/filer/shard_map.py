"""Replicated directory-prefix shard map for filer metadata.

The filer's ShardedSqliteStore hashes each directory into one of N
slots (md5(dir)[0] % N).  To scale that across machines, the master FSM
holds this map: slot -> lease holder, with per-holder fair-share
acquisition and lease expiry.  Store servers renew through the raft log
(`filer.lease` commands), so a failed-over master serves the exact same
assignment and two holders can never both believe they own a slot
beyond one lease TTL.

The slot COUNT itself can change online (`filer.resize` commands): a
two-phase split/merge where holders first re-shard their local data
into the target layout while dual-writing (prepare), then the map flips
atomically to the new count (commit).  The constraint that the new
count divides — or is divided by — the old one keeps re-sharding local:
on a split every entry of old slot s lands in a new slot s' with
s' % old == s, so each holder derives its new shards from data it
already owns; on a merge the new owner pulls the other sources'
handover dumps through the ordinary `prev` mechanism.

Deterministic by construction: every input (holder, now, ttl) rides in
the replicated command; no wall-clock or RNG reads happen here.
"""

from __future__ import annotations

import hashlib
import os
from typing import Optional


def default_slots() -> int:
    try:
        return int(os.environ.get("WEED_FILER_SHARDS", "") or 8)
    except ValueError:
        return 8


def slot_of(dir_path: str, slots: int) -> int:
    """Same hash the ShardedSqliteStore uses for its local files, so
    slot i of the map is exactly the holder's local meta_{i:02x}.db."""
    return hashlib.md5(dir_path.encode()).digest()[0] % slots


class ShardMap:
    def __init__(self, slots: Optional[int] = None):
        self.slots = int(slots) if slots else default_slots()
        # slot -> {"holder": addr, "expires": epoch-seconds}
        self.holders: dict[int, dict] = {}
        # slot -> holders that last gave it up (handover sources); a
        # merge can fold several old slots into one, hence list-valued
        self.prev: dict[int, list] = {}
        # holder -> lease expiry; the membership that fair shares are
        # computed over (a newly-joined holder must count toward the
        # divisor BEFORE it owns any slot, or incumbents never shed)
        self.members: dict[str, float] = {}
        self.epoch = 0
        # in-flight split/merge:
        # {"to": N, "phase": "prepare", "started": now, "acks": [...]}
        self.resize: Optional[dict] = None

    # -- lease protocol (applied under the master FSM) ------------------------
    def _drop(self, slot: int):
        entry = self.holders.pop(slot, None)
        if entry is not None:
            self.prev[slot] = [entry["holder"]]

    def _expire(self, now: float) -> bool:
        changed = False
        for slot in [s for s, h in self.holders.items()
                     if h["expires"] <= now]:
            self._drop(slot)
            changed = True
        for m in [m for m, exp in self.members.items() if exp <= now]:
            del self.members[m]
        return changed

    def lease(self, holder: str, now: float, ttl: float) -> dict:
        """Renew the holder's fair share and grant free slots up to it.
        Slots over the fair share are shed at renewal (recorded in
        `prev` for handover) — the response tells the holder exactly
        what it still owns, so there is never a moment with two live
        owners; membership churn converges within ~one lease TTL."""
        changed = self._expire(now)
        self.members[holder] = now + ttl
        active = ({h["holder"] for h in self.holders.values()}
                  | set(self.members))
        fair = -(-self.slots // max(1, len(active)))  # ceil
        held = sorted(s for s, h in self.holders.items()
                      if h["holder"] == holder)
        keep, shed = held[:fair], held[fair:]
        for slot in keep:
            self.holders[slot]["expires"] = now + ttl
        for slot in shed:
            self._drop(slot)
            changed = True
        for slot in range(self.slots):
            if len(keep) >= fair:
                break
            if slot not in self.holders:
                self.holders[slot] = {"holder": holder,
                                      "expires": now + ttl}
                keep.append(slot)
                changed = True
        if changed:
            self.epoch += 1
        return {"epoch": self.epoch, "slots": sorted(keep), "ttl": ttl,
                "slots_total": self.slots,
                "resize": dict(self.resize) if self.resize else None,
                "prev": {str(s): list(self.prev.get(s, []))
                         for s in keep},
                "map": self.assignments()}

    def release(self, holder: str, now: float) -> dict:
        """Graceful departure: free every slot immediately (the holder
        stays up long enough for successors to pull a handover dump)."""
        freed = [s for s, h in self.holders.items()
                 if h["holder"] == holder]
        for slot in freed:
            self._drop(slot)
        self.members.pop(holder, None)
        if self.resize is not None and holder in self.resize["acks"]:
            self.resize["acks"].remove(holder)
        if freed:
            self.epoch += 1
        return {"epoch": self.epoch, "released": sorted(freed),
                "map": self.assignments()}

    # -- online split / merge -------------------------------------------------
    def resize_start(self, to: int, now: float) -> dict:
        """Open a split (to > slots) or merge (to < slots).  Errors are
        returned, not raised — this runs inside the FSM apply path,
        which must stay total."""
        to = int(to)
        if self.resize is not None:
            return {"error": "resize already in flight",
                    "resize": dict(self.resize)}
        if to < 1:
            return {"error": "shard count must be >= 1"}
        if to == self.slots:
            return {"error": f"already at {to} slots"}
        if to % self.slots != 0 and self.slots % to != 0:
            return {"error": "new shard count must divide or be a "
                             f"multiple of {self.slots}"}
        self.resize = {"to": to, "phase": "prepare",
                       "started": float(now), "acks": []}
        self.epoch += 1
        return {"epoch": self.epoch, "resize": dict(self.resize)}

    def resize_ack(self, holder: str, now: float) -> dict:
        """A holder reports its local re-shard to the target layout is
        durable (idempotent; re-acks are no-ops)."""
        if self.resize is None:
            return {"error": "no resize in flight"}
        if holder and holder not in self.resize["acks"]:
            self.resize["acks"].append(holder)
            self.epoch += 1
        return {"epoch": self.epoch, "resize": dict(self.resize)}

    def resize_pending(self, now: float) -> list:
        """Holders/members whose ack the commit still waits on.  Pure
        read — expired holders are filtered, not dropped (mutation only
        happens inside replicated commands)."""
        if self.resize is None:
            return []
        need = {h["holder"] for h in self.holders.values()
                if h["expires"] > now}
        need |= {m for m, exp in self.members.items() if exp > now}
        return sorted(need - set(self.resize["acks"]))

    def resize_commit(self, now: float) -> dict:
        """Atomically flip the slot map to the target count.  Ownership
        carries over so the flip never orphans a slot: on a split each
        new slot inherits the holder of its source (s % old); on a merge
        the surviving owner is preferred and every other source becomes
        a `prev` handover the new owner pulls."""
        if self.resize is None:
            return {"error": "no resize in flight"}
        old, new = self.slots, int(self.resize["to"])
        holders: dict[int, dict] = {}
        prev: dict[int, list] = {}
        if new > old:  # split: new slot s sources old slot s % old
            for s in range(new):
                src = s % old
                entry = self.holders.get(src)
                if entry is not None:
                    holders[s] = {"holder": entry["holder"],
                                  "expires": entry["expires"]}
                elif self.prev.get(src):
                    prev[s] = list(self.prev[src])
        else:  # merge: new slot s sources {s + j*new for j}
            k = old // new
            for s in range(new):
                sources = [s + j * new for j in range(k)]
                own = self.holders.get(s)
                if own is None:
                    for src in sources:
                        if src in self.holders:
                            own = self.holders[src]
                            break
                if own is not None:
                    holders[s] = {"holder": own["holder"],
                                  "expires": own["expires"]}
                sources_prev: list = []
                for src in sources:
                    e = self.holders.get(src)
                    if e is not None and (own is None
                                          or e["holder"] != own["holder"]):
                        if e["holder"] not in sources_prev:
                            sources_prev.append(e["holder"])
                    elif e is None:
                        for p in self.prev.get(src, []):
                            if p not in sources_prev \
                                    and (own is None
                                         or p != own["holder"]):
                                sources_prev.append(p)
                if sources_prev:
                    prev[s] = sources_prev
        self.slots = new
        self.holders = holders
        self.prev = prev
        self.resize = None
        self.epoch += 1
        return {"epoch": self.epoch, "slots": new, "from": old}

    def resize_abort(self, now: float) -> dict:
        if self.resize is None:
            return {"error": "no resize in flight"}
        aborted = dict(self.resize)
        self.resize = None
        self.epoch += 1
        return {"epoch": self.epoch, "aborted": aborted}

    # -- views ----------------------------------------------------------------
    def assignments(self) -> dict:
        return {str(s): h["holder"]
                for s, h in sorted(self.holders.items())}

    def holder_of(self, dir_path: str) -> str:
        entry = self.holders.get(slot_of(dir_path, self.slots))
        return entry["holder"] if entry else ""

    def to_dict(self) -> dict:
        return {"slots": self.slots, "epoch": self.epoch,
                "holders": {str(s): dict(h)
                            for s, h in sorted(self.holders.items())},
                "prev": {str(s): list(p)
                         for s, p in sorted(self.prev.items())},
                "members": {m: exp
                            for m, exp in sorted(self.members.items())},
                "resize": dict(self.resize) if self.resize else None}

    @classmethod
    def from_dict(cls, d: dict) -> "ShardMap":
        d = d or {}
        m = cls(slots=d.get("slots") or None)
        m.epoch = int(d.get("epoch", 0))
        m.holders = {int(s): {"holder": h["holder"],
                              "expires": float(h["expires"])}
                     for s, h in d.get("holders", {}).items()}
        # pre-resize snapshots persisted prev as slot -> single holder
        m.prev = {int(s): ([p] if isinstance(p, str) else list(p))
                  for s, p in d.get("prev", {}).items()}
        m.members = {k: float(v)
                     for k, v in d.get("members", {}).items()}
        m.resize = dict(d["resize"]) if d.get("resize") else None
        return m
