"""Per-path filer configuration rules stored inside the filer itself.

Parity with weed/filer/filer_conf.go: a config entry at
/etc/seaweedfs/filer.conf holds a list of path-prefix rules
(collection, replication, ttl, read-only, ...); writes under a prefix pick
up the most-specific (longest) matching rule.  The reference stores
protobuf text; this stores JSON with the same rule fields.
"""

from __future__ import annotations

import json
import time
from dataclasses import asdict, dataclass, field

from .entry import Attr, Entry
from .filer_store import NotFoundError

FILER_CONF_PATH = "/etc/seaweedfs/filer.conf"  # filer_conf.go FilerConfName


@dataclass
class PathConf:
    location_prefix: str = "/"
    collection: str = ""
    replication: str = ""
    ttl: str = ""
    disk_type: str = ""
    fsync: bool = False
    read_only: bool = False
    max_file_name_length: int = 0
    # erasure-coding code family for volumes in this collection
    # ("rs_vandermonde" / "cauchy" / "pm_msr"; "" = cluster default)
    ec_code: str = ""
    # s3.bucket.quota: MiB budget for the bucket this rule covers
    # (negative = configured but disabled); quota_read_only records that
    # read_only was set BY quota enforcement so it can be auto-cleared
    quota_mb: int = 0
    quota_read_only: bool = False


@dataclass
class FilerConf:
    rules: list[PathConf] = field(default_factory=list)

    def add(self, rule: PathConf):
        self.rules = [r for r in self.rules
                      if r.location_prefix != rule.location_prefix]
        self.rules.append(rule)

    def delete(self, location_prefix: str):
        self.rules = [r for r in self.rules
                      if r.location_prefix != location_prefix]

    def match_path(self, path: str) -> PathConf:
        """Longest-prefix rule wins (filer_conf.go MatchStorageRule)."""
        best = PathConf()
        best_len = -1
        for rule in self.rules:
            prefix = rule.location_prefix
            if path.startswith(prefix) and len(prefix) > best_len:
                best, best_len = rule, len(prefix)
        return best

    def to_bytes(self) -> bytes:
        return json.dumps({"locations": [asdict(r) for r in self.rules]},
                          indent=2).encode()

    @classmethod
    def from_bytes(cls, data: bytes) -> "FilerConf":
        doc = json.loads(data.decode()) if data else {}
        known = PathConf.__dataclass_fields__
        return cls(rules=[
            PathConf(**{k: v for k, v in r.items() if k in known})
            for r in doc.get("locations", [])])

    # -- persistence in the filer tree --------------------------------------
    def save(self, filer):
        body = self.to_bytes()
        filer.create_entry(Entry(
            full_path=FILER_CONF_PATH,
            attr=Attr(mtime=time.time(), crtime=time.time(),
                      file_size=len(body)),
            content=body))

    @classmethod
    def load(cls, filer) -> "FilerConf":
        try:
            return cls.from_bytes(filer.find_entry(FILER_CONF_PATH).content)
        except NotFoundError:
            return cls()
