"""Shared out-of-process filer store: a store SERVICE + a client store.

The reference's redis family (weed/filer/redis/universal_redis_store.go:
20-130, redis2/, redis_lua/) lets many STATELESS filers share one
metadata store — an HA mode the embedded stores (filer_store.py) cannot
provide.  No redis server exists in this image, so the same capability is
built on the repo's own RPC substrate: `weed filer.store` hosts any
embedded store kind behind HTTP/JSON routes, and RemoteStore is a
FilerStore client speaking to it over pooled keep-alive connections.
Filers configured with `-store remote -storeAddress host:port` keep no
local metadata at all — kill one, start another, same namespace.
"""

from __future__ import annotations

import threading
from typing import Optional

from ..rpc.http_rpc import Request, RpcError, RpcServer, call
from .entry import Entry
from .filer_store import (FilerStore, MemoryStore, NotFoundError,
                          PerBucketStoreRouter, ShardedSqliteStore,
                          SqliteStore)


def make_store(kind: str, directory: Optional[str] = None) -> FilerStore:
    """Construct an embedded store by kind name (shared by the filer CLI
    and the store service)."""
    import os

    if kind in ("memory", ""):
        return MemoryStore()
    if directory is None:
        raise ValueError(f"store kind {kind!r} needs a directory")
    os.makedirs(directory, exist_ok=True)
    if kind == "sqlite":
        return SqliteStore(os.path.join(directory, "filer.db"))
    if kind == "sharded":
        return ShardedSqliteStore(os.path.join(directory, "meta"))
    if kind == "perbucket":
        return PerBucketStoreRouter(os.path.join(directory, "meta"))
    raise ValueError(f"unknown store kind {kind!r}")


class FilerStoreServer:
    """`weed filer.store`: host one embedded store for many filers."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 store: Optional[FilerStore] = None):
        self.store = store or MemoryStore()
        # one writer lock: the embedded stores are already thread-safe,
        # but insert/update of the SAME path from two filers must not
        # interleave partially (universal_redis_store serialises per key
        # through redis itself)
        self._lock = threading.RLock()
        self.server = RpcServer(host, port)
        self.server.add("POST", "/store/insert", self._h_insert)
        self.server.add("POST", "/store/update", self._h_insert)
        self.server.add("GET", "/store/find", self._h_find)
        self.server.add("POST", "/store/delete", self._h_delete)
        self.server.add("POST", "/store/delete_children",
                        self._h_delete_children)
        self.server.add("GET", "/store/list", self._h_list)
        self.server.add("GET", "/store/status", self._h_status)

    @property
    def address(self) -> str:
        return self.server.address

    def start(self):
        self.server.start()

    def stop(self):
        self.server.stop()
        self.store.close()

    def _h_insert(self, req: Request):
        entry = Entry.from_dict(req.json())
        with self._lock:
            self.store.insert_entry(entry)
        return {}

    def _h_find(self, req: Request):
        path = req.param("path", "") or "/"
        try:
            return self.store.find_entry(path).to_dict()
        except NotFoundError:
            raise RpcError(f"{path} not found", 404)

    def _h_delete(self, req: Request):
        with self._lock:
            self.store.delete_entry(req.json().get("path", ""))
        return {}

    def _h_delete_children(self, req: Request):
        with self._lock:
            self.store.delete_folder_children(req.json().get("path", ""))
        return {}

    def _h_list(self, req: Request):
        entries = self.store.list_directory(
            req.param("dir", "") or "/",
            start_file=req.param("start", "") or "",
            include_start=req.param("include_start") == "true",
            limit=int(req.param("limit", "1024")),
            prefix=req.param("prefix", "") or "")
        return {"entries": [e.to_dict() for e in entries]}

    def _h_status(self, req: Request):
        return {"store": type(self.store).__name__}


class RemoteStore(FilerStore):
    """FilerStore client against a FilerStoreServer — the stateless-filer
    mode.  Every operation is one pooled keep-alive round trip (the
    substrate retries per rpc/http_rpc's phase-split policy)."""

    def __init__(self, address: str, timeout: float = 20.0):
        self.address = address
        self.timeout = timeout

    def _call(self, path: str, payload=None, method: str = "GET"):
        try:
            return call(self.address, path, payload=payload,
                        method=method, timeout=self.timeout)
        except RpcError as e:
            if e.status == 404:
                raise NotFoundError(str(e))
            raise

    def insert_entry(self, entry: Entry):
        self._call("/store/insert", payload=entry.to_dict(),
                   method="POST")

    update_entry = insert_entry

    def find_entry(self, path: str) -> Entry:
        import urllib.parse

        return Entry.from_dict(self._call(
            "/store/find?path=" + urllib.parse.quote(path, safe="/")))

    def delete_entry(self, path: str):
        self._call("/store/delete", payload={"path": path}, method="POST")

    def delete_folder_children(self, path: str):
        self._call("/store/delete_children", payload={"path": path},
                   method="POST")

    def list_directory(self, dir_path: str, start_file: str = "",
                       include_start: bool = False, limit: int = 1024,
                       prefix: str = "") -> list[Entry]:
        import urllib.parse

        q = urllib.parse.urlencode({
            "dir": dir_path, "start": start_file,
            "include_start": "true" if include_start else "false",
            "limit": str(limit), "prefix": prefix})
        out = self._call("/store/list?" + q)
        return [Entry.from_dict(d) for d in out.get("entries", [])]
