"""Shared out-of-process filer store: a store SERVICE + a client store.

The reference's redis family (weed/filer/redis/universal_redis_store.go:
20-130, redis2/, redis_lua/) lets many STATELESS filers share one
metadata store — an HA mode the embedded stores (filer_store.py) cannot
provide.  No redis server exists in this image, so the same capability is
built on the repo's own RPC substrate: `weed filer.store` hosts any
embedded store kind behind HTTP/JSON routes, and RemoteStore is a
FilerStore client speaking to it over pooled keep-alive connections.
Filers configured with `-store remote -storeAddress host:port` keep no
local metadata at all — kill one, start another, same namespace.

Cluster mode (`-master` given): multiple store servers split the
directory-hash shard space.  The authoritative slot→holder map lives in
the MASTER's replicated FSM (filer/shard_map.py): each server leases its
fair share through `/filer/shard_lease` (a raft-committed command), so
a failed-over master serves the identical assignment.  Requests for a
slot held elsewhere are proxied to the holder (one hop, loop-guarded by
X-Shard-Hop); newly-acquired slots pull a handover dump from the
previous holder when it is still alive.
"""

from __future__ import annotations

import os
import threading
import urllib.parse
from typing import Optional

from ..rpc.http_rpc import Request, RpcError, RpcServer, call
from ..util import glog
from .entry import Entry
from .filer_store import (FilerStore, MemoryStore, NotFoundError,
                          PerBucketStoreRouter, ShardedSqliteStore,
                          SqliteStore)
from .shard_map import default_slots, slot_of

HOP_HEADER = "X-Shard-Hop"  # one proxy hop max, never a forwarding loop


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def make_store(kind: str, directory: Optional[str] = None) -> FilerStore:
    """Construct an embedded store by kind name (shared by the filer CLI
    and the store service)."""
    import os

    if kind in ("memory", ""):
        return MemoryStore()
    if directory is None:
        raise ValueError(f"store kind {kind!r} needs a directory")
    os.makedirs(directory, exist_ok=True)
    if kind == "sqlite":
        return SqliteStore(os.path.join(directory, "filer.db"))
    if kind == "sharded":
        return ShardedSqliteStore(os.path.join(directory, "meta"))
    if kind == "perbucket":
        return PerBucketStoreRouter(os.path.join(directory, "meta"))
    raise ValueError(f"unknown store kind {kind!r}")


class FilerStoreServer:
    """`weed filer.store`: host one embedded store for many filers."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 store: Optional[FilerStore] = None,
                 masters: Optional[list[str]] = None):
        self.store = store or MemoryStore()
        self.masters = [m for m in (masters or []) if m]
        # one writer lock: the embedded stores are already thread-safe,
        # but insert/update of the SAME path from two filers must not
        # interleave partially (universal_redis_store serialises per key
        # through redis itself)
        self._lock = threading.RLock()
        # cluster-mode shard state (all under _lock)
        self._slots = getattr(self.store, "shard_count", 0) \
            or default_slots()
        self._held: set[int] = set()
        self._map: dict[int, str] = {}
        self._epoch = 0
        self._lease_ttl = _env_float("WEED_FILER_SHARD_LEASE", 10.0)
        self._lease_stop = threading.Event()
        self._lease_thread: Optional[threading.Thread] = None
        self._pulled: set[int] = set()  # slots already handover-filled
        # online split/merge (two-phase): while a resize is in prepare,
        # a STAGING store holds the target layout; every write lands in
        # both (dual-write) and held slots are copied over, so at commit
        # the staging store simply becomes the store
        self._resize: Optional[dict] = None
        self._staging: Optional[FilerStore] = None
        self._staging_to = 0
        self._staged: set[int] = set()   # slots copied into staging
        self._retired_stores: list = []  # pre-flip stores, kept open
                                         # for readers already inside
        self.server = RpcServer(host, port)
        self.server.add("POST", "/store/insert", self._h_insert)
        self.server.add("POST", "/store/update", self._h_insert)
        self.server.add("GET", "/store/find", self._h_find)
        self.server.add("POST", "/store/delete", self._h_delete)
        self.server.add("POST", "/store/delete_children",
                        self._h_delete_children)
        self.server.add("GET", "/store/list", self._h_list)
        self.server.add("POST", "/store/rename", self._h_rename)
        self.server.add("GET", "/store/dump", self._h_dump)
        self.server.add("GET", "/store/status", self._h_status)

    @property
    def address(self) -> str:
        return self.server.address

    def start(self):
        self.server.start()
        if self.masters:
            try:
                self._lease_once()  # serve with slots from the start
            except RpcError as e:
                glog.warningf("filer.store: initial shard lease "
                              "failed: %s", e)
            self._lease_thread = threading.Thread(
                target=self._lease_loop, daemon=True)
            self._lease_thread.start()

    def stop(self):
        self._lease_stop.set()
        if self._lease_thread is not None:
            self._lease_thread.join(timeout=5)
            self._lease_thread = None
        if self.masters:
            try:  # graceful: free slots now so peers take over instantly
                self._master_call("/filer/shard_lease",
                                  {"holder": self.address,
                                   "release": True})
            except RpcError:
                pass  # the lease TTL frees them anyway
        self.server.stop()
        self.store.close()
        if self._staging is not None:
            self._staging.close()
        for s in self._retired_stores:
            s.close()

    # -- shard-lease protocol -------------------------------------------------
    def _master_call(self, path: str, payload: dict) -> dict:
        last: Optional[RpcError] = None
        for addr in self.masters:
            try:
                return call(addr, path, payload=payload, method="POST",
                            timeout=5)
            except RpcError as e:
                # a follower names the leader: honor the hint directly
                hint = (e.headers or {}).get("X-Raft-Leader", "")
                if hint and hint != addr:
                    try:
                        return call(hint, path, payload=payload,
                                    method="POST", timeout=5)
                    except RpcError as e2:
                        last = e2
                        continue
                last = e
        raise last or RpcError("no master reachable", 503)

    def _lease_once(self):
        r = self._master_call("/filer/shard_lease",
                              {"holder": self.address,
                               "ttl": self._lease_ttl})
        total = int(r.get("slots_total") or self._slots)
        if total != self._slots:
            # the slot map flipped to a new count: adopt the staged
            # layout BEFORE interpreting slot numbers from this reply
            self._adopt_layout(total)
        granted = set(int(s) for s in r.get("slots", []))
        prev = {int(k): v
                for k, v in (r.get("prev") or {}).items() if v}
        with self._lock:
            fresh = granted - self._held
        # pull handovers BEFORE exposing the slots as held: a freshly
        # granted slot must not answer "not found" for entries its
        # previous holder still has (requests 503 until then — the
        # clients' retry window, not a wrong answer)
        not_ready: set[int] = set()
        for slot in sorted(fresh):
            if not self._pull_handover(slot, prev.get(slot) or []):
                not_ready.add(slot)
        with self._lock:
            self._held = granted - not_ready
            self._map = {int(k): v
                         for k, v in (r.get("map") or {}).items()}
            self._epoch = int(r.get("epoch", 0))
            self._resize = r.get("resize")
        rz = r.get("resize")
        if rz and rz.get("phase") == "prepare":
            self._prepare_resize(int(rz["to"]))

    def _pull_handover(self, slot: int, sources) -> bool:
        """Best-effort: copy a newly-granted slot's entries from its
        previous holder(s) — a merge can fold several (graceful
        rebalance keeps data; after a crash the slot starts empty but
        WRITABLE — availability over history).  Returns False when a
        live source is still on a different slot layout (409): the slot
        is withheld this cycle and retried, never served half-filled."""
        if isinstance(sources, str):  # pre-resize masters send one addr
            sources = [sources] if sources else []
        sources = [s for s in sources if s and s != self.address]
        if not sources or slot in self._pulled:
            return True
        if not hasattr(self.store, "load_slot"):
            return True
        ok = True
        for src in sources:
            try:
                r = call(src,
                         f"/store/dump?slot={slot}&slots={self._slots}",
                         timeout=30)
                self.store.load_slot(slot, r.get("entries", []))
                glog.infof("filer.store: slot %d handover from %s "
                           "(%d entries)", slot, src,
                           len(r.get("entries", [])))
            except RpcError as e:
                if e.status == 409:
                    # source still dumping the OLD layout: copying now
                    # would interleave two hash spaces — wait it out
                    ok = False
                    continue
                pass  # holder gone: take over without its entries
        if ok:
            self._pulled.add(slot)
        return ok

    # -- online split / merge participation ------------------------------------
    def _staging_dir(self, to: int) -> str:
        return getattr(self.store, "directory", "") + f".r{to}"

    def _prepare_resize(self, to: int):
        """Prepare phase: stand up the target-layout staging store,
        dual-write into it (enabled the moment _staging is set), copy
        every held slot's entries across, then ack to the master.
        Idempotent — runs once per lease cycle until the commit."""
        if not hasattr(self.store, "dump_slot"):
            # nothing local to re-shard (memory store): ready at once
            self._ack_resize()
            return
        with self._lock:
            if self._staging is None or self._staging_to != to:
                self._staging = ShardedSqliteStore(
                    self._staging_dir(to), shard_count=to)
                self._staging_to = to
                self._staged = set()
        while True:
            with self._lock:
                todo = sorted(self._held - self._staged)
                if not todo:
                    break
                slot = todo[0]
                # the whole slot copy holds the write lock, so no entry
                # can slip between the dump and the dual-write window
                for d in self.store.dump_slot(slot):
                    self._staging.insert_entry(Entry.from_dict(d))
                self._staged.add(slot)
        self._ack_resize()

    def _ack_resize(self):
        try:
            self._master_call("/filer/shard_resize",
                              {"op": "ack", "holder": self.address})
        except RpcError as e:
            glog.v(1).infof("filer.store: resize ack failed: %s", e)

    def _adopt_layout(self, total: int):
        """Commit phase: the map flipped — the staging store becomes THE
        store.  A holder that crashed during prepare (no staging)
        rebuilds the target layout from its local shards first; local
        re-sharding is lossless because the new count divides (or is a
        multiple of) the old one."""
        with self._lock:
            if total == self._slots:
                return
            if hasattr(self.store, "dump_slot"):
                if self._staging is None or self._staging_to != total:
                    glog.warningf(
                        "filer.store: layout flip to %d slots without "
                        "staged data; re-sharding locally", total)
                    staging = ShardedSqliteStore(
                        self._staging_dir(total), shard_count=total)
                    for slot in range(
                            getattr(self.store, "shard_count", 0)):
                        for d in self.store.dump_slot(slot):
                            staging.insert_entry(Entry.from_dict(d))
                    self._staging = staging
                self._retired_stores.append(self.store)
                self.store = self._staging
            self._staging = None
            self._staging_to = 0
            self._staged = set()
            self._slots = total
            self._held = set()
            self._pulled = set()
            self._resize = None
        glog.infof("filer.store: %s adopted %d-slot layout",
                   self.address, total)

    def _lease_loop(self):
        period = max(0.5, self._lease_ttl / 3.0)
        while not self._lease_stop.wait(period):
            try:
                self._lease_once()
            except RpcError as e:
                glog.v(1).infof("filer.store: shard lease renewal "
                                "failed: %s", e)

    # -- shard routing ---------------------------------------------------------
    def _owner(self, dir_path: str) -> Optional[str]:
        """None = serve locally; otherwise the holder to proxy to.
        Raises 503 for an unheld, unassigned slot (a holder's lease must
        land before writes for it can be accepted anywhere)."""
        if not self.masters:
            return None  # standalone mode: this server owns everything
        slot = slot_of(dir_path, self._slots)
        with self._lock:
            if slot in self._held:
                return None
            owner = self._map.get(slot, "")
        if owner and owner != self.address:
            return owner
        raise RpcError(f"shard slot {slot} has no lease holder", 503)

    def _proxy(self, req: Request, owner: str, path: str,
               payload: Optional[dict] = None, method: str = "POST"):
        if req.headers.get(HOP_HEADER):
            # already one hop deep: the map is in flux between us and the
            # first server; fail fast instead of bouncing around
            raise RpcError(
                f"shard map disagreement proxying {path}", 503)
        return call(owner, path, payload=payload, method=method,
                    timeout=20, headers={HOP_HEADER: "1"})

    # -- handlers --------------------------------------------------------------
    def _h_insert(self, req: Request):
        d = req.json()
        entry = Entry.from_dict(d)
        owner = self._owner(entry.parent)
        if owner:
            return self._proxy(req, owner, "/store/insert", payload=d)
        with self._lock:
            self.store.insert_entry(entry)
            if self._staging is not None:
                self._staging.insert_entry(entry)
        return {}

    def _h_find(self, req: Request):
        path = req.param("path", "") or "/"
        parent = path.rsplit("/", 1)[0] or "/"
        owner = self._owner(parent)
        if owner:
            return self._proxy(
                req, owner,
                "/store/find?path=" + urllib.parse.quote(path, safe="/"),
                method="GET")
        try:
            return self.store.find_entry(path).to_dict()
        except NotFoundError:
            raise RpcError(f"{path} not found", 404)

    def _h_delete(self, req: Request):
        d = req.json()
        path = d.get("path", "")
        parent = path.rsplit("/", 1)[0] or "/"
        owner = self._owner(parent)
        if owner:
            return self._proxy(req, owner, "/store/delete", payload=d)
        with self._lock:
            self.store.delete_entry(path)
            if self._staging is not None:
                self._staging.delete_entry(path)
        return {}

    def _h_delete_children(self, req: Request):
        d = req.json()
        with self._lock:
            self.store.delete_folder_children(d.get("path", ""))
            if self._staging is not None:
                self._staging.delete_folder_children(d.get("path", ""))
            holders = (set(self._map.values()) - {self.address}
                       if not req.headers.get(HOP_HEADER) else set())
        # descendant dirs hash to arbitrary slots: fan out to every
        # holder (each fans over its LOCAL shards only — hop guard stops
        # re-broadcast)
        for holder in sorted(holders):
            try:
                call(holder, "/store/delete_children", payload=d,
                     method="POST", timeout=30,
                     headers={HOP_HEADER: "1"})
            except RpcError as e:
                glog.warningf("filer.store: delete_children fan-out to "
                              "%s failed: %s", holder, e)
        return {}

    def _h_list(self, req: Request):
        dir_path = req.param("dir", "") or "/"
        owner = self._owner(dir_path)
        if owner:
            q = urllib.parse.urlencode({
                "dir": dir_path,
                "start": req.param("start", "") or "",
                "include_start": req.param("include_start") or "false",
                "limit": req.param("limit", "1024"),
                "prefix": req.param("prefix", "") or ""})
            return self._proxy(req, owner, "/store/list?" + q,
                               method="GET")
        entries = self.store.list_directory(
            dir_path,
            start_file=req.param("start", "") or "",
            include_start=req.param("include_start") == "true",
            limit=int(req.param("limit", "1024")),
            prefix=req.param("prefix", "") or "")
        return {"entries": [e.to_dict() for e in entries]}

    def _h_rename(self, req: Request):
        """Cross-shard rename: src and dst may live on different
        holders; read src (routed), write dst (routed), delete src
        (routed).  Not atomic across holders — same contract as the
        reference's cross-store moves, where the filer retries."""
        d = req.json()
        src, dst = d.get("path", ""), d.get("new_path", "")
        if not src or not dst:
            raise RpcError("path and new_path required", 400)
        found = self._h_find_path(req, src)
        found["full_path"] = dst
        self._h_insert_routed(req, found)
        self._h_delete_routed(req, src)
        return {"renamed": src, "to": dst}

    def _h_find_path(self, req: Request, path: str) -> dict:
        parent = path.rsplit("/", 1)[0] or "/"
        owner = self._owner(parent)
        if owner:
            return self._proxy(
                req, owner,
                "/store/find?path=" + urllib.parse.quote(path, safe="/"),
                method="GET")
        try:
            return self.store.find_entry(path).to_dict()
        except NotFoundError:
            raise RpcError(f"{path} not found", 404)

    def _h_insert_routed(self, req: Request, d: dict):
        entry = Entry.from_dict(d)
        owner = self._owner(entry.parent)
        if owner:
            self._proxy(req, owner, "/store/insert", payload=d)
            return
        with self._lock:
            self.store.insert_entry(entry)
            if self._staging is not None:
                self._staging.insert_entry(entry)

    def _h_delete_routed(self, req: Request, path: str):
        parent = path.rsplit("/", 1)[0] or "/"
        owner = self._owner(parent)
        if owner:
            self._proxy(req, owner, "/store/delete",
                        payload={"path": path})
            return
        with self._lock:
            self.store.delete_entry(path)
            if self._staging is not None:
                self._staging.delete_entry(path)

    def _h_dump(self, req: Request):
        """Slot handover source: every entry in one local shard slot.
        The caller declares its slot layout (`slots=`); a mismatch is a
        409 — serving slot s of an N-slot space from an M-slot store
        would silently hand over the wrong hash range."""
        slot = int(req.param("slot", "-1"))
        if slot < 0:
            raise RpcError("slot required", 400)
        expected = req.param("slots", "") or ""
        if expected and int(expected) != self._slots:
            raise RpcError(
                f"shard layout mismatch: have {self._slots} slots, "
                f"caller expects {expected}", 409)
        if not hasattr(self.store, "dump_slot"):
            raise RpcError(
                f"{type(self.store).__name__} is not slot-addressable",
                400)
        return {"slot": slot, "slots": self._slots,
                "entries": self.store.dump_slot(slot)}

    def _h_status(self, req: Request):
        with self._lock:
            return {"store": type(self.store).__name__,
                    "cluster": bool(self.masters),
                    "slots": self._slots,
                    "held": sorted(self._held),
                    "epoch": self._epoch,
                    "resize": dict(self._resize) if self._resize
                    else None,
                    "staged": sorted(self._staged),
                    "map": {str(k): v
                            for k, v in sorted(self._map.items())}}


class RemoteStore(FilerStore):
    """FilerStore client against a FilerStoreServer — the stateless-filer
    mode.  Every operation is one pooled keep-alive round trip (the
    substrate retries per rpc/http_rpc's phase-split policy)."""

    def __init__(self, address: str, timeout: float = 20.0):
        self.address = address
        self.timeout = timeout

    def _call(self, path: str, payload=None, method: str = "GET"):
        try:
            return call(self.address, path, payload=payload,
                        method=method, timeout=self.timeout)
        except RpcError as e:
            if e.status == 404:
                raise NotFoundError(str(e))
            raise

    def insert_entry(self, entry: Entry):
        self._call("/store/insert", payload=entry.to_dict(),
                   method="POST")

    update_entry = insert_entry

    def find_entry(self, path: str) -> Entry:
        import urllib.parse

        return Entry.from_dict(self._call(
            "/store/find?path=" + urllib.parse.quote(path, safe="/")))

    def delete_entry(self, path: str):
        self._call("/store/delete", payload={"path": path}, method="POST")

    def delete_folder_children(self, path: str):
        self._call("/store/delete_children", payload={"path": path},
                   method="POST")

    def rename_entry(self, path: str, new_path: str):
        """Server-side (possibly cross-shard) rename."""
        self._call("/store/rename",
                   payload={"path": path, "new_path": new_path},
                   method="POST")

    def list_directory(self, dir_path: str, start_file: str = "",
                       include_start: bool = False, limit: int = 1024,
                       prefix: str = "") -> list[Entry]:
        import urllib.parse

        q = urllib.parse.urlencode({
            "dir": dir_path, "start": start_file,
            "include_start": "true" if include_start else "false",
            "limit": str(limit), "prefix": prefix})
        out = self._call("/store/list?" + q)
        return [Entry.from_dict(d) for d in out.get("entries", [])]
