"""Chunk-level read cache for streaming filer reads.

Parity with weed/filer/reader_cache.go + weed/util/chunk_cache: recently
fetched chunks are kept in RAM (bounded by byte budget, LRU eviction) so
sequential and repeated reads of the same file avoid re-fetching from
volume servers.
"""

from __future__ import annotations

import threading
from collections import OrderedDict


class ChunkCache:
    def __init__(self, capacity_bytes: int = 64 << 20):
        self.capacity = capacity_bytes
        self._data: OrderedDict[str, bytes] = OrderedDict()
        self._bytes = 0
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0

    def get(self, fid: str) -> bytes | None:
        with self._lock:
            data = self._data.get(fid)
            if data is None:
                self.misses += 1
                return None
            self._data.move_to_end(fid)
            self.hits += 1
            return data

    def put(self, fid: str, data: bytes):
        if len(data) > self.capacity:
            return  # oversized: never cache (chunk_cache size gate)
        with self._lock:
            old = self._data.pop(fid, None)
            if old is not None:
                self._bytes -= len(old)
            self._data[fid] = data
            self._bytes += len(data)
            while self._bytes > self.capacity:
                _, evicted = self._data.popitem(last=False)
                self._bytes -= len(evicted)

    def __len__(self) -> int:
        return len(self._data)

    @property
    def size_bytes(self) -> int:
        return self._bytes

    def close(self):
        """No resources to release; shares the tiered cache's interface."""
