"""Chunk-level read cache for streaming filer reads.

Parity with weed/filer/reader_cache.go + weed/util/chunk_cache:
recently fetched chunks are kept in RAM (bounded by byte budget, LRU
eviction) so sequential and repeated reads of the same file avoid
re-fetching from volume servers.

The implementation now lives in the unified read-through cache package
(`seaweedfs_tpu/cache/`); this module keeps the public `ChunkCache`
name for its importers.
"""

from __future__ import annotations

from ..cache.read_cache import ChunkCache  # noqa: F401
