"""Recursive chunk manifests for huge files.

Parity with weed/filer/filechunk_manifest.go: when a file accumulates more
than ManifestBatch chunks, batches of chunks are serialized and stored as
chunks themselves (flagged is_chunk_manifest); readers expand manifests
recursively before resolving visible intervals.  This keeps entry metadata
bounded no matter how large the file grows.
"""

from __future__ import annotations

import json
from typing import Callable

from .entry import FileChunk, total_size

MANIFEST_BATCH = 1000  # filechunk_manifest.go ManifestBatch

SaveFn = Callable[[bytes], FileChunk]  # persist blob, return its chunk
FetchFn = Callable[[str], bytes]  # fetch a chunk's bytes by fid


def has_chunk_manifest(chunks: list[FileChunk]) -> bool:
    return any(c.is_chunk_manifest for c in chunks)


def separate_manifest_chunks(chunks: list[FileChunk]
                             ) -> tuple[list[FileChunk], list[FileChunk]]:
    manifests = [c for c in chunks if c.is_chunk_manifest]
    plain = [c for c in chunks if not c.is_chunk_manifest]
    return manifests, plain


def maybe_manifestize(save: SaveFn, chunks: list[FileChunk],
                      batch: int = MANIFEST_BATCH) -> list[FileChunk]:
    """Fold runs of `batch` plain chunks into manifest chunks
    (doMaybeManifestize, filechunk_manifest.go).  Already-manifest chunks
    pass through; the fold repeats so manifests themselves roll up."""
    manifests, plain = separate_manifest_chunks(chunks)
    if len(plain) < batch:
        return chunks
    out = list(manifests)
    for i in range(0, len(plain) - len(plain) % batch, batch):
        group = plain[i:i + batch]
        body = json.dumps([c.to_dict() for c in group]).encode()
        saved = save(body)
        start = min(c.offset for c in group)
        out.append(FileChunk(
            fid=saved.fid,
            offset=start,
            size=total_size(group) - start,
            etag=saved.etag,
            modified_ts_ns=max(c.modified_ts_ns for c in group),
            is_chunk_manifest=True,
            cipher_key=saved.cipher_key))
    out.extend(plain[len(plain) - len(plain) % batch:])
    return maybe_manifestize(save, out, batch)


def resolve_chunk_manifest(fetch: FetchFn, chunks: list[FileChunk],
                           keep_manifests: bool = False
                           ) -> list[FileChunk]:
    """Expand manifest chunks (recursively) into the full plain chunk list
    (ResolveChunkManifest).  With keep_manifests, the manifest chunks
    themselves stay in the output — deletion needs every fid, including
    intermediate manifest blobs."""
    out: list[FileChunk] = []
    for c in chunks:
        if not c.is_chunk_manifest:
            out.append(c)
            continue
        if keep_manifests:
            out.append(c)
        blob = fetch(c.fid)
        if c.cipher_key:  # manifest blobs encrypt like data chunks
            from ..util.cipher import decrypt
            blob = decrypt(blob, c.cipher_key)
        nested = [FileChunk.from_dict(d)
                  for d in json.loads(blob.decode())]
        out.extend(resolve_chunk_manifest(fetch, nested, keep_manifests))
    return out
