"""Multi-filer HA: aggregate peer filers' metadata change feeds.

Parity with weed/filer/meta_aggregator.go + meta_replay.go: each filer
follows its peers' metadata subscriptions, merging their events into one
aggregated feed that downstream subscribers (replication, backup, other
filers) consume; a fresh filer bootstraps its store by replaying a peer's
feed from the beginning (filer.go:75-105).
"""

from __future__ import annotations

import threading
from typing import Callable, Optional

from ..rpc.http_rpc import RpcError, call
from .entry import Entry
from .filer import LOG_BUFFER_CAPACITY, Filer
from .filer_store import NotFoundError


def apply_meta_event(filer: Filer, event: dict):
    """Replay one change event into a local filer (meta_replay.go
    ReplayMetadataEvent): create/update/delete/rename all reduce to
    delete-old + insert-new."""
    old, new = event.get("old_entry"), event.get("new_entry")
    if old and (not new or old["full_path"] != new["full_path"]):
        try:
            filer.store.delete_entry(old["full_path"])
        except NotFoundError:
            pass
    if new:
        entry = Entry.from_dict(new)
        filer._ensure_parents(entry.parent)
        filer.store.insert_entry(entry)


class MetaAggregator:
    def __init__(self, peers: list[str],
                 on_event: Optional[Callable[[str, dict], None]] = None,
                 poll_interval: float = 0.5):
        self.peers = list(peers)
        self.on_event = on_event
        self.poll_interval = poll_interval
        self._events: list[tuple[str, dict]] = []  # (peer, event)
        self._cursor: dict[str, int] = {p: 0 for p in self.peers}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []

    def start(self):
        for peer in self.peers:
            t = threading.Thread(target=self._follow, args=(peer,),
                                 daemon=True)
            t.start()
            self._threads.append(t)

    def stop(self):
        self._stop.set()
        for t in self._threads:
            t.join(timeout=5)
        self._threads = []

    def poll_once(self, peer: str) -> int:
        """One subscription pull from a peer; returns new-event count."""
        since = self._cursor.get(peer, 0)
        r = call(peer, f"/metadata/subscribe?since={since}", timeout=10)
        events = r.get("events", [])
        if not events:
            return 0
        with self._lock:
            for e in events:
                self._events.append((peer, e))
                self._cursor[peer] = max(self._cursor.get(peer, 0),
                                         e["ts_ns"])
            if len(self._events) > LOG_BUFFER_CAPACITY:
                self._events = self._events[-LOG_BUFFER_CAPACITY:]
        if self.on_event:
            for e in events:
                self.on_event(peer, e)
        return len(events)

    def _follow(self, peer: str):
        while not self._stop.is_set():
            try:
                self.poll_once(peer)
            except RpcError:
                pass
            self._stop.wait(self.poll_interval)

    def events(self, since_ns: int = 0) -> list[dict]:
        """Merged feed across peers, timestamp-ordered."""
        with self._lock:
            merged = [e for _, e in self._events if e["ts_ns"] > since_ns]
        return sorted(merged, key=lambda e: e["ts_ns"])

    @staticmethod
    def bootstrap_from_peer(peer: str, filer: Filer) -> int:
        """Fresh-store catch-up: replay a peer's full feed into the local
        store (filer.go:75-94 maybeBootstrapFromPeers).  Returns count."""
        r = call(peer, "/metadata/subscribe?since=0", timeout=60)
        events = r.get("events", [])
        for e in events:
            apply_meta_event(filer, e)
        return len(events)
