"""Filer core: path -> Entry CRUD with directory management + change log.

Parity with weed/filer/filer.go:34-105: auto-creation of parent
directories on insert, recursive delete with chunk reclamation hooks,
rename, hardlink indirection (filer/filerstore_wrapper.go), and the
metadata change log (filer_notify.go:19-111): every mutation appends an
EventNotification to a LogBuffer that is flushed into date-partitioned
segment files under /topics/.system/log stored in the filer itself;
subscribers replay the persisted log then tail the in-RAM buffer
(filer_grpc_server_sub_meta.go).
"""

from __future__ import annotations

import json
import threading
import time
import uuid
from typing import Callable, Optional

from ..util.log_buffer import LogBuffer
from .entry import Attr, Entry, FileChunk, new_directory_entry
from .filer_store import FilerStore, MemoryStore, NotFoundError

LOG_BUFFER_CAPACITY = 10000
SYSTEM_LOG_DIR = "/topics/.system/log"  # filer_notify.go SystemLogDir
HARDLINK_DIR = "/etc/.hardlinks"  # hardlink indirection records


class MetaEvent:
    __slots__ = ("ts_ns", "directory", "old_entry", "new_entry")

    def __init__(self, directory: str, old_entry: Optional[dict],
                 new_entry: Optional[dict], ts_ns: Optional[int] = None):
        self.ts_ns = ts_ns if ts_ns is not None else time.time_ns()
        self.directory = directory
        self.old_entry = old_entry
        self.new_entry = new_entry

    def to_dict(self) -> dict:
        return {"ts_ns": self.ts_ns, "directory": self.directory,
                "old_entry": self.old_entry, "new_entry": self.new_entry}


class Filer:
    def __init__(self, store: Optional[FilerStore] = None,
                 meta_log_flush_interval: float = 60.0):
        self.store = store or MemoryStore()
        self.lock = threading.RLock()
        self.on_delete_chunks: Optional[Callable[[list[FileChunk]], None]] \
            = None
        # change-log buffer; flushed into /topics/.system/log segments.
        # Until persistence is enabled it acts as a capped ring buffer.
        self.meta_log_enabled = False
        self._log_buffer = LogBuffer(self._flush_meta_segment,
                                     meta_log_flush_interval,
                                     max_entries=LOG_BUFFER_CAPACITY)
        self._last_event_ns = 0
        # optional external sink for every change event
        # (weed/notification; wired from notification.toml)
        self.notification_queue = None
        # per-thread signature list stamped onto emitted events; a sync
        # client sets its own cluster signature so active-active
        # replication can skip events it produced itself
        # (filer_pb EventNotification.signatures / IsFromOtherCluster)
        self._sig_local = threading.local()

    def set_event_signatures(self, signatures: Optional[list]):
        self._sig_local.value = signatures or None

    # -- change log (filer_notify.go NotifyUpdateEvent) ----------------------
    def _notify(self, directory: str, old_entry: Optional[Entry],
                new_entry: Optional[Entry]):
        if (directory + "/").startswith(SYSTEM_LOG_DIR + "/"):
            return  # never log the log (filer_notify.go:21 guard)
        # strictly-monotonic event timestamps so since_ns cursors never skip
        ts = time.time_ns()
        if ts <= self._last_event_ns:
            ts = self._last_event_ns + 1
        self._last_event_ns = ts
        event = MetaEvent(
            directory,
            old_entry.to_dict() if old_entry else None,
            new_entry.to_dict() if new_entry else None, ts_ns=ts)
        record = event.to_dict()
        sigs = getattr(self._sig_local, "value", None)
        if sigs:
            record["signatures"] = list(sigs)
        self._log_buffer.add(ts, record)
        if self.notification_queue is not None:
            key = ((new_entry or old_entry).full_path
                   if (new_entry or old_entry) else directory)
            try:
                self.notification_queue.send(key, record)
            except Exception as e:  # a broken sink must not fail writes
                from ..util import glog

                glog.errorf("notification send %s: %s", key, e)

    def enable_meta_log(self, background: bool = True):
        """Turn on persistence of the change log into date-partitioned
        segment files under /topics/.system/log (filer_notify.go:62-111)."""
        self.meta_log_enabled = True
        self._log_buffer.max_entries = None  # flushes bound RAM instead
        if background:
            self._log_buffer.start()

    def flush_meta_log(self) -> int:
        return self._log_buffer.flush()

    def _flush_meta_segment(self, start_ns: int, stop_ns: int,
                            events: list[dict]):
        if not self.meta_log_enabled:
            return
        # /topics/.system/log/2026-07-29/11-30-05.123456 (one file per flush)
        t = time.gmtime(start_ns / 1e9)
        day = time.strftime("%Y-%m-%d", t)
        name = time.strftime("%H-%M-%S", t) + f".{start_ns % 10**9:09d}"
        body = "\n".join(json.dumps(e) for e in events).encode()
        entry = Entry(
            full_path=f"{SYSTEM_LOG_DIR}/{day}/{name}",
            attr=Attr(mtime=time.time(), crtime=time.time(),
                      file_size=len(body)),
            content=body,
            extended={"start_ns": start_ns, "stop_ns": stop_ns})
        self.create_entry(entry)

    def read_persisted_meta(self, since_ns: int = 0) -> list[dict]:
        """Replay flushed events from the date-partitioned segment files
        (ReadPersistedLogBuffer, filer_notify.go:88-111).  Whole days older
        than the cursor's date are skipped without listing their segments."""
        out: list[dict] = []
        try:
            days = self.store.list_directory(SYSTEM_LOG_DIR, limit=100000)
        except NotFoundError:
            return out
        since_day = time.strftime("%Y-%m-%d",
                                  time.gmtime(since_ns / 1e9)) \
            if since_ns else ""
        for day in sorted(days, key=lambda e: e.name):
            if day.name < since_day:
                continue
            segments = self.store.list_directory(day.full_path, limit=100000)
            for seg in sorted(segments, key=lambda e: e.name):
                if seg.extended.get("stop_ns", 1 << 63) <= since_ns:
                    continue
                for line in seg.content.decode().splitlines():
                    event = json.loads(line)
                    if event["ts_ns"] > since_ns:
                        out.append(event)
        return out

    def subscribe_metadata(self, since_ns: int = 0,
                           path_prefix: str = "/") -> list[dict]:
        """Replay persisted segments, then the in-RAM tail — the reference's
        replay-then-tail subscription contract
        (filer_grpc_server_sub_meta.go).  Events stay visible in RAM while
        a flush is persisting them, so dedupe on the (unique, strictly
        monotonic) ts_ns."""
        events = self.read_persisted_meta(since_ns) \
            + self._log_buffer.read_since(since_ns)
        prefix = path_prefix.rstrip("/") + "/"
        seen: set[int] = set()
        out = []
        for e in events:
            if e["ts_ns"] in seen or \
                    not (e["directory"] + "/").startswith(prefix):
                continue
            seen.add(e["ts_ns"])
            out.append(e)
        return out

    def close(self):
        """Flush any buffered change-log events and stop the flusher."""
        self._log_buffer.stop()

    # -- hardlinks (filerstore_wrapper.go hardlink indirection) --------------
    def create_hard_link(self, src_path: str, dst_path: str):
        """Make dst share src's content: both entries carry the same
        hard_link_id pointing at a shared record holding attr+chunks with a
        refcount; deletes reclaim chunks only at refcount zero."""
        src_path = self._norm(src_path)
        dst_path = self._norm(dst_path)
        with self.lock:
            src = self.store.find_entry(src_path)
            if src.is_directory:
                raise ValueError("cannot hardlink a directory")
            existing_dst = self._find_or_none(dst_path)
            if existing_dst is not None and existing_dst.is_directory:
                raise ValueError(f"{dst_path} is a directory")
            if not src.hard_link_id:
                src_before = Entry.from_dict(src.to_dict())
                src.hard_link_id = uuid.uuid4().hex
                self._write_hardlink(src.hard_link_id, src, refcount=1)
                # the entry itself becomes a pointer; replicas following the
                # change feed must see the conversion
                src.chunks, src.content = [], b""
                self.store.update_entry(src)
                self._notify(src.parent, src_before, src)
            record = self._read_hardlink(src.hard_link_id)
            record["refcount"] += 1
            self._put_hardlink(src.hard_link_id, record)
            try:
                dst = Entry(full_path=dst_path,
                            attr=Attr(mtime=time.time(), crtime=time.time(),
                                      mode=src.attr.mode),
                            hard_link_id=src.hard_link_id)
                self.create_entry(dst)
            except Exception:
                record["refcount"] -= 1  # roll back the reference bump
                self._put_hardlink(src.hard_link_id, record)
                raise

    def _hardlink_path(self, link_id: str) -> str:
        return f"{HARDLINK_DIR}/{link_id}"

    def _write_hardlink(self, link_id: str, src: Entry, refcount: int):
        self._put_hardlink(link_id, {
            "refcount": refcount,
            "attr": src.to_dict()["attr"],
            "chunks": [c.to_dict() for c in src.chunks],
            "content": src.content.hex() if src.content else "",
            "extended": src.extended,
        })

    def _put_hardlink(self, link_id: str, record: dict):
        body = json.dumps(record).encode()
        self._ensure_parents(HARDLINK_DIR)
        entry = Entry(full_path=self._hardlink_path(link_id),
                      attr=Attr(mtime=time.time(), crtime=time.time(),
                                file_size=len(body)),
                      content=body)
        old = self._find_or_none(entry.full_path)
        self.store.insert_entry(entry)
        # shared records ride the change log so feed replicas can resolve
        # hardlinked entries (they'd otherwise read back empty)
        self._notify(HARDLINK_DIR, old, entry)

    def _read_hardlink(self, link_id: str) -> dict:
        return json.loads(
            self.store.find_entry(self._hardlink_path(link_id)).content)

    def _resolve_hardlink(self, entry: Entry) -> Entry:
        """Materialize a hardlink pointer entry from its shared record.
        Returns a fresh Entry — never mutates the store's object (the
        MemoryStore hands out its stored instances)."""
        if not entry.hard_link_id:
            return entry
        try:
            record = self._read_hardlink(entry.hard_link_id)
        except NotFoundError:
            return entry
        resolved = Entry.from_dict(entry.to_dict())
        a = record["attr"]
        resolved.attr.mime = a.get("mime", resolved.attr.mime)
        resolved.attr.md5 = a.get("md5", "")
        resolved.attr.file_size = a.get("file_size", 0)
        resolved.chunks = [FileChunk.from_dict(c) for c in record["chunks"]]
        resolved.content = bytes.fromhex(record["content"]) \
            if record.get("content") else b""
        resolved.extended = record.get("extended", {}) or resolved.extended
        return resolved

    # -- CRUD ----------------------------------------------------------------
    def create_entry(self, entry: Entry):
        pending: list[FileChunk] = []
        with self.lock:
            self._ensure_parents(entry.parent)
            old = self._find_or_none(entry.full_path)
            if old is not None and old.is_directory and not entry.is_directory:
                raise ValueError(
                    f"{entry.full_path} is a directory")
            self.store.insert_entry(entry)
            self._notify(entry.parent, old, entry)
            if old is None:
                return
            if old.hard_link_id:
                # overwrote a hardlink pointer: drop its reference (even
                # when both point at the same record — the new entry holds
                # its own freshly-counted reference from create_hard_link)
                self._release_file(old, pending)
            elif old.chunks:
                # overwritten file: reclaim chunks no longer referenced
                kept = {c.fid for c in entry.chunks}
                pending += [c for c in old.chunks if c.fid not in kept]
        self._reclaim(pending)

    def _ensure_parents(self, dir_path: str):
        if dir_path in ("", "/"):
            return
        try:
            existing = self.store.find_entry(dir_path)
            if not existing.is_directory:
                raise ValueError(f"{dir_path} is a file")
            return
        except NotFoundError:
            pass
        self._ensure_parents(dir_path.rsplit("/", 1)[0] or "/")
        d = new_directory_entry(dir_path)
        self.store.insert_entry(d)
        self._notify(d.parent, None, d)

    @staticmethod
    def _expired(entry: Entry) -> bool:
        """TTL'd file entries expire ttl_sec after creation
        (entry.go Entry.IsExpired semantics); directories never do."""
        return (entry.attr.ttl_sec > 0 and not entry.is_directory
                and entry.attr.crtime + entry.attr.ttl_sec < time.time())

    def find_entry(self, path: str) -> Entry:
        entry = self._resolve_hardlink(
            self.store.find_entry(self._norm(path)))
        if self._expired(entry):
            # lazily reap the metadata; the TTL volume holding the
            # chunks expires wholesale on the cluster side, so no
            # per-chunk delete RPCs on the read path — and re-verify
            # under the lock so a concurrent re-create of the same path
            # is never deleted.  Any release RPCs (hardlink refcount
            # drop) run AFTER the lock: a slow volume server must not
            # stall every metadata operation behind a read
            pending: list[FileChunk] = []
            with self.lock:
                current = self._find_or_none(entry.full_path)
                if current is not None and self._expired(current):
                    try:
                        # hardlinked entries must still release their
                        # refcount; plain files skip per-chunk delete
                        # RPCs (the TTL volume expires them wholesale)
                        pending = self._delete_entry_locked(
                            entry.full_path,
                            delete_chunks=bool(current.hard_link_id))
                    except (NotFoundError, ValueError):
                        pass
            self._reclaim(pending)
            raise NotFoundError(path)
        return entry

    def _find_or_none(self, path: str) -> Optional[Entry]:
        try:
            return self.store.find_entry(path)
        except NotFoundError:
            return None

    def update_entry(self, entry: Entry):
        with self.lock:
            old = self._find_or_none(entry.full_path)
            if old is not None and old.hard_link_id:
                # write-through to the shared record so every link sees it
                entry.hard_link_id = old.hard_link_id
                record = self._read_hardlink(old.hard_link_id)
                self._write_hardlink(old.hard_link_id, entry,
                                     refcount=record["refcount"])
                entry = Entry(full_path=entry.full_path, attr=entry.attr,
                              extended=entry.extended,
                              hard_link_id=old.hard_link_id)
            self.store.update_entry(entry)
            self._notify(entry.parent, old, entry)

    def delete_entry(self, path: str, recursive: bool = False,
                     ignore_recursive_error: bool = False,
                     delete_chunks: bool = True):
        """filer_delete_entry.go semantics: directories need recursive=True
        unless empty; file deletion reclaims chunks unless the caller opts
        out (the HTTP skipChunkDelete param, used by metadata-only
        restores).  Chunk-delete RPCs are issued after the filer lock is
        released — a slow volume server must not stall metadata ops."""
        with self.lock:
            pending = self._delete_entry_locked(path, recursive,
                                                delete_chunks)
        self._reclaim(pending)

    def _delete_entry_locked(self, path: str, recursive: bool = False,
                             delete_chunks: bool = True
                             ) -> list[FileChunk]:
        """Metadata-side delete under self.lock; returns the chunks to
        reclaim once the caller has dropped the lock."""
        path = self._norm(path)
        pending: list[FileChunk] = []
        entry = self.store.find_entry(path)
        if entry.is_directory:
            children = self.store.list_directory(path, limit=1)
            if children and not recursive:
                raise ValueError(f"{path} is not empty")
            self._delete_recursive(path, delete_chunks, pending)
            self.store.delete_entry(path)
        else:
            self.store.delete_entry(path)
            if delete_chunks:
                self._release_file(entry, pending)
        self._notify(entry.parent, entry, None)
        return pending

    def _reclaim(self, chunks: list[FileChunk]):
        """Fire the chunk-delete callback (volume-server RPCs) — call
        with the filer lock RELEASED."""
        if chunks and self.on_delete_chunks:
            self.on_delete_chunks(chunks)

    def _release_file(self, entry: Entry, pending: list[FileChunk]):
        """Collect a deleted file's reclaimable chunks into `pending`,
        honoring hardlink refcounts.  Store mutations happen here (under
        the caller's lock); the delete RPCs happen later via _reclaim."""
        if entry.hard_link_id:
            try:
                record = self._read_hardlink(entry.hard_link_id)
            except NotFoundError:
                return
            record["refcount"] -= 1
            if record["refcount"] > 0:
                self._put_hardlink(entry.hard_link_id, record)
                return
            record_path = self._hardlink_path(entry.hard_link_id)
            record_entry = self._find_or_none(record_path)
            self.store.delete_entry(record_path)
            if record_entry is not None:
                self._notify(HARDLINK_DIR, record_entry, None)
            pending += [FileChunk.from_dict(c) for c in record["chunks"]]
        else:
            pending += entry.chunks

    def _delete_recursive(self, dir_path: str, delete_chunks: bool,
                          pending: list[FileChunk]):
        while True:
            children = self.store.list_directory(dir_path, limit=1024)
            if not children:
                break
            for child in children:
                if child.is_directory:
                    self._delete_recursive(child.full_path, delete_chunks,
                                           pending)
                    self.store.delete_entry(child.full_path)
                else:
                    self.store.delete_entry(child.full_path)
                    if delete_chunks:
                        self._release_file(child, pending)

    def list_directory(self, path: str, start_file: str = "",
                       limit: int = 1024, prefix: str = "",
                       include_start: bool = False,
                       name_pattern: str = "",
                       name_pattern_exclude: str = "") -> list[Entry]:
        """List children, filtering expired entries BEFORE the limit
        counts them (a page of expired entries must not truncate
        pagination) and applying optional glob patterns the way the
        reference's filer_search.go does: a literal pattern head becomes
        a store-side prefix, the rest matches fnmatch-style, and
        name_pattern_exclude drops matching names."""
        import fnmatch

        path = self._norm(path)
        if name_pattern and not prefix:
            # split the pattern at the first wildcard: the literal head
            # narrows the store scan (splitPattern, filer_search.go:11-21)
            cut = len(name_pattern)
            for wc in "*?[":
                pos = name_pattern.find(wc)
                if pos >= 0:
                    cut = min(cut, pos)
            prefix = name_pattern[:cut]
        out: list[Entry] = []
        cursor, inc = start_file, include_start
        while len(out) < limit:
            want = limit - len(out)
            batch = self.store.list_directory(
                path, start_file=cursor, limit=want, prefix=prefix,
                include_start=inc)
            if not batch:
                break
            for e in batch:
                if self._expired(e):
                    continue
                if name_pattern and not fnmatch.fnmatchcase(
                        e.name, name_pattern):
                    continue
                if name_pattern_exclude and fnmatch.fnmatchcase(
                        e.name, name_pattern_exclude):
                    continue
                out.append(self._resolve_hardlink(e)
                           if e.hard_link_id else e)
            cursor, inc = batch[-1].name, False
            if len(batch) < want:
                break
        return out

    # -- generic KV (filer_grpc_server_kv.go KvGet/KvPut) ---------------------
    # Clients use this for small cluster-wide state.  Stored as raw
    # store entries under a reserved prefix (every store kind inherits
    # it); store-level access skips event notification like the
    # reference's Store.KvPut does.
    KV_DIR = "/etc/seaweedfs/kv"

    def _kv_path(self, key: bytes) -> str:
        return f"{self.KV_DIR}/{key.hex()}"

    def kv_put(self, key: bytes, value: bytes):
        """Set key -> value; empty value deletes (KvPut semantics)."""
        if not value:
            self.kv_delete(key)
            return
        entry = Entry(full_path=self._kv_path(key),
                      attr=Attr(crtime=time.time(), mtime=time.time()))
        entry.content = value
        with self.lock:
            self.store.insert_entry(entry)

    def kv_get(self, key: bytes) -> Optional[bytes]:
        """Value for key, or None when absent (ErrKvNotFound -> empty)."""
        try:
            return bytes(self.store.find_entry(
                self._kv_path(key)).content)
        except NotFoundError:
            return None

    def kv_delete(self, key: bytes):
        with self.lock:
            try:
                self.store.delete_entry(self._kv_path(key))
            except NotFoundError:
                pass

    def rename(self, old_path: str, new_path: str):
        """Atomic single-entry rename + recursive subtree move
        (filer_rename.go).  The change event carries both the old and new
        entry so feed replicas delete the old path (meta_replay.go)."""
        pending: list[FileChunk] = []
        with self.lock:
            self._rename_locked(self._norm(old_path), self._norm(new_path),
                                pending)
        self._reclaim(pending)

    def _rename_locked(self, old_path: str, new_path: str,
                       pending: list[FileChunk]):
        entry = self.store.find_entry(old_path)
        dst = self._find_or_none(new_path)
        if dst is not None:
            if dst.is_directory and not entry.is_directory:
                raise ValueError(f"{new_path} is a directory")
            # overwrite drops one reference; RPCs deferred past the lock
            self._release_file(dst, pending)
        self._ensure_parents(new_path.rsplit("/", 1)[0] or "/")
        if entry.is_directory:
            for child in self.store.list_directory(old_path,
                                                   limit=100000):
                self._rename_locked(child.full_path,
                                    new_path + "/" + child.name, pending)
        old_snapshot = Entry.from_dict(entry.to_dict())
        entry.full_path = new_path
        self.store.insert_entry(entry)
        self.store.delete_entry(old_path)
        self._notify(entry.parent, old_snapshot, entry)

    @staticmethod
    def _norm(path: str) -> str:
        if not path.startswith("/"):
            path = "/" + path
        if len(path) > 1:
            path = path.rstrip("/")
        return path
