"""Filer core: path -> Entry CRUD with directory management + change log.

Parity with weed/filer/filer.go:34-105: auto-creation of parent
directories on insert, recursive delete with chunk reclamation hooks,
rename, and the metadata change log (filer_notify.go:19-111): every
mutation appends an EventNotification that subscribers can replay/tail
(filer_grpc_server_sub_meta.go).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Iterator, Optional

from .entry import Attr, Entry, FileChunk, new_directory_entry
from .filer_store import FilerStore, MemoryStore, NotFoundError

LOG_BUFFER_CAPACITY = 10000


class MetaEvent:
    __slots__ = ("ts_ns", "directory", "old_entry", "new_entry")

    def __init__(self, directory: str, old_entry: Optional[dict],
                 new_entry: Optional[dict]):
        self.ts_ns = time.time_ns()
        self.directory = directory
        self.old_entry = old_entry
        self.new_entry = new_entry

    def to_dict(self) -> dict:
        return {"ts_ns": self.ts_ns, "directory": self.directory,
                "old_entry": self.old_entry, "new_entry": self.new_entry}


class Filer:
    def __init__(self, store: Optional[FilerStore] = None):
        self.store = store or MemoryStore()
        self.lock = threading.RLock()
        # ring buffer of change events (util/log_buffer analogue)
        self._log: list[MetaEvent] = []
        self._log_lock = threading.Lock()
        self.on_delete_chunks: Optional[Callable[[list[FileChunk]], None]] \
            = None

    # -- change log (filer_notify.go NotifyUpdateEvent) ----------------------
    def _notify(self, directory: str, old_entry: Optional[Entry],
                new_entry: Optional[Entry]):
        event = MetaEvent(
            directory,
            old_entry.to_dict() if old_entry else None,
            new_entry.to_dict() if new_entry else None)
        with self._log_lock:
            self._log.append(event)
            if len(self._log) > LOG_BUFFER_CAPACITY:
                self._log = self._log[-LOG_BUFFER_CAPACITY:]

    def subscribe_metadata(self, since_ns: int = 0,
                           path_prefix: str = "/") -> list[dict]:
        """Replay change events newer than since_ns under path_prefix."""
        with self._log_lock:
            return [e.to_dict() for e in self._log
                    if e.ts_ns > since_ns
                    and (e.directory + "/").startswith(
                        path_prefix.rstrip("/") + "/")]

    # -- CRUD ----------------------------------------------------------------
    def create_entry(self, entry: Entry):
        with self.lock:
            self._ensure_parents(entry.parent)
            old = self._find_or_none(entry.full_path)
            if old is not None and old.is_directory and not entry.is_directory:
                raise ValueError(
                    f"{entry.full_path} is a directory")
            self.store.insert_entry(entry)
            self._notify(entry.parent, old, entry)
            if (old is not None and self.on_delete_chunks
                    and old.chunks):
                # overwritten file: reclaim chunks no longer referenced
                kept = {c.fid for c in entry.chunks}
                orphaned = [c for c in old.chunks if c.fid not in kept]
                if orphaned:
                    self.on_delete_chunks(orphaned)

    def _ensure_parents(self, dir_path: str):
        if dir_path in ("", "/"):
            return
        try:
            existing = self.store.find_entry(dir_path)
            if not existing.is_directory:
                raise ValueError(f"{dir_path} is a file")
            return
        except NotFoundError:
            pass
        self._ensure_parents(dir_path.rsplit("/", 1)[0] or "/")
        d = new_directory_entry(dir_path)
        self.store.insert_entry(d)
        self._notify(d.parent, None, d)

    def find_entry(self, path: str) -> Entry:
        return self.store.find_entry(self._norm(path))

    def _find_or_none(self, path: str) -> Optional[Entry]:
        try:
            return self.store.find_entry(path)
        except NotFoundError:
            return None

    def update_entry(self, entry: Entry):
        with self.lock:
            old = self._find_or_none(entry.full_path)
            self.store.update_entry(entry)
            self._notify(entry.parent, old, entry)

    def delete_entry(self, path: str, recursive: bool = False,
                     ignore_recursive_error: bool = False):
        """filer_delete_entry.go semantics: directories need recursive=True
        unless empty; file deletion reclaims chunks."""
        path = self._norm(path)
        with self.lock:
            entry = self.store.find_entry(path)
            if entry.is_directory:
                children = self.store.list_directory(path, limit=1)
                if children and not recursive:
                    raise ValueError(f"{path} is not empty")
                self._delete_recursive(path)
                self.store.delete_entry(path)
            else:
                self.store.delete_entry(path)
                if self.on_delete_chunks and entry.chunks:
                    self.on_delete_chunks(entry.chunks)
            self._notify(entry.parent, entry, None)

    def _delete_recursive(self, dir_path: str):
        while True:
            children = self.store.list_directory(dir_path, limit=1024)
            if not children:
                break
            for child in children:
                if child.is_directory:
                    self._delete_recursive(child.full_path)
                    self.store.delete_entry(child.full_path)
                else:
                    self.store.delete_entry(child.full_path)
                    if self.on_delete_chunks and child.chunks:
                        self.on_delete_chunks(child.chunks)

    def list_directory(self, path: str, start_file: str = "",
                       limit: int = 1024, prefix: str = "",
                       include_start: bool = False) -> list[Entry]:
        return self.store.list_directory(
            self._norm(path), start_file=start_file, limit=limit,
            prefix=prefix, include_start=include_start)

    def rename(self, old_path: str, new_path: str):
        """Atomic single-entry rename + recursive subtree move
        (filer_rename.go)."""
        old_path, new_path = self._norm(old_path), self._norm(new_path)
        with self.lock:
            entry = self.store.find_entry(old_path)
            dst = self._find_or_none(new_path)
            if dst is not None:
                if dst.is_directory and not entry.is_directory:
                    raise ValueError(f"{new_path} is a directory")
                if self.on_delete_chunks and dst.chunks:
                    self.on_delete_chunks(dst.chunks)
            self._ensure_parents(new_path.rsplit("/", 1)[0] or "/")
            if entry.is_directory:
                for child in self.store.list_directory(old_path,
                                                       limit=100000):
                    self.rename(child.full_path,
                                new_path + "/" + child.name)
            entry.full_path = new_path
            self.store.insert_entry(entry)
            self.store.delete_entry(old_path)
            self._notify(entry.parent, None, entry)

    @staticmethod
    def _norm(path: str) -> str:
        if not path.startswith("/"):
            path = "/" + path
        if len(path) > 1:
            path = path.rstrip("/")
        return path
