"""Chunked-file model: overlapping chunk resolution into visible intervals.

Parity with weed/filer/filechunks.go: a file's chunk list may contain
overlapping writes (later mtime wins); readers need the non-overlapping
"visible" view, and range reads need (chunk, offset-in-chunk, size) spans.
ETag of a multi-chunk file = md5 of the concatenated chunk md5s
(filer/filechunks.go ETagChunks).
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass

from .entry import FileChunk


@dataclass
class VisibleInterval:
    start: int
    stop: int
    fid: str
    modified_ts_ns: int
    chunk_offset: int  # where `start` falls inside the chunk
    chunk_size: int
    cipher_key: bytes = b""


def non_overlapping_visible_intervals(chunks: list[FileChunk]
                                      ) -> list[VisibleInterval]:
    """Resolve overlapping chunks: later-modified chunks shadow earlier
    ones (NonOverlappingVisibleIntervals, filechunks.go:14-80)."""
    visibles: list[VisibleInterval] = []
    for chunk in sorted(chunks, key=lambda c: (c.modified_ts_ns, c.fid)):
        new_v = VisibleInterval(
            start=chunk.offset, stop=chunk.offset + chunk.size,
            fid=chunk.fid, modified_ts_ns=chunk.modified_ts_ns,
            chunk_offset=0, chunk_size=chunk.size,
            cipher_key=chunk.cipher_key)
        out: list[VisibleInterval] = []
        for v in visibles:
            if v.stop <= new_v.start or v.start >= new_v.stop:
                out.append(v)  # no overlap
                continue
            if v.start < new_v.start:
                out.append(VisibleInterval(
                    start=v.start, stop=new_v.start, fid=v.fid,
                    modified_ts_ns=v.modified_ts_ns,
                    chunk_offset=v.chunk_offset,
                    chunk_size=v.chunk_size,
                    cipher_key=v.cipher_key))
            if v.stop > new_v.stop:
                out.append(VisibleInterval(
                    start=new_v.stop, stop=v.stop, fid=v.fid,
                    modified_ts_ns=v.modified_ts_ns,
                    chunk_offset=v.chunk_offset + (new_v.stop - v.start),
                    chunk_size=v.chunk_size,
                    cipher_key=v.cipher_key))
        out.append(new_v)
        visibles = sorted(out, key=lambda v: v.start)
    return visibles


@dataclass
class ChunkView:
    fid: str
    offset_in_chunk: int
    size: int
    logic_offset: int
    cipher_key: bytes = b""


def read_chunk_views(chunks: list[FileChunk], offset: int,
                     size: int) -> list[ChunkView]:
    """Spans to fetch for a [offset, offset+size) read
    (ViewFromChunks/ReadFromChunks, filechunks_read.go)."""
    views: list[ChunkView] = []
    stop = offset + size
    for v in non_overlapping_visible_intervals(chunks):
        if v.stop <= offset or v.start >= stop:
            continue
        start = max(offset, v.start)
        end = min(stop, v.stop)
        views.append(ChunkView(
            fid=v.fid,
            offset_in_chunk=v.chunk_offset + (start - v.start),
            size=end - start,
            logic_offset=start,
            cipher_key=v.cipher_key))
    return views


def etag_of_chunks(chunks: list[FileChunk]) -> str:
    """md5-of-md5s for multi-chunk files (filechunks.go ETagChunks)."""
    if len(chunks) == 1:
        return chunks[0].etag
    h = hashlib.md5()
    for c in sorted(chunks, key=lambda c: c.offset):
        h.update(bytes.fromhex(c.etag) if c.etag else b"")
    return f"{h.hexdigest()}-{len(chunks)}"
