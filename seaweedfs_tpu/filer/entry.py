"""Filer entry model: a path plus attributes plus a chunk list.

Parity with weed/filer/entry.go:11-45 + filer.proto Entry/FuseAttributes:
an Entry is either a directory (no chunks) or a file assembled from
FileChunks, each pointing at a needle (fid) in a volume, with offset/size
describing where the chunk sits in the logical file.  Small files may be
inlined in `content` (filer_server_handlers_write_autochunk.go
saveSmallContentToMetadata).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Optional


@dataclass
class FileChunk:
    fid: str  # "vid,keyhexcookiehex"
    offset: int  # position in the logical file
    size: int  # PLAINTEXT size (ciphered blobs are larger on the volume)
    etag: str = ""
    modified_ts_ns: int = 0
    is_chunk_manifest: bool = False  # chunk holds a serialized chunk list
    # per-chunk AES key for encrypt-at-rest (filer.proto FileChunk
    # cipher_key); lives only in filer metadata, never on volume servers
    cipher_key: bytes = b""

    def to_dict(self) -> dict:
        d = {"fid": self.fid, "offset": self.offset, "size": self.size,
             "etag": self.etag, "modified_ts_ns": self.modified_ts_ns}
        if self.is_chunk_manifest:
            d["is_chunk_manifest"] = True
        if self.cipher_key:
            d["cipher_key"] = self.cipher_key.hex()
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "FileChunk":
        return cls(fid=d["fid"], offset=d["offset"], size=d["size"],
                   etag=d.get("etag", ""),
                   modified_ts_ns=d.get("modified_ts_ns", 0),
                   is_chunk_manifest=d.get("is_chunk_manifest", False),
                   cipher_key=bytes.fromhex(d["cipher_key"])
                   if d.get("cipher_key") else b"")


@dataclass
class Attr:
    mtime: float = 0.0
    crtime: float = 0.0
    mode: int = 0o660
    uid: int = 0
    gid: int = 0
    mime: str = ""
    ttl_sec: int = 0
    user_name: str = ""
    group_names: list = field(default_factory=list)
    md5: str = ""
    file_size: int = 0

    @property
    def is_directory(self) -> bool:
        return bool(self.mode & 0o40000)  # os.ModeDir analogue


@dataclass
class Entry:
    full_path: str
    attr: Attr = field(default_factory=Attr)
    chunks: list[FileChunk] = field(default_factory=list)
    extended: dict = field(default_factory=dict)
    content: bytes = b""  # inlined small-file content
    hard_link_id: str = ""
    symlink_target: str = ""
    # remote-storage mount bookkeeping (filer.proto RemoteEntry): set on
    # entries under a mounted directory; a file with a remote_entry and
    # no chunks reads through to the remote object
    remote_entry: dict = field(default_factory=dict)

    @property
    def name(self) -> str:
        return self.full_path.rsplit("/", 1)[-1]

    @property
    def parent(self) -> str:
        parent = self.full_path.rsplit("/", 1)[0]
        return parent or "/"

    @property
    def is_directory(self) -> bool:
        return self.attr.is_directory

    def size(self) -> int:
        if self.content:
            return len(self.content)
        return max(self.attr.file_size,
                   total_size(self.chunks))

    def to_dict(self) -> dict:
        return {
            "full_path": self.full_path,
            "attr": {
                "mtime": self.attr.mtime, "crtime": self.attr.crtime,
                "mode": self.attr.mode, "uid": self.attr.uid,
                "gid": self.attr.gid, "mime": self.attr.mime,
                "ttl_sec": self.attr.ttl_sec, "md5": self.attr.md5,
                "file_size": self.attr.file_size,
            },
            "chunks": [c.to_dict() for c in self.chunks],
            "extended": self.extended,
            "content": self.content.hex() if self.content else "",
            "hard_link_id": self.hard_link_id,
            "symlink_target": self.symlink_target,
            **({"remote_entry": self.remote_entry}
               if self.remote_entry else {}),
        }

    @classmethod
    def from_dict(cls, d: dict) -> "Entry":
        a = d.get("attr", {})
        return cls(
            full_path=d["full_path"],
            attr=Attr(mtime=a.get("mtime", 0), crtime=a.get("crtime", 0),
                      mode=a.get("mode", 0o660), uid=a.get("uid", 0),
                      gid=a.get("gid", 0), mime=a.get("mime", ""),
                      ttl_sec=a.get("ttl_sec", 0), md5=a.get("md5", ""),
                      file_size=a.get("file_size", 0)),
            chunks=[FileChunk.from_dict(c) for c in d.get("chunks", [])],
            extended=d.get("extended", {}),
            content=bytes.fromhex(d["content"]) if d.get("content") else b"",
            hard_link_id=d.get("hard_link_id", ""),
            symlink_target=d.get("symlink_target", ""),
            remote_entry=d.get("remote_entry", {}),
        )


def new_directory_entry(path: str, mode: int = 0o770) -> Entry:
    now = time.time()
    return Entry(full_path=path,
                 attr=Attr(mtime=now, crtime=now, mode=mode | 0o40000))


def total_size(chunks: list[FileChunk]) -> int:
    """Logical file size = max chunk end (filechunks.go TotalSize)."""
    size = 0
    for c in chunks:
        size = max(size, c.offset + c.size)
    return size
