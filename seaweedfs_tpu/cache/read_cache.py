"""The unified tiered read-through cache (HBM -> host RAM -> disk).

`TieredReadCache` merges the historical `util/chunk_cache.py`
TieredChunkCache (RAM LRU + size-classed disk rings) and the filer's
private reader `ChunkCache` (RAM-only LRU) into one object shared by
every GET path.  Semantics preserved from both ancestors:

  * with disk layers: small chunks (<= unit_size) live in RAM AND the
    small disk layer; medium/large chunks go to their own disk layers
    only (chunk_cache.go routing);
  * without disk layers: everything lives in RAM under the byte budget
    (reader_cache.go behaviour — important because default filer chunks
    are 4 MiB, above the small-class limit).

New here: an optional HBM tier fed by promotion (a chunk that keeps
hitting in RAM gets pinned in a `DevicePool` resident slab), QoS-aware
admission (background traffic bypasses the fill path), explicit
invalidation (`invalidate` / `invalidate_volume`) wired to the
delete/vacuum/rebuild paths, and per-tier hit/fill accounting exported
through the metrics registry.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Optional

from .. import qos
from ..stats import metrics as stats
from ..stats.sketch import SpaceSaving
from .disk import OnDiskCacheLayer
from .hbm import HbmTier
from .ram import RamCache

# RAM hits before a chunk is considered hot enough to pin in HBM
_PROMOTE_AFTER = 2
# hard ceiling on promotion-heat counters regardless of knobs
_HEAT_MAX = 65536


def _heat_capacity() -> int:
    """Promotion heat is a Space-Saving sketch bounded by the same
    WEED_HEAT_MAX_KEYS knob as the access recorder: under pressure it
    evicts the *coldest* counter instead of (as the old dict did)
    dropping every fid's accumulated heat at once."""
    try:
        knob = int(os.environ.get("WEED_HEAT_MAX_KEYS", "") or 4096)
    except ValueError:
        knob = 4096
    return max(16, min(_HEAT_MAX, knob))


def _heat_epoch_s() -> float:
    try:
        return max(0.25, float(
            os.environ.get("WEED_HEAT_EPOCH_S", "") or 60.0))
    except ValueError:
        return 60.0


def _heat_decay() -> float:
    try:
        return min(1.0, max(0.0, float(
            os.environ.get("WEED_HEAT_DECAY", "") or 0.5)))
    except ValueError:
        return 0.5


def _env_mb(name: str, default_mb: int) -> int:
    raw = os.environ.get(name, "")
    if raw:
        try:
            return int(float(raw) * (1 << 20))
        except ValueError:
            pass
    return default_mb << 20


def default_mem_bytes() -> int:
    return _env_mb("WEED_READ_CACHE_MB", 64)


def default_disk_bytes() -> int:
    return _env_mb("WEED_READ_CACHE_DISK_MB", 1024)


def default_hbm_bytes() -> int:
    return _env_mb("WEED_READ_CACHE_HBM_MB", 0)


def background_fills() -> bool:
    """Whether background-class traffic may fill the cache (off by
    default so scrub/rebuild sweeps cannot wash out interactive heat)."""
    return os.environ.get("WEED_READ_CACHE_BG_FILL", "0") == "1"


class TieredReadCache:
    """HBM -> RAM -> disk read-through cache with QoS-aware admission."""

    def __init__(self, mem_bytes: Optional[int] = None, directory: str = "",
                 disk_bytes: Optional[int] = None, unit_size: int = 1 << 20,
                 hbm_bytes: Optional[int] = None):
        if mem_bytes is None:
            mem_bytes = default_mem_bytes()
        if disk_bytes is None:
            disk_bytes = default_disk_bytes()
        if hbm_bytes is None:
            hbm_bytes = default_hbm_bytes()
        self.limit0 = unit_size          # small
        self.limit1 = 4 * unit_size      # medium
        self.mem = RamCache(mem_bytes)
        self.layers: list[OnDiskCacheLayer] = []
        if directory:
            os.makedirs(directory, exist_ok=True)
            # same 1/8 : 3/8 : 1/2 split and segment counts as the reference
            self.layers = [
                OnDiskCacheLayer(directory, "c0_2", disk_bytes // 8, 2),
                OnDiskCacheLayer(directory, "c1_3", disk_bytes * 3 // 8, 3),
                OnDiskCacheLayer(directory, "c2_2", disk_bytes // 2, 2),
            ]
        self.hbm: Optional[HbmTier] = (
            HbmTier(hbm_bytes) if hbm_bytes > 0 else None)
        # layers lock themselves; this guards counters + the heat sketch
        self._stat_lock = threading.Lock()
        self.hits = 0
        self.misses = 0
        self.tier_hits = {"hbm": 0, "ram": 0, "disk": 0}
        self.fills = {"admitted": 0, "qos_bypass": 0}
        self._heat = SpaceSaving(_heat_capacity())
        self._heat_epoch = time.monotonic()

    # -- accounting ----------------------------------------------------

    def _count_hit(self, tier: str):
        with self._stat_lock:
            self.hits += 1
            self.tier_hits[tier] += 1
        stats.ReadCacheRequestsCounter.inc(labels=(tier,))

    def _count_miss(self):
        with self._stat_lock:
            self.misses += 1
        stats.ReadCacheRequestsCounter.inc(labels=("miss",))

    def _publish_resident(self):
        stats.ReadCacheResidentBytesGauge.labels("ram").set(
            self.mem.size_bytes)
        if self.layers:
            stats.ReadCacheResidentBytesGauge.labels("disk").set(
                sum(layer.size_bytes for layer in self.layers))
        if self.hbm is not None:
            stats.ReadCacheResidentBytesGauge.labels("hbm").set(
                self.hbm.size_bytes)

    def stats_snapshot(self) -> dict:
        with self._stat_lock:
            snap = {
                "hits": self.hits,
                "misses": self.misses,
                "tier_hits": dict(self.tier_hits),
                "fills": dict(self.fills),
            }
        lookups = snap["hits"] + snap["misses"]
        snap["hit_ratio"] = snap["hits"] / lookups if lookups else 0.0
        snap["resident_bytes"] = {"ram": self.mem.size_bytes}
        if self.layers:
            snap["resident_bytes"]["disk"] = sum(
                layer.size_bytes for layer in self.layers)
        if self.hbm is not None:
            snap["resident_bytes"]["hbm"] = self.hbm.size_bytes
        return snap

    # -- promotion -----------------------------------------------------

    def _note_ram_hit(self, fid: str, data: Any):
        if self.hbm is None:
            return
        with self._stat_lock:
            # epoch-windowed exponential decay: heat from the last
            # epoch counts at WEED_HEAT_DECAY weight, so yesterday's
            # hot chunk must re-earn its HBM slot
            now = time.monotonic()
            epoch = _heat_epoch_s()
            elapsed = now - self._heat_epoch
            if elapsed >= epoch:
                self._heat.scale(_heat_decay() ** int(elapsed // epoch))
                self._heat_epoch = now
            self._heat.offer(fid)
            if self._heat.estimate(fid) < _PROMOTE_AFTER:
                return
            # promoted: retire its counter so steady hitters don't
            # re-put into HBM on every RAM hit
            self._heat.counts.pop(fid, None)
        self.hbm.put(fid, data)

    # -- the read-through interface ------------------------------------

    def get(self, fid: str) -> Optional[Any]:
        data = self.mem.get(fid)
        if data is not None:
            self._count_hit("ram")
            self._note_ram_hit(fid, data)
            return data
        if self.hbm is not None:
            data = self.hbm.get(fid)
            if data is not None:
                # re-warm RAM so the next hit is a host-memory hit
                self.mem.put(fid, data)
                self._count_hit("hbm")
                return data
        for layer in self.layers:
            data = layer.get(fid)
            if data is not None:
                self._count_hit("disk")
                return data
        self._count_miss()
        return None

    def get_slice(self, fid: str) -> Optional[tuple]:
        """Zero-copy variant for the sendfile reply path: a (dup'd fd,
        offset, length) triple when the fid sits in a DISK layer, else
        None.  RAM/HBM tiers have no backing fd and stay on the
        in-memory reply path, which is already faster for them."""
        if not self.layers:
            return None
        if self.mem.get(fid) is not None or (
                self.hbm is not None and self.hbm.get(fid) is not None):
            return None
        for layer in self.layers:
            s = layer.get_slice(fid)
            if s is not None:
                self._count_hit("disk")
                return s
        return None

    def put(self, fid: str, data: Any, nbytes: Optional[int] = None):
        if qos.enabled() and qos.current_class() == qos.BACKGROUND \
                and not background_fills():
            with self._stat_lock:
                self.fills["qos_bypass"] += 1
            stats.ReadCacheFillCounter.inc(labels=("qos_bypass",))
            return
        with self._stat_lock:
            self.fills["admitted"] += 1
        stats.ReadCacheFillCounter.inc(labels=("admitted",))
        n = len(data) if nbytes is None else nbytes
        if not self.layers:
            self.mem.put(fid, data, nbytes=n)
            self._publish_resident()
            return
        if n <= self.limit0:
            self.mem.put(fid, data, nbytes=n)
            layer = self.layers[0]
        elif n <= self.limit1:
            layer = self.layers[1]
        else:
            layer = self.layers[2]
        if isinstance(data, (bytes, bytearray, memoryview)):
            layer.put(fid, data)
        self._publish_resident()

    # -- invalidation --------------------------------------------------

    def invalidate(self, fid: str, reason: str = "delete") -> bool:
        dropped = self.mem.pop(fid)
        if self.hbm is not None:
            dropped = self.hbm.pop(fid) or dropped
        for layer in self.layers:
            dropped = layer.invalidate(fid) or dropped
        with self._stat_lock:
            self._heat.counts.pop(fid, None)
        if dropped:
            stats.ReadCacheInvalidationsCounter.inc(labels=(reason,))
            self._publish_resident()
        return dropped

    def invalidate_volume(self, vid: int, reason: str = "vacuum") -> int:
        """Drop every cached entry belonging to volume `vid` (fids are
        canonically ``"<vid>,<needle-hex>"``)."""
        prefix = f"{vid},"
        dropped = self.mem.drop_prefix(prefix)
        if self.hbm is not None:
            dropped += self.hbm.drop_prefix(prefix)
        for layer in self.layers:
            dropped += layer.drop_prefix(prefix)
        with self._stat_lock:
            for k in [k for k in self._heat.counts if k.startswith(prefix)]:
                del self._heat.counts[k]
        if dropped:
            stats.ReadCacheInvalidationsCounter.inc(dropped, labels=(reason,))
            self._publish_resident()
        return dropped

    # -- housekeeping --------------------------------------------------

    def clear(self):
        self.mem.clear()
        if self.hbm is not None:
            self.hbm.clear()
        for layer in self.layers:
            layer.clear()
        with self._stat_lock:
            self._heat = SpaceSaving(self._heat.capacity)
        self._publish_resident()

    def __len__(self) -> int:
        return len(self.mem)

    @property
    def capacity(self) -> int:
        return self.mem.capacity

    @property
    def size_bytes(self) -> int:
        return self.mem.size_bytes

    def close(self):
        if self.hbm is not None:
            self.hbm.close()
        for layer in self.layers:
            layer.close()


class ChunkCache(TieredReadCache):
    """RAM-only unified cache keeping `filer/reader_cache.py`'s public
    interface (``ChunkCache(capacity_bytes)``)."""

    def __init__(self, capacity_bytes: int = 64 << 20):
        super().__init__(mem_bytes=capacity_bytes)
