"""HBM tier: hottest chunks pinned in `DevicePool` resident slabs.

The EC decode path already proves content-addressed device-resident
slabs work (`ops/device_pool.py` survivor stacks); this generalizes the
same discipline to plain GET serving.  Each pinned chunk holds one
resident reference in the process-wide pool — held references do not
count against ``WEED_EC_DEVICE_POOL_MB`` idle-byte eviction, so pinned
read traffic and EC scratch coexist — and the tier keeps its own LRU
bounded by ``WEED_READ_CACHE_HBM_MB``.

On CPU-only harnesses `jax.device_put` lands in host memory, so the
tier degrades to a second RAM copy; it is therefore off by default and
only worth enabling where HBM is real.  Uploads/readbacks go through
``numpy`` u8 views; if jax is unavailable the tier is inert (every put
fails softly, every get misses).
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Optional

from ..ops.device_pool import get_pool


class _ResidentLost(Exception):
    """The pool no longer holds our slab (reset/clear raced us)."""


def _no_refill():
    raise _ResidentLost()


class HbmTier:
    def __init__(self, capacity_bytes: int):
        self.capacity = capacity_bytes
        self._keys: OrderedDict[str, int] = OrderedDict()  # fid -> nbytes
        self._bytes = 0
        self._lock = threading.Lock()

    @staticmethod
    def _pool_key(fid: str):
        return ("read_cache", fid)

    def put(self, fid: str, data) -> bool:
        if not isinstance(data, (bytes, bytearray, memoryview)):
            return False
        nbytes = len(data)
        if nbytes == 0 or nbytes > self.capacity:
            return False
        with self._lock:
            if fid in self._keys:
                self._keys.move_to_end(fid)
                return True
        try:
            import jax
            import numpy as np

            host = np.frombuffer(bytes(data), dtype=np.uint8)
            get_pool().acquire_resident(
                self._pool_key(fid), lambda: jax.device_put(host), nbytes)
        except Exception:
            return False
        with self._lock:
            if fid in self._keys:  # lost the publish race: drop our ref
                get_pool().release_resident(self._pool_key(fid))
                self._keys.move_to_end(fid)
                return True
            self._keys[fid] = nbytes
            self._bytes += nbytes
            evicted = []
            while self._bytes > self.capacity and len(self._keys) > 1:
                old, n = self._keys.popitem(last=False)
                self._bytes -= n
                evicted.append(old)
        for old in evicted:
            get_pool().release_resident(self._pool_key(old))
        return True

    def get(self, fid: str) -> Optional[bytes]:
        with self._lock:
            if fid not in self._keys:
                return None
            self._keys.move_to_end(fid)
        key = self._pool_key(fid)
        try:
            payload = get_pool().acquire_resident(key, _no_refill, 0)
        except _ResidentLost:
            self.pop(fid)
            return None
        try:
            import numpy as np

            return np.asarray(payload).tobytes()
        except Exception:
            return None
        finally:
            get_pool().release_resident(key)

    def pop(self, fid: str) -> bool:
        with self._lock:
            n = self._keys.pop(fid, None)
            if n is None:
                return False
            self._bytes -= n
        get_pool().release_resident(self._pool_key(fid))
        return True

    def drop_prefix(self, prefix: str) -> int:
        with self._lock:
            stale = [k for k in self._keys if k.startswith(prefix)]
            for k in stale:
                self._bytes -= self._keys.pop(k)
        for k in stale:
            get_pool().release_resident(self._pool_key(k))
        return len(stale)

    def __len__(self) -> int:
        return len(self._keys)

    @property
    def size_bytes(self) -> int:
        return self._bytes

    def clear(self):
        with self._lock:
            stale = list(self._keys)
            self._keys.clear()
            self._bytes = 0
        for k in stale:
            get_pool().release_resident(self._pool_key(k))

    def close(self):
        self.clear()
