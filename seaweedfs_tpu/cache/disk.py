"""Disk tier: size-classed on-disk FIFO ring layers.

Parity with weed/util/chunk_cache (on_disk_cache_layer.go,
chunk_cache_on_disk.go): each layer is a ring of append-only cache
volumes — a flat data file plus an in-RAM fid index — and when the
front volume fills, the oldest volume is reset and rotated to the
front, giving FIFO eviction in volume-sized steps with no per-entry
bookkeeping on disk.  Restarts rebuild nothing: cache volumes restart
empty (the index is RAM-only), which is correct for a cache and avoids
the reference's leveldb sidecar.

Chunks larger than a layer's segment can never fit; they are dropped at
admission and counted in ``SeaweedFS_chunk_cache_oversize_drops_total``
(historically they vanished silently).
"""

from __future__ import annotations

import os
import threading
from typing import Optional

from ..stats import metrics as stats


class CacheVolume:
    """One append-only cache segment: flat file + RAM index."""

    def __init__(self, file_name: str, size_limit: int):
        self.file_name = file_name
        self.size_limit = size_limit
        self._index: dict[str, tuple[int, int]] = {}  # fid -> (off, len)
        # unbuffered: reads go through os.pread, which sees only what has
        # actually reached the fd
        self._file = open(file_name, "wb+", buffering=0)
        self.file_size = 0

    def get(self, fid: str) -> Optional[bytes]:
        loc = self._index.get(fid)
        if loc is None:
            return None
        return os.pread(self._file.fileno(), loc[1], loc[0])

    def get_slice(self, fid: str) -> Optional[tuple]:
        """(dup'd fd, offset, length) for zero-copy sendfile, or None.
        The dup keeps the bytes readable even if this segment rotates
        (reset() swaps in a NEW inode) or the cache closes mid-send;
        the consumer owns — and must close — the returned fd."""
        loc = self._index.get(fid)
        if loc is None:
            return None
        return os.dup(self._file.fileno()), loc[0], loc[1]

    def has_room(self, n: int) -> bool:
        return self.file_size + n <= self.size_limit

    def put(self, fid: str, data) -> None:
        off = self.file_size
        self._file.seek(off)
        self._file.write(data)
        self.file_size = off + len(data)
        self._index[fid] = (off, len(data))

    def drop(self, fid: str) -> bool:
        """Forget the fid; the bytes stay until the segment rotates."""
        return self._index.pop(fid, None) is not None

    def drop_prefix(self, prefix: str) -> int:
        stale = [k for k in self._index if k.startswith(prefix)]
        for k in stale:
            del self._index[k]
        return len(stale)

    def reset(self):
        # replace the inode instead of truncating it: in-flight
        # sendfile slices hold dup'd fds to the OLD inode and must keep
        # seeing their bytes until the transfer finishes
        self._file.close()
        try:
            os.unlink(self.file_name)
        except OSError:
            pass
        self._file = open(self.file_name, "wb+", buffering=0)
        self._index.clear()
        self.file_size = 0

    def close(self):
        try:
            self._file.close()
            os.unlink(self.file_name)
        except OSError:
            pass


class OnDiskCacheLayer:
    """Ring of cache volumes with rotate-on-full FIFO eviction
    (on_disk_cache_layer.go setChunk)."""

    def __init__(self, directory: str, prefix: str, total_bytes: int,
                 segments: int):
        self.seg_size = max(1, total_bytes // segments)
        self.volumes = [
            CacheVolume(os.path.join(directory, f"{prefix}_{i}.dat"),
                        self.seg_size)
            for i in range(segments)]
        self._lock = threading.Lock()  # per-layer, not cache-global
        self.oversize_drops = 0

    def get(self, fid: str) -> Optional[bytes]:
        with self._lock:
            for v in self.volumes:
                data = v.get(fid)
                if data is not None:
                    return data
            return None

    def get_slice(self, fid: str) -> Optional[tuple]:
        """(dup'd fd, offset, length) under the layer lock, so the dup
        happens before any concurrent rotation can reset the segment."""
        with self._lock:
            for v in self.volumes:
                s = v.get_slice(fid)
                if s is not None:
                    return s
            return None

    def put(self, fid: str, data) -> None:
        if len(data) > self.seg_size:
            # can never fit; don't wipe a segment discovering that —
            # but don't let the drop vanish silently either
            with self._lock:
                self.oversize_drops += 1
            stats.ChunkCacheOversizeDropsCounter.inc()
            return
        with self._lock:
            if not self.volumes[0].has_room(len(data)):
                oldest = self.volumes.pop()
                oldest.reset()
                self.volumes.insert(0, oldest)
            self.volumes[0].put(fid, data)

    def invalidate(self, fid: str) -> bool:
        with self._lock:
            dropped = False
            for v in self.volumes:
                dropped = v.drop(fid) or dropped
            return dropped

    def drop_prefix(self, prefix: str) -> int:
        with self._lock:
            return sum(v.drop_prefix(prefix) for v in self.volumes)

    @property
    def size_bytes(self) -> int:
        with self._lock:
            return sum(v.file_size for v in self.volumes)

    def clear(self):
        with self._lock:
            for v in self.volumes:
                v.reset()

    def close(self):
        with self._lock:
            for v in self.volumes:
                v.close()
