"""Host-RAM tier: LRU bounded by byte budget.

Parity with weed/filer/reader_cache.go + weed/util/chunk_cache —
recently fetched chunks are kept in RAM so sequential and repeated
reads avoid re-fetching from volume servers.  Payloads are usually
immutable ``bytes`` but any object may be cached by passing an explicit
``nbytes`` (the volume server caches parsed needles this way).
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Any, Optional


class RamCache:
    def __init__(self, capacity_bytes: int = 64 << 20):
        self.capacity = capacity_bytes
        self._data: OrderedDict[str, tuple[Any, int]] = OrderedDict()
        self._bytes = 0
        self._lock = threading.Lock()

    def get(self, fid: str) -> Optional[Any]:
        with self._lock:
            entry = self._data.get(fid)
            if entry is None:
                return None
            self._data.move_to_end(fid)
            return entry[0]

    def put(self, fid: str, data: Any, nbytes: Optional[int] = None):
        n = len(data) if nbytes is None else nbytes
        if n > self.capacity:
            return  # oversized: never cache (chunk_cache size gate)
        with self._lock:
            old = self._data.pop(fid, None)
            if old is not None:
                self._bytes -= old[1]
            self._data[fid] = (data, n)
            self._bytes += n
            while self._bytes > self.capacity:
                _, (_, evicted) = self._data.popitem(last=False)
                self._bytes -= evicted

    def pop(self, fid: str) -> bool:
        with self._lock:
            old = self._data.pop(fid, None)
            if old is None:
                return False
            self._bytes -= old[1]
            return True

    def drop_prefix(self, prefix: str) -> int:
        with self._lock:
            stale = [k for k in self._data if k.startswith(prefix)]
            for k in stale:
                self._bytes -= self._data.pop(k)[1]
            return len(stale)

    def clear(self):
        with self._lock:
            self._data.clear()
            self._bytes = 0

    def __len__(self) -> int:
        return len(self._data)

    @property
    def size_bytes(self) -> int:
        return self._bytes

    def close(self):
        """No resources to release; shares the tiered cache's interface."""
