"""Unified tiered read-through cache for object GETs.

One cache subsystem shared by the volume-server needle-read path, the
filer chunk-fetch path and the s3api GET path, replacing the two
historical disjoint caches (``util/chunk_cache.py`` and
``filer/reader_cache.py``, both of which now re-export from here):

  HBM   — hottest chunks pinned in `DevicePool` resident slabs
          (``WEED_READ_CACHE_HBM_MB``, default off)
  RAM   — warm chunks in a host LRU bounded by byte budget
          (``WEED_READ_CACHE_MB``)
  disk  — cold chunks in size-classed append-only FIFO ring volumes
          (``WEED_READ_CACHE_DISK_MB``)

Admission is QoS-class-aware: interactive/standard traffic fills on
miss, background traffic (scrubs, rebuilds) bypasses the fill so
maintenance sweeps cannot wash the cache (override with
``WEED_READ_CACHE_BG_FILL=1``).  Hits serve via zero-copy `memoryview`
writeback into the socket send; invalidation hooks ride the existing
delete / vacuum / ec.rebuild paths.
"""

from .ram import RamCache
from .disk import CacheVolume, OnDiskCacheLayer
from .hbm import HbmTier
from .read_cache import (ChunkCache, TieredReadCache, background_fills,
                         default_disk_bytes, default_hbm_bytes,
                         default_mem_bytes)

__all__ = [
    "CacheVolume",
    "ChunkCache",
    "HbmTier",
    "OnDiskCacheLayer",
    "RamCache",
    "TieredReadCache",
    "background_fills",
    "default_disk_bytes",
    "default_hbm_bytes",
    "default_mem_bytes",
]
