"""Streaming batched EC encode: .dat files -> 14 shard files through the
sharded TPU encoder, with pipelined host I/O.

This is the production encode path (BASELINE configs 1 + 4).  The reference
encodes one volume at a time, feeding its CPU codec 256 KB-per-shard slices
inside a synchronous loop (/root/reference/weed/storage/erasure_coding/
ec_encoder.go:194-231).  Here the striped rows of MANY volumes are tiled
into (B, 10, L) uint8 batches and pushed through one jit-compiled
parity+CRC step (parallel/mesh.py) with a three-stage pipeline:

  reader thread   — fills pinned host buffers from the .dat files and
                    appends the data-shard bytes to .ec00-.ec09 (data
                    shards are a pure re-interleaving of the .dat, no
                    compute needed);
  main thread     — device_put(batch N+1) and dispatches its encode while
                    batch N's parity is still materializing (double
                    buffering: transfers overlap compute via async
                    dispatch); finalizes fused CRCs and chains them into
                    per-shard-file rolling CRC32Cs;
  writer thread   — appends parity bytes to .ec10-.ec13.

Every shard chunk's CRC32C is computed on device, fused with the parity
matmul (BASELINE config 5); whole-shard-file CRCs are returned and persisted
in the .vif sidecar for scrub tooling.
"""

from __future__ import annotations

import math
import os
import queue
import threading
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

DATA_SHARDS = 10
PARITY_SHARDS = 4
TOTAL_SHARDS = 14

# per-dispatch target: B * 10 * L bytes of data-shard input
TARGET_BATCH_BYTES = 64 << 20
MAX_CHUNK_BYTES = 1 << 20
_SLOTS = 4   # host staging buffers in flight
_INFLIGHT = 3  # device dispatches queued before draining (hides dispatch
               # latency — significant over the axon TPU relay)


@dataclass
class _Unit:
    """One (volume, row, column-chunk): a (10, L) slice of work."""
    vol: int
    row_start: int     # byte offset of the row in the .dat
    shard_off: int     # byte offset of this chunk in each shard file
    col: int           # column offset within the row's blocks
    block_size: int


@dataclass
class _VolumePlan:
    base: str
    dat_size: int
    rows: list[tuple[int, int, int]] = field(default_factory=list)
    # (row_start_in_dat, shard_offset, block_size)


def _plan_volume(base: str, large_block: int, small_block: int) -> _VolumePlan:
    """Row plan mirroring WriteEcFiles striping (ec_encoder.go:57-59):
    large rows while > 10 large blocks remain, then small rows, zero-padded."""
    dat_size = os.path.getsize(base + ".dat")
    plan = _VolumePlan(base, dat_size)
    remaining = dat_size
    row_start = 0
    shard_off = 0
    while remaining > large_block * DATA_SHARDS:
        plan.rows.append((row_start, shard_off, large_block))
        row_start += large_block * DATA_SHARDS
        shard_off += large_block
        remaining -= large_block * DATA_SHARDS
    while remaining > 0:
        plan.rows.append((row_start, shard_off, small_block))
        row_start += small_block * DATA_SHARDS
        shard_off += small_block
        remaining -= small_block * DATA_SHARDS
    return plan


def _chunk_len(large_block: int, small_block: int) -> int:
    """Static column-chunk width L: divides every block size in the plan."""
    cand = min(small_block, MAX_CHUNK_BYTES)
    if large_block % cand == 0 and small_block % cand == 0:
        return cand
    return math.gcd(large_block, small_block)


def _make_units(plans: list[_VolumePlan], chunk: int) -> list[_Unit]:
    units = []
    for vi, plan in enumerate(plans):
        for row_start, shard_off, block in plan.rows:
            for col in range(0, block, chunk):
                units.append(_Unit(vi, row_start, shard_off + col, col, block))
    return units


def _read_unit(dat, dat_size: int, u: _Unit, chunk: int, out: np.ndarray):
    """Fill out (10, chunk) with the unit's data-shard bytes, zero-padding
    past EOF (the tail row's zero padding is part of the format)."""
    for i in range(DATA_SHARDS):
        start = u.row_start + i * u.block_size + u.col
        view = memoryview(out[i]).cast("B")
        if start >= dat_size:
            out[i].fill(0)
            continue
        dat.seek(start)
        got = dat.readinto(view)
        if got < chunk:
            out[i, got:].fill(0)


class _ShardWriters:
    """Open .ec00-.ec13 for one volume; tracks rolling per-file CRC32C."""

    def __init__(self, base: str, to_ext):
        self.files = [open(base + to_ext(i), "wb")
                      for i in range(TOTAL_SHARDS)]
        self.crcs = [0] * TOTAL_SHARDS

    def close(self):
        for f in self.files:
            f.close()


def encode_volumes(bases: list[str], large_block: Optional[int] = None,
                   small_block: Optional[int] = None,
                   mesh=None, batch_units: Optional[int] = None,
                   host_codec=None) -> dict[str, list[int]]:
    """Encode every `base` (.dat) into 14 shard files via the batched
    pipeline.  Returns {base: [crc32c of each shard file] * 14}.

    Volumes are batched together: chunks from different volumes ride the
    same device dispatch, which is what makes the 100-volume HBM-resident
    configuration (BASELINE config 4) one pipeline rather than 100 encodes.

    host_codec: pass an encoder object (or True for the best host codec)
    to run the SAME pipeline — reader thread, staging slots, CRC combine,
    writer backpressure — with the native host codec as the compute stage
    instead of a device dispatch.  This is the auto-selected fallback on
    link-capped machines: unlike the reference's synchronous loop
    (ec_encoder.go:194-231) the pipeline overlaps file I/O with compute,
    and it still produces the fused shard-file CRCs for the .vif.
    """
    from ..storage.erasure_coding import (LARGE_BLOCK_SIZE, SMALL_BLOCK_SIZE,
                                          to_ext)

    large_block = large_block or LARGE_BLOCK_SIZE
    small_block = small_block or SMALL_BLOCK_SIZE
    plans = [_plan_volume(b, large_block, small_block) for b in bases]
    chunk = _chunk_len(large_block, small_block)
    units = _make_units(plans, chunk)

    writers = {vi: _ShardWriters(p.base, to_ext)
               for vi, p in enumerate(plans)}
    if not units:
        out = {}
        for vi, p in enumerate(plans):
            writers[vi].close()
            out[p.base] = [0] * TOTAL_SHARDS
        return out
    if host_codec:
        return _encode_units_host(plans, units, chunk, writers, host_codec)
    return _encode_units_device(plans, units, chunk, writers, mesh,
                                batch_units)


class _PipelineIO:
    """Shared reader/writer scaffolding of the streaming pipeline:
    staging slots, backpressure queues, the reader thread (fills slots
    and appends data shards), the writer thread (appends parity shards),
    and the torn-shutdown sequencing.  The device and host compute
    stages differ only in what happens between `ready` and `parity_q`."""

    def __init__(self, plans, units, chunk, writers, b):
        self.plans, self.units, self.chunk = plans, units, chunk
        self.writers, self.b = writers, b
        self.n_batches = (len(units) + b - 1) // b
        self.dats = [open(p.base + ".dat", "rb") for p in plans]
        self.free_slots: "queue.Queue[np.ndarray]" = queue.Queue()
        for _ in range(_SLOTS):
            self.free_slots.put(
                np.zeros((b, DATA_SHARDS, chunk), dtype=np.uint8))
        self.ready: "queue.Queue" = queue.Queue(maxsize=_SLOTS)
        self.parity_q: "queue.Queue" = queue.Queue(maxsize=_SLOTS)
        self.errors: list[BaseException] = []
        self.stop = threading.Event()
        self._rt = threading.Thread(target=self._reader, daemon=True)
        self._wt = threading.Thread(target=self._writer, daemon=True)

    def put(self, q, item) -> bool:
        while not self.stop.is_set():
            try:
                q.put(item, timeout=0.5)
                return True
            except queue.Full:
                continue
        return False

    def get(self, q):
        while not self.stop.is_set():
            try:
                return q.get(timeout=0.5)
            except queue.Empty:
                continue
        return None

    def _reader(self):
        try:
            for n in range(self.n_batches):
                batch = self.units[n * self.b:(n + 1) * self.b]
                buf = self.get(self.free_slots)
                if buf is None:
                    return
                for k, u in enumerate(batch):
                    _read_unit(self.dats[u.vol],
                               self.plans[u.vol].dat_size, u,
                               self.chunk, buf[k])
                    w = self.writers[u.vol]
                    for i in range(DATA_SHARDS):
                        w.files[i].seek(u.shard_off)
                        w.files[i].write(buf[k, i])
                if not self.put(self.ready, (buf, batch)):
                    return
            self.put(self.ready, None)
        except BaseException as e:  # propagate to the main thread
            self.errors.append(e)
            self.stop.set()

    def _writer(self):
        try:
            while True:
                item = self.get(self.parity_q)
                if item is None:
                    return
                parity, batch = item
                for k, u in enumerate(batch):
                    w = self.writers[u.vol]
                    for i in range(PARITY_SHARDS):
                        f = w.files[DATA_SHARDS + i]
                        f.seek(u.shard_off)
                        f.write(parity[k, i])
        except BaseException as e:
            self.errors.append(e)
            self.stop.set()

    def start(self):
        self._rt.start()
        self._wt.start()

    def finish(self):
        self.put(self.parity_q, None)
        self._wt.join(timeout=60)
        self.stop.set()
        self._rt.join(timeout=30)
        for f in self.dats:
            f.close()
        for w in self.writers.values():
            w.close()

    def result(self) -> dict[str, list[int]]:
        if self.errors:
            raise self.errors[0]
        from ..stats import metrics as stats

        stats.EcEncodeBytesCounter.inc(
            sum(p.dat_size for p in self.plans))
        return {p.base: self.writers[vi].crcs
                for vi, p in enumerate(self.plans)}


def _encode_units_device(plans, units, chunk, writers, mesh,
                         batch_units) -> dict[str, list[int]]:
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from ..ops import crc32c as crc_host
    from ..ops.crc_device import finalize
    from .mesh import make_mesh, make_sharded_encoder, words_capable

    if mesh is None:
        mesh = make_mesh()
    n_data, n_block = mesh.devices.shape
    if chunk % n_block:
        mesh = Mesh(mesh.devices.reshape(-1, 1), mesh.axis_names)
        n_data, n_block = mesh.devices.shape

    if batch_units is None:
        batch_units = max(1, TARGET_BATCH_BYTES // (DATA_SHARDS * chunk))
    b = min(batch_units, len(units))
    b = max(n_data, ((b + n_data - 1) // n_data) * n_data)

    # word-layout fast path: packed int32 views move host<->device with
    # no device bitcasts (the int32->uint8 relayout costs 10x the kernel)
    use_words = words_capable(mesh, chunk)
    step = make_sharded_encoder(mesh, words=use_words)
    sharding = NamedSharding(mesh, P("data", None, "block"))

    io = _PipelineIO(plans, units, chunk, writers, b)
    inflight: list = []  # (buf, batch, parity_dev, crc_dev)

    def drain_one():
        buf, batch, parity_dev, crc_dev = inflight.pop(0)
        # blocks until compute done; sharded gathers can come back
        # non-contiguous, and file writes need a contiguous buffer
        parity = np.ascontiguousarray(np.asarray(parity_dev))
        if use_words:  # packed int32 parity words -> bytes (free view)
            parity = parity.view(np.uint8).reshape(
                parity.shape[0], PARITY_SHARDS, chunk)
        crcs = finalize(crc_dev, chunk)
        io.free_slots.put(buf)  # device consumed the input transfer
        for k, u in enumerate(batch):
            w = writers[u.vol]
            for s in range(TOTAL_SHARDS):
                w.crcs[s] = crc_host.crc32c_combine(
                    w.crcs[s], int(crcs[k, s]), chunk)
        io.put(io.parity_q, (parity, batch))

    io.start()
    try:
        while not io.stop.is_set():
            item = io.get(io.ready)
            if item is None:
                break
            buf, batch = item
            if use_words:
                # pin to the mesh's device: the caller may run several
                # 1-device meshes side by side
                dev = jax.device_put(buf.view(np.int32),
                                     mesh.devices.flat[0])
            else:
                dev = jax.device_put(buf, sharding)
            parity_dev, crc_dev = step(dev)
            inflight.append((buf, batch, parity_dev, crc_dev))
            if len(inflight) >= _INFLIGHT:
                drain_one()
        while inflight and not io.stop.is_set():
            drain_one()
    except BaseException:
        io.stop.set()
        raise
    finally:
        io.finish()
    return io.result()


def _encode_units_host(plans, units, chunk, writers,
                       host_codec) -> dict[str, list[int]]:
    """The pipeline with the host codec as the compute stage: same
    reader thread / staging slots / writer backpressure / rolling CRC
    combine as the device path (via _PipelineIO), no JAX involved.  The
    native codec and SSE4.2 CRC release the GIL, so the reader and
    writer threads overlap with compute on multi-core hosts."""
    from ..ops import codec as codec_mod
    from ..ops import crc32c as crc_host

    enc = host_codec if hasattr(host_codec, "_apply") \
        else codec_mod.new_host_encoder(DATA_SHARDS, PARITY_SHARDS)
    parity_matrix = np.asarray(enc.matrix[DATA_SHARDS:])

    batch_units = max(1, TARGET_BATCH_BYTES // (DATA_SHARDS * chunk))
    b = min(batch_units, len(units))
    io = _PipelineIO(plans, units, chunk, writers, b)
    io.start()
    try:
        while not io.stop.is_set():
            item = io.get(io.ready)
            if item is None:
                break
            buf, batch = item
            parity = np.empty((len(batch), PARITY_SHARDS, chunk),
                              dtype=np.uint8)
            for k, u in enumerate(batch):
                parity[k] = enc._apply(parity_matrix, buf[k])
                w = writers[u.vol]
                for s in range(DATA_SHARDS):
                    w.crcs[s] = crc_host.crc32c_combine(
                        w.crcs[s], crc_host.crc32c(buf[k, s]), chunk)
                for s in range(PARITY_SHARDS):
                    w.crcs[DATA_SHARDS + s] = crc_host.crc32c_combine(
                        w.crcs[DATA_SHARDS + s],
                        crc_host.crc32c(parity[k, s]), chunk)
            io.free_slots.put(buf)
            io.put(io.parity_q, (parity, batch))
    except BaseException:
        io.stop.set()
        raise
    finally:
        io.finish()
    return io.result()


def rebuild_matrix(present: list[int], missing: list[int],
                   data_shards: int = DATA_SHARDS,
                   total_shards: int = TOTAL_SHARDS):
    """(survivor_ids, M) with M (len(missing) x data_shards) mapping the
    chosen survivors directly to the missing shards: data rows come from
    the inverted survivor submatrix, parity rows from encode-rows times
    that inverse (the one-matmul form of klauspost Reconstruct)."""
    from ..ops import gf256

    full = gf256.build_matrix(data_shards, total_shards)
    chosen = present[:data_shards]
    inv = gf256.gf_invert(full[chosen])
    rows = []
    for m in missing:
        if m < data_shards:
            rows.append(inv[m])
        else:
            rows.append(gf256.gf_matmul(full[m:m + 1], inv)[0])
    return chosen, np.stack(rows).astype(np.uint8)


def rebuild_shards(base: str, mesh=None,
                   batch_units: Optional[int] = None) -> dict[int, int]:
    """Regenerate every missing .ecNN from survivors through the batched
    device pipeline (RebuildEcFiles, ec_encoder.go:233-287 — the
    reference loops 1 MB buffers through its CPU codec; here survivor
    chunks batch into (B, 10, L) device dispatches with fused CRC32C of
    the rebuilt shards).  Returns {shard_id: crc32c of the rebuilt file}.
    """
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from ..ops import crc32c as crc_host
    from ..ops.crc_device import finalize
    from ..storage.erasure_coding import to_ext
    from .mesh import make_mesh, make_sharded_apply

    present = [i for i in range(TOTAL_SHARDS)
               if os.path.exists(base + to_ext(i))]
    missing = [i for i in range(TOTAL_SHARDS) if i not in present]
    if not missing:
        return {}
    if len(present) < DATA_SHARDS:
        raise ValueError(
            f"too few shards to rebuild: {len(present)} < {DATA_SHARDS}")
    chosen, matrix = rebuild_matrix(present, missing)
    sizes = {os.path.getsize(base + to_ext(i)) for i in chosen}
    if len(sizes) != 1:
        raise ValueError(f"survivor shard sizes differ: {sorted(sizes)}")
    shard_size = sizes.pop()
    if shard_size == 0:
        for sid in missing:
            open(base + to_ext(sid), "wb").close()
        return {sid: 0 for sid in missing}

    chunk = min(MAX_CHUNK_BYTES, shard_size)
    offsets = list(range(0, shard_size, chunk))

    if mesh is None:
        mesh = make_mesh()
    n_data, n_block = mesh.devices.shape
    if chunk % n_block:
        mesh = Mesh(mesh.devices.reshape(-1, 1), mesh.axis_names)
        n_data, n_block = mesh.devices.shape
    if batch_units is None:
        batch_units = max(1, TARGET_BATCH_BYTES // (DATA_SHARDS * chunk))
    b = min(batch_units, len(offsets))
    b = max(n_data, ((b + n_data - 1) // n_data) * n_data)

    step = make_sharded_apply(mesh, matrix)
    sharding = NamedSharding(mesh, P("data", None, "block"))

    inputs = [open(base + to_ext(i), "rb") for i in chosen]
    outputs = {sid: open(base + to_ext(sid), "wb") for sid in missing}
    crcs = {sid: 0 for sid in missing}
    try:
        inflight: list = []

        def drain_one():
            batch_offs, out_dev, crc_dev = inflight.pop(0)
            out = np.ascontiguousarray(np.asarray(out_dev))
            raw = np.asarray(crc_dev)
            for k, off in enumerate(batch_offs):
                width = min(chunk, shard_size - off)
                fin = finalize(raw[k], chunk)
                for j, sid in enumerate(missing):
                    outputs[sid].seek(off)
                    outputs[sid].write(out[k, j, :width])
                    # chunks are full except possibly the last; a short
                    # final chunk was zero-padded on device, and CRCs of
                    # zero-extended data un-extend via combine algebra
                    chunk_crc = int(fin[j]) if width == chunk else \
                        crc_host.crc32c(out[k, j, :width].tobytes())
                    crcs[sid] = crc_host.crc32c_combine(
                        crcs[sid], chunk_crc, width)
            return None

        # two staging buffers: a buffer is refilled only after its batch
        # drained (which implies the host->device transfer completed)
        bufs = [np.zeros((b, DATA_SHARDS, chunk), dtype=np.uint8)
                for _ in range(2)]
        for step_i, start in enumerate(range(0, len(offsets), b)):
            buf = bufs[step_i % 2]
            batch_offs = offsets[start:start + b]
            for k, off in enumerate(batch_offs):
                width = min(chunk, shard_size - off)
                for i, f in enumerate(inputs):
                    f.seek(off)
                    view = memoryview(buf[k, i])[:width]
                    got = f.readinto(view)
                    if got < width:
                        buf[k, i, got:width] = 0
                    if width < chunk:
                        buf[k, i, width:] = 0
            dev = jax.device_put(buf, sharding)
            out_dev, crc_dev = step(dev)
            inflight.append((batch_offs, out_dev, crc_dev))
            if len(inflight) >= 2:
                drain_one()
        while inflight:
            drain_one()
    finally:
        for f in inputs:
            f.close()
        for f in outputs.values():
            f.close()
    return crcs
