"""Streaming batched EC encode: .dat files -> 14 shard files through the
sharded TPU encoder, with pipelined host I/O.

This is the production encode path (BASELINE configs 1 + 4).  The reference
encodes one volume at a time, feeding its CPU codec 256 KB-per-shard slices
inside a synchronous loop (/root/reference/weed/storage/erasure_coding/
ec_encoder.go:194-231).  Here the striped rows of MANY volumes are tiled
into (B, 10, L) uint8 batches and pushed through one jit-compiled
parity+CRC step (parallel/mesh.py) with a three-stage pipeline:

  reader thread   — fills pooled host staging slots from the .dat files
                    and appends the data-shard bytes to .ec00-.ec09 (data
                    shards are a pure re-interleaving of the .dat, no
                    compute needed; all-zero padding rows are skipped —
                    the shard files are ftruncate()d to final size, so
                    their bytes are already zero);
  main thread     — dispatches batch N+1 into the persistent jitted step
                    while earlier batches are still in flight (depth
                    WEED_EC_DEVICE_INFLIGHT), uploading through the
                    device slab pool (ops/device_pool.py): staging slots
                    and donated output slots are leased once and recycled,
                    so the steady state performs zero per-batch device
                    allocations;
  completion thread — synchronizes finished batches, chains per-shard-file
                    rolling CRC32Cs, recycles slots, and hands parity to
  writer thread   — appends parity bytes to .ec10-.ec13.

Units from ALL volumes in the call pack into ONE fixed compiled shape
(tail batch padded, pad columns masked out of CRC and writes), so a
100-volume encode is one pipeline with at most a handful of compiled
shapes.  On TPU meshes the per-chunk CRC32C is computed on device, fused
with the parity matmul (BASELINE config 5); on CPU meshes parity runs as
a persistent batched SWAR step and CRCs use the ~30x-faster host crc32c
kernel, overlapped with the next batch's compute.  Whole-shard-file CRCs
are returned and persisted in the .vif sidecar for scrub tooling.
"""

from __future__ import annotations

import ctypes
import math
import os
import queue
import threading
import time
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from .. import profiling
from ..qos import lanes as _lanes

DATA_SHARDS = 10
PARITY_SHARDS = 4
TOTAL_SHARDS = 14

# per-dispatch target: B * 10 * L bytes of data-shard input
TARGET_BATCH_BYTES = 64 << 20
MAX_CHUNK_BYTES = 1 << 20
_SLOTS = 4   # host staging buffers in flight
_INFLIGHT = 3  # device dispatches queued before draining (hides dispatch
               # latency — significant over the axon TPU relay)


@dataclass
class _Unit:
    """One (volume, row, column-chunk): a (10, L) slice of work."""
    vol: int
    row_start: int     # byte offset of the row in the .dat
    shard_off: int     # byte offset of this chunk in each shard file
    col: int           # column offset within the row's blocks
    block_size: int
    real_rows: int = DATA_SHARDS  # rows with any .dat bytes; rows past
    #                               this are the format's zero padding


@dataclass
class _VolumePlan:
    base: str
    dat_size: int
    rows: list[tuple[int, int, int]] = field(default_factory=list)
    # (row_start_in_dat, shard_offset, block_size)


def _plan_volume(base: str, large_block: int, small_block: int) -> _VolumePlan:
    """Row plan mirroring WriteEcFiles striping (ec_encoder.go:57-59):
    large rows while > 10 large blocks remain, then small rows, zero-padded."""
    dat_size = os.path.getsize(base + ".dat")
    plan = _VolumePlan(base, dat_size)
    remaining = dat_size
    row_start = 0
    shard_off = 0
    while remaining > large_block * DATA_SHARDS:
        plan.rows.append((row_start, shard_off, large_block))
        row_start += large_block * DATA_SHARDS
        shard_off += large_block
        remaining -= large_block * DATA_SHARDS
    while remaining > 0:
        plan.rows.append((row_start, shard_off, small_block))
        row_start += small_block * DATA_SHARDS
        shard_off += small_block
        remaining -= small_block * DATA_SHARDS
    return plan


def _chunk_len(large_block: int, small_block: int) -> int:
    """Static column-chunk width L: divides every block size in the plan."""
    cand = min(small_block, MAX_CHUNK_BYTES)
    if large_block % cand == 0 and small_block % cand == 0:
        return cand
    return math.gcd(large_block, small_block)


def _make_units(plans: list[_VolumePlan], chunk: int) -> list[_Unit]:
    units = []
    for vi, plan in enumerate(plans):
        for row_start, shard_off, block in plan.rows:
            for col in range(0, block, chunk):
                # rows i with row_start + i*block + col < dat_size carry
                # real bytes; the rest are zero padding the device paths
                # can compact away (their shard bytes are ftruncate
                # zeros and their chunk CRC is crc32c_zeros(chunk))
                avail = plan.dat_size - row_start - col
                real = 0 if avail <= 0 else min(
                    DATA_SHARDS, -(-avail // block))
                units.append(_Unit(vi, row_start, shard_off + col, col,
                                   block, real))
    return units


def _read_unit(dat, dat_size: int, u: _Unit, chunk: int, out: np.ndarray):
    """Fill out (10, chunk) with the unit's data-shard bytes, zero-padding
    past EOF (the tail row's zero padding is part of the format)."""
    for i in range(DATA_SHARDS):
        start = u.row_start + i * u.block_size + u.col
        view = memoryview(out[i]).cast("B")
        if start >= dat_size:
            out[i].fill(0)
            continue
        dat.seek(start)
        got = dat.readinto(view)
        if got < chunk:
            out[i, got:].fill(0)


# -- the write stage's shared plumbing --------------------------------------
# checked vectored writes, dirty-page writeback pacing, and the raw shard
# fd set.  Shared by all three consumers: the host pipeline's writer pool,
# the device pipeline's drain side, and the rebuild path.

_IOV_MAX = 1024       # kernel cap on iovecs per pwritev
_SFR_WAIT_BEFORE = 1  # SYNC_FILE_RANGE_WAIT_BEFORE
_SFR_WRITE = 2        # SYNC_FILE_RANGE_WRITE
_SFR_WAIT_AFTER = 4   # SYNC_FILE_RANGE_WAIT_AFTER

_sfr_fn = None
_sfr_probed = False


def _sync_file_range():
    """ctypes handle to sync_file_range(2) — not exposed by the os
    module; None when the libc doesn't have it (non-Linux)."""
    global _sfr_fn, _sfr_probed
    if not _sfr_probed:
        _sfr_probed = True
        try:
            libc = ctypes.CDLL(None, use_errno=True)
            fn = libc.sync_file_range
            fn.argtypes = [ctypes.c_int, ctypes.c_int64, ctypes.c_int64,
                           ctypes.c_uint]
            fn.restype = ctypes.c_int
            _sfr_fn = fn
        except (OSError, AttributeError):
            _sfr_fn = None
    return _sfr_fn


def _write_knobs() -> tuple[bool, int, int, bool]:
    """The WEED_EC_WRITE_* knob set, read per encode (daemons and tests
    flip them without a reimport): (write_behind, writers, flush_bytes,
    drop_cache).

      WEED_EC_WRITE_BEHIND     0 disables the decoupled writer stage
                               (compute workers write synchronously)
      WEED_EC_WRITERS          writer-pool size (0 = auto: workers/2,
                               capped at 4)
      WEED_EC_WRITE_FLUSH_MB   writeback pacing window in MiB
                               (0 disables pacing; default 32)
      WEED_EC_WRITE_DROP_CACHE 1 = drop synced windows from the page
                               cache (posix_fadvise DONTNEED)
    """
    behind = os.environ.get("WEED_EC_WRITE_BEHIND", "1").lower() \
        not in ("0", "false", "no")
    writers = int(os.environ.get("WEED_EC_WRITERS", "0") or 0)
    mb = os.environ.get("WEED_EC_WRITE_FLUSH_MB", "")
    flush_bytes = int(float(mb) * (1 << 20)) if mb else (32 << 20)
    drop = os.environ.get("WEED_EC_WRITE_DROP_CACHE", "0").lower() \
        not in ("", "0", "false", "no")
    return behind, writers, flush_bytes, drop


def _pwritev_full(fd: int, bufs, offset: int) -> int:
    """pwritev that writes every byte or raises OSError.  A short kernel
    write must fail the encode, not silently truncate a shard whose CRC
    was already computed from memory (ADVICE.md batched_encode.py:551):
    partial progress is retried from where the kernel stopped; zero
    progress is a hard error."""
    iovs = [memoryview(b) for b in bufs]
    total = sum(v.nbytes for v in iovs)
    written = 0
    while written < total:
        n = os.pwritev(fd, iovs, offset + written)
        if n <= 0:
            raise OSError(
                "pwritev made no progress: %d of %d bytes at offset %d "
                "(shard would be truncated)" % (written, total, offset))
        written += n
        if written >= total:
            break
        while n >= iovs[0].nbytes:  # drop fully-written iovecs
            n -= iovs[0].nbytes
            iovs.pop(0)
        if n:
            iovs[0] = iovs[0][n:]
    return total


class _WritebackPacer:
    """Paces dirty-page writeback for the shard writer stage: after
    every `flush_bytes` written to an fd, kick the kernel's async
    writeback for the newly-written window (sync_file_range(WRITE)) so
    dirty pages drain continuously instead of accumulating until
    vm.dirty_ratio stalls every writer at once — the failure mode of the
    8.79 GiB scale run, whose write stage was 93.5% of wall time.  With
    drop_cache the window is synced and evicted (posix_fadvise DONTNEED):
    shard bytes are write-once and never re-read by this process.

    Time spent flushing is accumulated in `flush_seconds` so callers can
    attribute it separately from the pwritev busy time."""

    def __init__(self, flush_bytes: int, drop_cache: bool):
        self.flush_bytes = flush_bytes
        self.drop_cache = drop_cache
        self._sfr = _sync_file_range() if flush_bytes > 0 else None
        self._lock = threading.Lock()
        self._state: dict[int, list[int]] = {}  # fd -> [acc, cursor, hi]
        self.flush_seconds = 0.0
        self.flushes = 0

    def wrote(self, fd: int, offset: int, n: int):
        if self.flush_bytes <= 0 or n <= 0:
            return
        with self._lock:
            st = self._state.setdefault(fd, [0, 0, 0])
            st[0] += n
            end = offset + n
            if end > st[2]:
                st[2] = end
            if st[0] < self.flush_bytes:
                return
            st[0] = 0
            lo, hi = st[1], st[2]
            st[1] = hi
        self._flush_window(fd, lo, hi)

    def _flush_window(self, fd: int, lo: int, hi: int):
        if hi <= lo:
            return
        t0 = time.perf_counter()
        try:
            if self._sfr is not None:
                self._sfr(fd, lo, hi - lo, _SFR_WRITE)
            if self.drop_cache:
                if self._sfr is not None:
                    self._sfr(fd, lo, hi - lo,
                              _SFR_WAIT_BEFORE | _SFR_WRITE | _SFR_WAIT_AFTER)
                os.posix_fadvise(fd, lo, hi - lo, os.POSIX_FADV_DONTNEED)
        except OSError:
            self.flush_bytes = 0  # fs doesn't support pacing; stop trying
            return
        with self._lock:
            self.flush_seconds += time.perf_counter() - t0
            self.flushes += 1

    def forget(self, fds):
        """Drop per-fd state on close: fd numbers get recycled."""
        with self._lock:
            for fd in fds:
                self._state.pop(fd, None)


class _ShardFileSet:
    """One volume's 14 shard files as raw O_WRONLY fds (no BufferedWriter
    copy, no seek-flush churn — profiling showed buffered seek+write was
    the #1 cost of the old host stage) with rolling per-file CRC32C.
    pwritev is positional and thread-safe, so reader, writer-pool and
    drain threads can all write concurrently.  Files are ftruncate()d to
    their final size up front: extending i_size a megabyte at a time
    measurably slows tmpfs/ext4 writes (~3x on the profiled box).  Every
    write goes through the checked pwritev (full length or OSError) and
    reports to the writeback pacer."""

    def __init__(self, base: str, to_ext, shard_size: int = 0,
                 pacer: Optional[_WritebackPacer] = None):
        self.fds = [os.open(base + to_ext(i),
                            os.O_CREAT | os.O_TRUNC | os.O_WRONLY, 0o644)
                    for i in range(TOTAL_SHARDS)]
        if shard_size:
            for fd in self.fds:
                os.ftruncate(fd, shard_size)
        self.crcs = [0] * TOTAL_SHARDS
        self.pacer = pacer

    def write(self, shard: int, bufs, offset: int) -> int:
        fd = self.fds[shard]
        n = _pwritev_full(fd, bufs, offset)
        if self.pacer is not None:
            self.pacer.wrote(fd, offset, n)
        return n

    def close(self):
        if self.pacer is not None:
            self.pacer.forget(self.fds)
        for fd in self.fds:
            os.close(fd)


def encode_volumes(bases: list[str], large_block: Optional[int] = None,
                   small_block: Optional[int] = None,
                   mesh=None, batch_units: Optional[int] = None,
                   host_codec=None,
                   stage_stats: Optional[dict] = None) -> dict[str, list[int]]:
    """Encode every `base` (.dat) into 14 shard files via the batched
    pipeline.  Returns {base: [crc32c of each shard file] * 14}.

    Volumes are batched together: chunks from different volumes ride the
    same device dispatch, which is what makes the 100-volume HBM-resident
    configuration (BASELINE config 4) one pipeline rather than 100 encodes.

    host_codec: pass an encoder object (or True for the best host codec)
    to run the host pipeline — a reader thread filling staging slots and a
    pool of compute workers, each encoding a span through the fused
    native parity+CRC call (ops/codec.py encode_rows) and pwritev()ing
    its data+parity shard bytes on unbuffered fds.  This is the auto-selected
    fallback on link-capped machines: unlike the reference's synchronous
    loop (ec_encoder.go:194-231) it overlaps file I/O with compute and
    fans the codec out across cores, and it still produces the fused
    shard-file CRCs for the .vif.

    stage_stats: optional dict filled with per-stage busy seconds
    (read/encode+crc/write) and wall time — the pipeline's own answer to
    "which stage is the bottleneck" at any scale.
    """
    from ..storage.erasure_coding import (LARGE_BLOCK_SIZE, SMALL_BLOCK_SIZE,
                                          to_ext)

    large_block = large_block or LARGE_BLOCK_SIZE
    small_block = small_block or SMALL_BLOCK_SIZE
    plans = [_plan_volume(b, large_block, small_block) for b in bases]
    chunk = _chunk_len(large_block, small_block)
    units = _make_units(plans, chunk)

    if not units:
        out = {}
        for vi, p in enumerate(plans):
            _ShardFileSet(p.base, to_ext).close()
            out[p.base] = [0] * TOTAL_SHARDS
        return out
    if host_codec:
        return _encode_units_host(plans, units, chunk, host_codec,
                                  stage_stats)
    _, _, flush_bytes, drop_cache = _write_knobs()
    pacer = _WritebackPacer(flush_bytes, drop_cache)
    writers = {vi: _ShardFileSet(
                   p.base, to_ext,
                   (p.rows[-1][1] + p.rows[-1][2]) if p.rows else 0,
                   pacer)
               for vi, p in enumerate(plans)}
    return _encode_units_device(plans, units, chunk, writers, mesh,
                                batch_units, stage_stats)


class _PipelineIO:
    """Shared reader/writer scaffolding of the streaming pipeline:
    pooled staging slots, backpressure queues, the reader thread (fills
    slots and appends data shards), the writer thread (appends parity
    shards), and the torn-shutdown sequencing.  The device compute
    stages differ only in what happens between `ready` and `parity_q`.

    Staging slots are leased from the device slab pool so repeated
    encodes with the same geometry reuse the same buffers.  Two layouts:

      "bk" — (B, 10, L): the TPU word/sharded steps' input layout; every
             unit's 10 rows are zero-padded to the format (device CRC
             covers all 14 shards).
      "kb" — (10, B, L): the pooled CPU parity step's layout — slicing
             [:k_max] off axis 0 compacts away trailing all-zero rows
             as one contiguous view, and each shard row stays contiguous
             for readinto/pwritev/host-CRC.

    Either way the reader skips zero-padding rows' shard writes (the
    files are ftruncate zeros already) and trims partial tail rows to
    their real bytes; `ready` items carry the batch's compacted row
    count k_max ("bk" readers always report the full 10)."""

    def __init__(self, plans, units, chunk, writers, b, layout, pool,
                 n_slots=_SLOTS):
        self.plans, self.units, self.chunk = plans, units, chunk
        self.writers, self.b = writers, b
        self.layout = layout
        self.pool = pool
        self.n_batches = (len(units) + b - 1) // b
        self.dats = [open(p.base + ".dat", "rb") for p in plans]
        self.timers = {"read": 0.0, "dispatch": 0.0, "encode_crc": 0.0,
                       "write": 0.0}
        self.tlock = threading.Lock()
        shape = (b, DATA_SHARDS, chunk) if layout == "bk" \
            else (DATA_SHARDS, b, chunk)
        self._slot_leases = []
        self.free_slots: "queue.Queue" = queue.Queue()
        key = ("ec-stage", layout, shape)
        nbytes = b * DATA_SHARDS * chunk
        for _ in range(n_slots):
            ls = pool.lease(key, lambda: np.zeros(shape, dtype=np.uint8),
                            nbytes)
            self._slot_leases.append(ls)
            self.free_slots.put(ls)
        self.ready: "queue.Queue" = queue.Queue(maxsize=n_slots)
        self.parity_q: "queue.Queue" = queue.Queue(maxsize=n_slots)
        self.errors: list[BaseException] = []
        self.stop = threading.Event()
        self._rt = threading.Thread(target=self._reader, daemon=True)
        self._wt = threading.Thread(target=self._writer, daemon=True)

    def put(self, q, item) -> bool:
        while not self.stop.is_set():
            try:
                q.put(item, timeout=0.5)
                return True
            except queue.Full:
                continue
        return False

    def get(self, q):
        while not self.stop.is_set():
            try:
                return q.get(timeout=0.5)
            except queue.Empty:
                continue
        return None

    def _fill_row(self, u: _Unit, i: int, row: np.ndarray) -> int:
        """Read shard row i of the unit into `row`, zero-padding a short
        read; returns the count of real .dat bytes in the row."""
        dat = self.dats[u.vol]
        start = u.row_start + i * u.block_size + u.col
        dat.seek(start)
        got = dat.readinto(memoryview(row).cast("B"))
        if got < self.chunk:
            row[got:] = 0
        return min(self.chunk, self.plans[u.vol].dat_size - start)

    def _reader(self):
        try:
            for n in range(self.n_batches):
                batch = self.units[n * self.b:(n + 1) * self.b]
                slot = self.get(self.free_slots)
                if slot is None:
                    return
                buf = slot.payload
                t0 = time.perf_counter()
                if self.layout == "kb":
                    k_max = max(u.real_rows for u in batch)
                else:
                    k_max = DATA_SHARDS
                for k, u in enumerate(batch):
                    w = self.writers[u.vol]
                    for i in range(u.real_rows):
                        row = buf[i, k] if self.layout == "kb" \
                            else buf[k, i]
                        real = self._fill_row(u, i, row)
                        w.write(i, [row[:real]], u.shard_off)
                    # zero padding rows up to the compacted height: they
                    # feed the parity math but neither files nor CRCs
                    # (files are ftruncate zeros, CRC is the cached
                    # zeros CRC)
                    for i in range(u.real_rows, k_max):
                        if self.layout == "kb":
                            buf[i, k].fill(0)
                        else:
                            buf[k, i].fill(0)
                with self.tlock:
                    self.timers["read"] += time.perf_counter() - t0
                if not self.put(self.ready, (slot, batch, k_max)):
                    return
            self.put(self.ready, None)
        except BaseException as e:  # propagate to the main thread
            self.errors.append(e)
            self.stop.set()

    def _writer(self):
        try:
            while True:
                item = self.get(self.parity_q)
                if item is None:
                    return
                parity, batch = item
                t0 = time.perf_counter()
                for k, u in enumerate(batch):
                    if u.real_rows == 0:
                        continue  # parity of all-zero rows is zero:
                        #           already on disk via ftruncate
                    w = self.writers[u.vol]
                    for i in range(PARITY_SHARDS):
                        w.write(DATA_SHARDS + i, [parity[k, i]],
                                u.shard_off)
                with self.tlock:
                    self.timers["write"] += time.perf_counter() - t0
        except BaseException as e:
            self.errors.append(e)
            self.stop.set()

    def start(self):
        self._rt.start()
        self._wt.start()

    def finish(self):
        self.put(self.parity_q, None)
        self._wt.join(timeout=60)
        self.stop.set()
        self._rt.join(timeout=30)
        for f in self.dats:
            f.close()
        for w in self.writers.values():
            w.close()
        for ls in self._slot_leases:
            self.pool.release(ls)
        self._slot_leases = []

    def result(self) -> dict[str, list[int]]:
        if self.errors:
            raise self.errors[0]
        from ..stats import metrics as stats

        stats.EcEncodeBytesCounter.inc(
            sum(p.dat_size for p in self.plans))
        return {p.base: self.writers[vi].crcs
                for vi, p in enumerate(self.plans)}


def _device_inflight() -> int:
    """WEED_EC_DEVICE_INFLIGHT: device dispatches in flight before the
    completion thread must drain one (default 3).  Depth hides dispatch
    and transfer latency — H2D, compute and D2H genuinely overlap: the
    staging slots (depth + 1 or more) are the double-buffered H2D ring,
    the donated output slots (depth + 1) the D2H drain ring."""
    try:
        return max(1, int(
            os.environ.get("WEED_EC_DEVICE_INFLIGHT", "") or _INFLIGHT))
    except ValueError:
        return _INFLIGHT


def _fused_crc_on(platform: str) -> bool:
    """WEED_EC_FUSED_CRC: whether the pooled parity step also computes
    every shard row's CRC32C on device ("1"/"0" force it; "auto" — the
    default — fuses off-CPU and keeps the host crc32c walk on CPU
    meshes, where the native kernel is ~30x the GF(2) bit-matmul CRC's
    rate).  With the fused path active the host CRC walk leaves the
    completion thread entirely — the pipeline's critical path is
    read/dispatch/write only."""
    raw = os.environ.get("WEED_EC_FUSED_CRC", "auto").strip().lower()
    if raw in ("1", "on", "true", "fused", "yes"):
        return True
    if raw in ("0", "off", "false", "host", "no"):
        return False
    return platform != "cpu"


def _encode_units_device(plans, units, chunk, writers, mesh,
                         batch_units,
                         stage_stats: Optional[dict] = None
                         ) -> dict[str, list[int]]:
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from ..ops import crc32c as crc_host
    from ..ops.crc_device import finalize
    from ..ops.device_pool import get_pool
    from .mesh import (make_ec_mesh, make_parity_step,
                       make_sharded_encoder, words_capable)

    wall0 = time.perf_counter()
    if mesh is None:
        mesh = make_ec_mesh()  # WEED_EC_DEVICE_SHARD picks the width
    n_data, n_block = mesh.devices.shape
    platform = mesh.devices.flat[0].platform
    # Path selection: the single-TPU-device Pallas words step when it
    # can serve; otherwise the pooled persistent kb step (shard_map over
    # the batch axis on multi-device meshes) whenever the chunk packs
    # into int32 words; the bk XLA step is the odd-chunk fallback.
    use_words = words_capable(mesh, chunk)
    pooled = (not use_words) and chunk % 4 == 0
    # Pooled-path CRC placement (WEED_EC_FUSED_CRC): fused — the parity
    # step also emits every shard row's raw CRC32C image from the same
    # HBM-resident words — or the host crc32c walk on the completion
    # thread (the CPU-mesh default: the native host kernel is ~30x the
    # GF(2) bit-matmul CRC's rate there).
    fused = pooled and _fused_crc_on(platform)
    host_crc = pooled and not fused
    width = (chunk // 4) if pooled else chunk  # sharded trailing axis
    if pooled and n_block != 1:
        # the kb step shards the batch axis only — a shard row's CRC
        # reduces over its whole width, so byte columns stay device-local
        mesh = Mesh(mesh.devices.reshape(-1, 1), mesh.axis_names)
        n_data, n_block = mesh.devices.shape
    elif not pooled and width % n_block:
        mesh = Mesh(mesh.devices.reshape(-1, 1), mesh.axis_names)
        n_data, n_block = mesh.devices.shape

    if batch_units is None:
        batch_units = max(1, TARGET_BATCH_BYTES // (DATA_SHARDS * chunk))
    # ONE fixed compiled shape for every batch in the call (the tail
    # batch is shorter than b; its pad columns are never read back)
    b = min(batch_units, len(units))
    b = max(n_data, ((b + n_data - 1) // n_data) * n_data)

    depth = _device_inflight()
    pool = get_pool()
    single = mesh.devices.size == 1
    dev0 = mesh.devices.flat[0]
    # the pool's free-lists and the link counters key per device; a
    # sharded slab spans the mesh, so it accounts under one composite
    # placement label
    dev_label = str(dev0) if single else f"sharded:{mesh.devices.size}"
    sharding = NamedSharding(mesh, P("data", None, "block"))
    sharding_kb = NamedSharding(mesh, P(None, "data", "block"))

    if pooled:
        step = make_parity_step(mesh, fused_crc=fused)
        layout = "kb"
        backend = ("device-pooled-swar-fused-crc" if fused
                   else "device-pooled-swar")
        # numpy -> jax via dlpack is ZERO-copy on the CPU backend: the
        # staging slot IS the device buffer, so H2D costs nothing (the
        # slot is recycled only after the completion thread synchronized
        # the batch, so the aliased memory is never overwritten mid-read)
        zero_copy = single and dev0 == jax.devices("cpu")[0]
    else:
        # word-layout fast path: packed int32 views move host<->device
        # with no device bitcasts (the relayout costs 10x the kernel)
        step = make_sharded_encoder(mesh, words=use_words)
        layout = "bk"
        backend = "device-words" if use_words else "device-xla"
        zero_copy = False

    # the staging slots double as the H2D ring: the reader fills slot
    # N+1 while slot N's transfer/compute is in flight, at any depth
    n_slots = max(_SLOTS, depth + 1)
    io = _PipelineIO(plans, units, chunk, writers, b, layout, pool,
                     n_slots=n_slots)
    timers = io.timers

    # donated output-slot ring (pooled path): depth+1 device slots the
    # persistent step aliases its parity into — the donation swap means
    # the steady state allocates nothing on device per batch; the ring
    # is also the D2H drain buffer (the completion thread copies out of
    # slot N while slot N+1 is still computing)
    out_ring: "queue.Queue" = queue.Queue()
    out_leases: list = []
    if pooled:
        oshape = (PARITY_SHARDS, b, width)

        def _out_factory():
            z = np.zeros(oshape, dtype=np.int32)
            return jax.device_put(z, dev0 if single else sharding_kb)

        okey = ("ec-out", mesh, oshape)
        for _ in range(depth + 1):
            ls = pool.lease(okey, _out_factory, PARITY_SHARDS * b * chunk,
                            device=dev_label)
            out_leases.append(ls)
            out_ring.put(ls)

    zcrc = crc_host.crc32c_zeros(chunk)
    done_q: "queue.Queue" = queue.Queue(maxsize=depth)
    k_shapes: set = set()
    kernel_lats: list = []  # host-timed dispatch->ready per batch

    def _complete(slot, batch, out, crc_dev, t_disp, k_rows):
        """Synchronize one batch: D2H, per-chunk CRCs chained into the
        rolling shard-file CRCs (FIFO order — CRC chaining is order-
        dependent), slots recycled, parity handed to the writer."""
        buf = slot.payload
        t0 = time.perf_counter()
        if pooled:
            parity = None
            fin = None
            if out is not None:
                # copies out of the donated slot (required: the slot is
                # re-donated for a later batch while the writer thread
                # still holds this parity); blocks until compute done
                parity32 = np.array(out.payload)
                lat = time.perf_counter() - t_disp
                kernel_lats.append(lat)
                profiling.record_device_batch(lat, units=len(batch),
                                              k=k_rows,
                                              devices=mesh.devices.size)
                pool.note_d2h(parity32.nbytes, device=dev_label)
                out_ring.put(out)
                parity = parity32.view(np.uint8).reshape(
                    PARITY_SHARDS, b, chunk)
                if fused:
                    raw = np.asarray(crc_dev)
                    pool.note_d2h(raw.nbytes, device=dev_label)
                    fin = finalize(raw, chunk)  # (k_rows + 4, b)
            if fused:
                # the device already CRC'd every row (padding rows were
                # zeroed in staging, so their image equals the cached
                # zeros CRC) — only the O(1)-per-chunk combines remain
                for k, u in enumerate(batch):
                    w = writers[u.vol]
                    r = u.real_rows
                    for i in range(DATA_SHARDS):
                        c = int(fin[i, k]) if i < k_rows else zcrc
                        w.crcs[i] = crc_host.crc32c_combine(
                            w.crcs[i], c, chunk)
                    for j in range(PARITY_SHARDS):
                        c = int(fin[k_rows + j, k]) if r else zcrc
                        w.crcs[DATA_SHARDS + j] = crc_host.crc32c_combine(
                            w.crcs[DATA_SHARDS + j], c, chunk)
            else:
                t_crc = time.perf_counter()
                for k, u in enumerate(batch):
                    w = writers[u.vol]
                    r = u.real_rows
                    for i in range(DATA_SHARDS):
                        c = crc_host.crc32c(buf[i, k]) if i < r else zcrc
                        w.crcs[i] = crc_host.crc32c_combine(
                            w.crcs[i], c, chunk)
                    for j in range(PARITY_SHARDS):
                        c = crc_host.crc32c(parity[j, k]) if r else zcrc
                        w.crcs[DATA_SHARDS + j] = crc_host.crc32c_combine(
                            w.crcs[DATA_SHARDS + j], c, chunk)
                with io.tlock:
                    # distinct timer: this key's absence from the stage
                    # stats is the proof the fused path took host CRC
                    # off the critical path
                    timers["host_crc"] = timers.get("host_crc", 0.0) \
                        + (time.perf_counter() - t_crc)
            with io.tlock:
                timers["encode_crc"] += time.perf_counter() - t0
            io.free_slots.put(slot)
            if parity is not None:
                # (4, B, L) -> writer's [k][i] indexing as a free view
                io.put(io.parity_q, (parity.transpose(1, 0, 2), batch))
        else:
            parity_dev, crc_dev = out
            # blocks until compute done; sharded gathers can come back
            # non-contiguous, and file writes need a contiguous buffer
            parity = np.ascontiguousarray(np.asarray(parity_dev))
            lat = time.perf_counter() - t_disp
            kernel_lats.append(lat)
            profiling.record_device_batch(lat, units=len(batch), k=k_rows,
                                          devices=mesh.devices.size)
            pool.note_d2h(parity.nbytes, device=dev_label)
            if use_words:  # packed int32 parity words -> bytes
                parity = parity.view(np.uint8).reshape(
                    parity.shape[0], PARITY_SHARDS, chunk)
            crcs = finalize(crc_dev, chunk)
            io.free_slots.put(slot)  # device consumed the transfer
            for k, u in enumerate(batch):
                w = writers[u.vol]
                for s in range(TOTAL_SHARDS):
                    w.crcs[s] = crc_host.crc32c_combine(
                        w.crcs[s], int(crcs[k, s]), chunk)
            with io.tlock:
                timers["encode_crc"] += time.perf_counter() - t0
            io.put(io.parity_q, (parity, batch))

    def _completion():
        try:
            while True:
                item = io.get(done_q)
                if item is None:
                    return
                _complete(*item)
        except BaseException as e:
            io.errors.append(e)
            io.stop.set()

    ct = threading.Thread(target=_completion, daemon=True)
    io.start()
    ct.start()
    try:
        while not io.stop.is_set():
            item = io.get(io.ready)
            if item is None:
                break
            slot, batch, k_max = item
            buf = slot.payload
            # background device lane: bulk encode yields to in-flight
            # foreground (degraded-read recover) decodes per batch
            lane_wait = _lanes.LANES.background_checkpoint()
            if lane_wait:
                with io.tlock:
                    timers["lane_wait"] = timers.get("lane_wait", 0.0) \
                        + lane_wait
            t0 = time.perf_counter()
            crc_dev = None
            if pooled:
                out = None
                if k_max > 0:
                    k_shapes.add(k_max)
                    words = buf.view(np.int32)[:k_max]
                    if zero_copy:
                        din = jax.dlpack.from_dlpack(words)
                    else:
                        din = jax.device_put(
                            words, dev0 if single else sharding_kb)
                        pool.note_h2d(words.nbytes, device=dev_label)
                    out = io.get(out_ring)  # backpressure at `depth`
                    if out is None:
                        break
                    # donation swap: the step aliases its result into
                    # the slot's buffer; the old handle is dead
                    if fused:
                        out.payload, crc_dev = step(din, out.payload)
                    else:
                        out.payload = step(din, out.payload)
            else:
                if use_words:
                    # pin to the mesh's device: the caller may run
                    # several 1-device meshes side by side
                    din = jax.device_put(buf.view(np.int32), dev0)
                else:
                    din = jax.device_put(buf, sharding)
                pool.note_h2d(buf.nbytes, device=dev_label)
                out = step(din)
            with io.tlock:
                timers["dispatch"] += time.perf_counter() - t0
            if not io.put(done_q, (slot, batch, out, crc_dev, t0, k_max)):
                break
        io.put(done_q, None)
        ct.join(timeout=600)
    except BaseException:
        io.stop.set()
        raise
    finally:
        if ct.is_alive():
            io.stop.set()
            ct.join(timeout=30)
        io.finish()
        for ls in out_leases:
            pool.release(ls)
    result = io.result()

    wall = time.perf_counter() - wall0
    # XLA cost analysis once per compiled geometry (pooled SWAR path;
    # StableHLO-level, no backend compile — see mesh.step_cost_analysis)
    kernel_cost = {}
    if pooled:
        from .mesh import step_cost_analysis

        for k in sorted(k_shapes):
            geom = f"k{k}xb{b}xw{width}" + ("f" if fused else "")
            entry = step_cost_analysis(
                step, geom,
                jax.ShapeDtypeStruct((k, b, width), np.int32),
                jax.ShapeDtypeStruct((PARITY_SHARDS, b, width), np.int32))
            if entry is not None:
                kernel_cost[geom] = entry
    if stage_stats is not None:
        stage_stats.update({k: round(v, 3) for k, v in timers.items()})
        stage_stats["wall"] = round(wall, 3)
        stage_stats["backend"] = backend
        stage_stats["batches"] = io.n_batches
        stage_stats["batch_units"] = b
        stage_stats["k_shapes"] = sorted(k_shapes)
        stage_stats["inflight"] = depth
        stage_stats["staging_slots"] = n_slots
        stage_stats["zero_copy_h2d"] = zero_copy
        stage_stats["devices"] = mesh.devices.size
        stage_stats["device_shard"] = dev_label
        stage_stats["crc_path"] = "host" if host_crc else "fused-device"
        for k in ("read", "dispatch", "encode_crc", "write"):
            stage_stats[f"{k}_frac"] = (
                round(timers[k] / wall, 3) if wall > 0 else 0.0)
        if kernel_lats:
            lats = sorted(kernel_lats)
            stage_stats["kernel"] = {
                "batches": len(lats),
                "dispatch_ready_p50_ms": round(
                    lats[len(lats) // 2] * 1e3, 3),
                "dispatch_ready_p95_ms": round(
                    lats[min(len(lats) - 1,
                             int(len(lats) * 0.95))] * 1e3, 3),
                "dispatch_ready_max_ms": round(lats[-1] * 1e3, 3),
            }
        if kernel_cost:
            stage_stats["kernel_cost"] = kernel_cost
        stage_stats["pool"] = pool.snapshot()
    from ..stats import metrics as stats
    for k, v in timers.items():
        stats.EcEncodeStageSeconds.labels(k).set(round(v, 3))
    return result


# Host-pipeline work sizing: a span batches consecutive equal-block rows
# into one contiguous .dat read (the striped rows of ec_encoder.go:57-59
# are adjacent on disk, so R rows = ONE preadv of R*10*block bytes, and
# each shard's R blocks land adjacently in its file = ONE pwritev).
# 30 MB spans measured best: large enough to amortize syscalls, small
# enough that the span is still cache-warm when the fused kernel walks
# it (64 MB spans cost ~20% — the early rows evict before compute).
_HOST_SPAN_BYTES = 30 << 20    # target bytes of .dat per work item
_HOST_SPAN_MAX_BLOCK = 8 << 20  # rows above this get column-chunked
_HOST_COL_CHUNK = 4 << 20       # column width for large-block rows


@dataclass
class _HostWork:
    """One host-pipeline work item: either a contiguous span of `rows`
    equal-size striped rows ((rows, 10, length) straight out of the
    .dat), or one column chunk of a large row (10 strided preads)."""
    vol: int
    kind: str        # "span" | "col"
    dat_off: int     # span: contiguous byte start; col: row start
    shard_off: int
    length: int      # per-shard width L of one row (span) / chunk (col)
    rows: int        # span: R; col: 1
    block_size: int  # col: the row's block size (pread stride)
    col: int = 0     # col: byte offset of the chunk within the block


def _host_work_items(plans) -> list[_HostWork]:
    items: list[_HostWork] = []
    for vi, plan in enumerate(plans):
        pending: Optional[_HostWork] = None
        for row_start, shard_off, block in plan.rows:
            if block <= _HOST_SPAN_MAX_BLOCK:
                # IOV_MAX caps a pwritev at 1024 iovecs (one per row)
                rmax = max(1, min(
                    1024, _HOST_SPAN_BYTES // (DATA_SHARDS * block)))
                if (pending is not None
                        and pending.block_size == block
                        and pending.rows < rmax):
                    pending.rows += 1
                    continue
                if pending is not None:
                    items.append(pending)
                pending = _HostWork(vi, "span", row_start, shard_off,
                                    block, 1, block)
            else:
                if pending is not None:
                    items.append(pending)
                    pending = None
                for col in range(0, block, _HOST_COL_CHUNK):
                    width = min(_HOST_COL_CHUNK, block - col)
                    items.append(_HostWork(vi, "col", row_start,
                                           shard_off + col, width, 1,
                                           block, col))
        if pending is not None:
            items.append(pending)
    return items


def _encode_units_host(plans, units, chunk, host_codec,
                       stage_stats=None) -> dict[str, list[int]]:
    """The host encode path as a true three-stage pipeline.  Work items
    (multi-row spans / column chunks) flow

      read    — a reader thread fills staging slots with contiguous
                preadv()s of the .dat;
      encode  — a pool of compute workers (WEED_EC_HOST_WORKERS, default
                one per *available* core, each releasing the GIL inside
                the fused native parity+CRC kernel) encodes into pooled
                parity slots;
      write   — a dedicated writer pool drains a bounded hand-off queue,
                coalescing adjacent spans into one pwritev per shard
                file and pacing dirty-page writeback (_WritebackPacer)
                so scale runs don't stall on a full dirty-page budget.

    Compute workers hand (data, parity, crcs) to the writer stage and
    immediately pull the next item instead of blocking on 14 synchronous
    pwritev calls — at 300-volume scale the write stage was 93.5% of
    wall time while the codec sat idle.  Parity slots are pooled rather
    than thread-local because with write-behind a slot outlives its
    compute call until the writer stage releases it (each worker
    effectively double-buffers).

    On a single-core host everything runs inline in the calling thread —
    profiling showed reader/worker threads on one core cost ~3x in GIL
    convoying around every ctypes/syscall boundary.  WEED_EC_WRITE_BEHIND=0
    degrades to the two-stage form (compute workers write synchronously),
    byte- and CRC-identical either way.

    stage_stats (optional dict) gets per-stage busy seconds + fractions
    (read / encode_crc / write / flush): the pipeline's own answer to
    "which stage is the bottleneck"."""
    import time as _t
    from concurrent.futures import ThreadPoolExecutor

    from ..ops import codec as codec_mod
    from ..ops import crc32c as crc_host
    from ..storage.erasure_coding import to_ext

    enc = host_codec if hasattr(host_codec, "_apply") \
        else codec_mod.new_host_encoder(DATA_SHARDS, PARITY_SHARDS)
    parity_matrix = np.ascontiguousarray(
        np.asarray(enc.matrix[DATA_SHARDS:], dtype=np.uint8))
    fused = hasattr(enc, "encode_rows")

    nworkers = int(os.environ.get("WEED_EC_HOST_WORKERS", "0") or 0)
    if nworkers <= 0:
        from ..util.platform import available_cpu_count

        # affinity-aware: an affinity-restricted box must not over-spawn
        # workers onto cores it cannot use (ADVICE.md bench.py:969)
        nworkers = max(1, min(16, available_cpu_count()))

    write_behind, nwriters, flush_bytes, drop_cache = _write_knobs()
    write_behind = write_behind and nworkers > 1
    if nwriters <= 0:
        nwriters = max(1, min(4, nworkers // 2))
    if not write_behind:
        nwriters = 0

    items = _host_work_items(plans)
    slot_bytes = max(i.rows * DATA_SHARDS * i.length for i in items)
    parity_bytes = max(i.rows * PARITY_SHARDS * i.length for i in items)
    # pooled parity slots (not thread-local: see docstring); sized so
    # compute never starves while the writer pool holds slots in flight
    n_pslots = 1 if nworkers == 1 else nworkers + 2 * nwriters + 2
    parity_free: "queue.Queue[np.ndarray]" = queue.Queue()
    for _ in range(n_pslots):
        parity_free.put(np.empty(parity_bytes, dtype=np.uint8))

    stop = threading.Event()
    errors: list[BaseException] = []
    pacer = _WritebackPacer(flush_bytes, drop_cache)
    dat_fds = [os.open(p.base + ".dat", os.O_RDONLY) for p in plans]
    vols = {vi: _ShardFileSet(
                p.base, to_ext,
                (p.rows[-1][1] + p.rows[-1][2]) if p.rows else 0,
                pacer)
            for vi, p in enumerate(plans)}
    timers = {"read": 0.0, "encode_crc": 0.0, "write": 0.0, "flush": 0.0}
    tlock = threading.Lock()

    def read_item(w: _HostWork, flat: np.ndarray) -> np.ndarray:
        """Fill (and return) the item's (rows, 10, length) view of the
        flat slot buffer, zero-padding past the .dat's EOF."""
        dat_size = plans[w.vol].dat_size
        fd = dat_fds[w.vol]
        nbytes = w.rows * DATA_SHARDS * w.length
        view = flat[:nbytes].reshape(w.rows, DATA_SHARDS, w.length)
        if w.kind == "span":
            span = view.reshape(-1)
            want = min(nbytes, max(0, dat_size - w.dat_off))
            got = 0
            while got < want:
                n = os.preadv(fd, [span[got:want]], w.dat_off + got)
                if n == 0:
                    break
                got += n
            if got < nbytes:
                span[got:] = 0
        else:
            row = view[0]
            for i in range(DATA_SHARDS):
                # shard i's chunk inside the large striped row
                start = w.dat_off + i * w.block_size + w.col
                want = min(w.length, max(0, dat_size - start))
                got = 0
                while got < want:
                    n = os.preadv(fd, [row[i, got:want]], start + got)
                    if n == 0:
                        break
                    got += n
                if got < w.length:
                    row[i, got:] = 0
        return view

    def encode_item(w: _HostWork, data: np.ndarray):
        """Encode stage: parity+CRC into a pooled parity slot.  The slot
        travels with the item to the writer stage (write-behind) or is
        released right after the inline write."""
        t0 = _t.perf_counter()
        while True:  # stop-aware: an error elsewhere must not wedge us
            try:
                pbuf = parity_free.get(timeout=0.5)
                break
            except queue.Empty:
                if stop.is_set():
                    raise RuntimeError("encode pipeline stopped")
        need = w.rows * PARITY_SHARDS * w.length
        parity = pbuf[:need].reshape(w.rows, PARITY_SHARDS, w.length)
        if fused:
            crcs = enc.encode_rows(parity_matrix, data, parity)
        else:
            crcs = [0] * TOTAL_SHARDS
            for r in range(w.rows):
                parity[r] = enc._apply(parity_matrix, data[r])
                for i in range(DATA_SHARDS):
                    crcs[i] = crc_host.crc32c(data[r, i], crcs[i])
                for i in range(PARITY_SHARDS):
                    crcs[DATA_SHARDS + i] = crc_host.crc32c(
                        parity[r, i], crcs[DATA_SHARDS + i])
        with tlock:
            timers["encode_crc"] += _t.perf_counter() - t0
        return pbuf, parity, crcs

    def write_item(w: _HostWork, data: np.ndarray, parity: np.ndarray):
        """Write stage body: the item's data+parity shard spans."""
        t0 = _t.perf_counter()
        v = vols[w.vol]
        for i in range(DATA_SHARDS):
            v.write(i, [data[r, i] for r in range(w.rows)], w.shard_off)
        for i in range(PARITY_SHARDS):
            v.write(DATA_SHARDS + i,
                    [parity[r, i] for r in range(w.rows)], w.shard_off)
        with tlock:
            timers["write"] += _t.perf_counter() - t0

    def encode_write_item(w: _HostWork, data: np.ndarray) -> list[int]:
        """Two-stage form (WEED_EC_WRITE_BEHIND=0): the compute worker
        writes synchronously, as the pipeline always did before the
        writer stage was split out."""
        pbuf, parity, crcs = encode_item(w, data)
        write_item(w, data, parity)
        parity_free.put(pbuf)
        return crcs

    def combine(w: _HostWork, crcs: list[int]):
        v = vols[w.vol]
        for s in range(TOTAL_SHARDS):
            v.crcs[s] = crc_host.crc32c_combine(
                v.crcs[s], crcs[s], w.rows * w.length)

    def qput(q, item) -> bool:
        while not stop.is_set():
            try:
                q.put(item, timeout=0.5)
                return True
            except queue.Full:
                continue
        return False

    def qget(q):
        while not stop.is_set():
            try:
                return q.get(timeout=0.5)
            except queue.Empty:
                continue
        return None

    wall0 = _t.perf_counter()
    try:
        if nworkers == 1:
            flat = np.empty(slot_bytes, dtype=np.uint8)
            for w in items:
                t0 = _t.perf_counter()
                data = read_item(w, flat)
                timers["read"] += _t.perf_counter() - t0
                pbuf, parity, crcs = encode_item(w, data)
                write_item(w, data, parity)
                parity_free.put(pbuf)
                combine(w, crcs)
        else:
            n_slots = max(_SLOTS, nworkers + 2 * nwriters + 2)
            free_slots: "queue.Queue[np.ndarray]" = queue.Queue()
            for _ in range(n_slots):
                free_slots.put(np.empty(slot_bytes, dtype=np.uint8))
            ready: "queue.Queue" = queue.Queue(maxsize=n_slots)
            write_q: "queue.Queue" = queue.Queue(maxsize=2 * nwriters + 2)

            def reader():
                try:
                    for w in items:
                        flat = qget(free_slots)
                        if flat is None:
                            return
                        t0 = _t.perf_counter()
                        data = read_item(w, flat)
                        with tlock:
                            timers["read"] += _t.perf_counter() - t0
                        if not qput(ready, (flat, data, w)):
                            return
                    qput(ready, None)
                except BaseException as e:
                    errors.append(e)
                    stop.set()

            # the writer pool: items arrive in stripe order (the main
            # loop combines and enqueues in submission order), so a
            # writer can coalesce the adjacent spans queued behind its
            # current item into ONE pwritev per shard file
            _GROUP_MAX = 8  # spans per coalesced group

            def write_group(group):
                t0 = _t.perf_counter()
                v = vols[group[0][0].vol]
                base_off = group[0][0].shard_off
                for s in range(TOTAL_SHARDS):
                    iovs = []
                    for (w, _flat, data, parity, _pbuf) in group:
                        src = data if s < DATA_SHARDS else parity
                        j = s if s < DATA_SHARDS else s - DATA_SHARDS
                        for r in range(w.rows):
                            iovs.append(src[r, j])
                    v.write(s, iovs, base_off)
                with tlock:
                    timers["write"] += _t.perf_counter() - t0
                for (_w, flat, _data, _parity, pbuf) in group:
                    free_slots.put(flat)
                    parity_free.put(pbuf)

            def writer_loop():
                carry = None
                try:
                    while True:
                        if carry is not None:
                            item, carry = carry, None
                        else:
                            item = qget(write_q)
                        if item is None:
                            return
                        group = [item]
                        rows = item[0].rows
                        while len(group) < _GROUP_MAX:
                            try:
                                nxt = write_q.get_nowait()
                            except queue.Empty:
                                break
                            if nxt is None:
                                # a sibling's sentinel: hand it back
                                write_q.put(None)
                                break
                            lw, nw = group[-1][0], nxt[0]
                            if (nw.vol != lw.vol
                                    or nw.shard_off != lw.shard_off
                                    + lw.rows * lw.length
                                    or rows + nw.rows > _IOV_MAX):
                                carry = nxt
                                break
                            group.append(nxt)
                            rows += nw.rows
                        write_group(group)
                except BaseException as e:
                    errors.append(e)
                    stop.set()

            rt = threading.Thread(target=reader, daemon=True)
            rt.start()
            wthreads = [threading.Thread(target=writer_loop, daemon=True)
                        for _ in range(nwriters)]
            for wt in wthreads:
                wt.start()
            pool = ThreadPoolExecutor(max_workers=nworkers)
            # keep up to nworkers+1 items in flight; combine in order
            # (per-file CRCs chain in stripe order, and in-order hand-off
            # is what lets the writer pool coalesce adjacent spans)
            pending: list = []
            try:
                done = False
                while not done and not stop.is_set():
                    try:
                        item = ready.get(timeout=0.5)
                    except queue.Empty:
                        continue
                    if item is None:
                        done = True
                    else:
                        flat, data, w = item
                        fn = encode_item if write_behind else \
                            encode_write_item
                        pending.append(
                            (w, flat, data, pool.submit(fn, w, data)))
                    while pending and (len(pending) > nworkers or done):
                        w, flat, data, fut = pending.pop(0)
                        if write_behind:
                            pbuf, parity, crcs = fut.result()
                            combine(w, crcs)
                            if not qput(write_q,
                                        (w, flat, data, parity, pbuf)):
                                break
                        else:
                            combine(w, fut.result())
                            free_slots.put(flat)
                for _ in range(nwriters):
                    qput(write_q, None)
                for wt in wthreads:
                    wt.join(timeout=600)
                if errors:
                    raise errors[0]
            except BaseException:
                stop.set()
                if errors:  # the root cause, not a secondary unwind
                    raise errors[0] from None
                raise
            finally:
                stop.set()
                pool.shutdown(wait=True)
                rt.join(timeout=30)
                for wt in wthreads:
                    wt.join(timeout=5)
    finally:
        for fd in dat_fds:
            os.close(fd)
        for v in vols.values():
            v.close()

    wall = _t.perf_counter() - wall0
    # the pacer flushes inside timed write sections: attribute its time
    # to the flush stage, not double-counted under write
    timers["flush"] = pacer.flush_seconds
    timers["write"] = max(0.0, timers["write"] - pacer.flush_seconds)
    if stage_stats is not None:
        stage_stats.update({k: round(v, 3) for k, v in timers.items()})
        stage_stats["wall"] = round(wall, 3)
        stage_stats["backend"] = "host-pipeline"
        stage_stats["workers"] = nworkers
        stage_stats["writers"] = nwriters
        stage_stats["write_behind"] = write_behind
        stage_stats["fused"] = fused
        stage_stats["items"] = len(items)
        stage_stats["flushes"] = pacer.flushes
        for k in ("read", "encode_crc", "write", "flush"):
            stage_stats[f"{k}_frac"] = (
                round(timers[k] / wall, 3) if wall > 0 else 0.0)
    from ..stats import metrics as stats
    stats.EcEncodeBytesCounter.inc(sum(p.dat_size for p in plans))
    for k, v in timers.items():
        stats.EcEncodeStageSeconds.labels(k).set(round(v, 3))
    if pacer.flushes:
        stats.EcWritebackFlushCounter.inc(pacer.flushes)
    # the stage timers aggregate busy seconds across worker threads, so
    # they become synthesised child spans of one encode root (recorded
    # before the root finishes — retention is decided at the root)
    from .. import tracing
    root = tracing.start(
        "ec.encode_volumes",
        tags={"volumes": len(plans), "workers": nworkers,
              "writers": nwriters, "items": len(items)})
    root.start_ts -= wall
    for k, v in timers.items():
        tracing.record_span(f"ec.encode.{k}", v, parent=root)
    root.finish(duration=wall)
    return {p.base: vols[vi].crcs for vi, p in enumerate(plans)}


def rebuild_matrix(present: list[int], missing: list[int],
                   data_shards: int = DATA_SHARDS,
                   total_shards: int = TOTAL_SHARDS):
    """(survivor_ids, M) with M (len(missing) x data_shards) mapping the
    chosen survivors directly to the missing shards: data rows come from
    the inverted survivor submatrix, parity rows from encode-rows times
    that inverse (the one-matmul form of klauspost Reconstruct).  Row
    construction lives in ops.rs_numpy.decode_rows — the same cached
    decode plans the degraded-read path uses — so a rebuild right after
    an incident's reads pays zero extra inversions."""
    from ..ops.rs_numpy import decode_rows

    chosen = present[:data_shards]
    rows = decode_rows(data_shards, total_shards, chosen, tuple(missing))
    return chosen, np.array(rows, dtype=np.uint8, copy=True)


def rebuild_shards(base: str, mesh=None,
                   batch_units: Optional[int] = None) -> dict[int, int]:
    """Regenerate every missing .ecNN from survivors through the batched
    device pipeline (RebuildEcFiles, ec_encoder.go:233-287 — the
    reference loops 1 MB buffers through its CPU codec; here survivor
    chunks batch into (B, 10, L) device dispatches with fused CRC32C of
    the rebuilt shards).  Returns {shard_id: crc32c of the rebuilt file}.
    """
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from ..ops import crc32c as crc_host
    from ..ops.crc_device import finalize
    from ..ops.device_pool import get_pool
    from ..storage.erasure_coding import to_ext
    from .mesh import make_ec_mesh, make_sharded_apply

    present = [i for i in range(TOTAL_SHARDS)
               if os.path.exists(base + to_ext(i))]
    missing = [i for i in range(TOTAL_SHARDS) if i not in present]
    if not missing:
        return {}
    if len(present) < DATA_SHARDS:
        raise ValueError(
            f"too few shards to rebuild: {len(present)} < {DATA_SHARDS}")
    chosen, matrix = rebuild_matrix(present, missing)
    sizes = {os.path.getsize(base + to_ext(i)) for i in chosen}
    if len(sizes) != 1:
        raise ValueError(f"survivor shard sizes differ: {sorted(sizes)}")
    shard_size = sizes.pop()
    if shard_size == 0:
        for sid in missing:
            open(base + to_ext(sid), "wb").close()
        return {sid: 0 for sid in missing}

    chunk = min(MAX_CHUNK_BYTES, shard_size)
    offsets = list(range(0, shard_size, chunk))

    if mesh is None:
        mesh = make_ec_mesh()
    n_data, n_block = mesh.devices.shape
    if chunk % n_block:
        mesh = Mesh(mesh.devices.reshape(-1, 1), mesh.axis_names)
        n_data, n_block = mesh.devices.shape
    if batch_units is None:
        batch_units = max(1, TARGET_BATCH_BYTES // (DATA_SHARDS * chunk))
    b = min(batch_units, len(offsets))
    b = max(n_data, ((b + n_data - 1) // n_data) * n_data)

    step = make_sharded_apply(mesh, matrix)
    sharding = NamedSharding(mesh, P("data", None, "block"))
    pool = get_pool()
    dev_label = (str(mesh.devices.flat[0]) if mesh.devices.size == 1
                 else f"sharded:{mesh.devices.size}")
    # two pooled staging buffers: a buffer is refilled only after its
    # batch drained (which implies the host->device transfer completed);
    # leased from the slab pool so consecutive rebuilds with the same
    # geometry reuse them instead of reallocating.  The lease carries
    # the mesh's placement label: a rebuild against one device set must
    # never be handed a slab staged for a different one.
    skey = ("rebuild-stage", (b, DATA_SHARDS, chunk))
    slots = [pool.lease(skey,
                        lambda: np.zeros((b, DATA_SHARDS, chunk),
                                         dtype=np.uint8),
                        b * DATA_SHARDS * chunk, device=dev_label)
             for _ in range(2)]

    inputs = [open(base + to_ext(i), "rb") for i in chosen]
    _, _, flush_bytes, drop_cache = _write_knobs()
    pacer = _WritebackPacer(flush_bytes, drop_cache)
    out_fds = {sid: os.open(base + to_ext(sid),
                            os.O_CREAT | os.O_TRUNC | os.O_WRONLY, 0o644)
               for sid in missing}
    for fd in out_fds.values():
        os.ftruncate(fd, shard_size)
    crcs = {sid: 0 for sid in missing}
    # write-behind: rebuilt batches are handed to a writer thread so the
    # next device dispatch isn't serialized behind checked pwritevs; the
    # pacer keeps large rebuilds from stalling on dirty-page writeback
    werrs: list[BaseException] = []
    wq: "queue.Queue" = queue.Queue(maxsize=2)

    def wb_writer():
        try:
            while True:
                item = wq.get()
                if item is None:
                    return
                batch_offs, out = item
                for k, off in enumerate(batch_offs):
                    width = min(chunk, shard_size - off)
                    for j, sid in enumerate(missing):
                        fd = out_fds[sid]
                        _pwritev_full(fd, [out[k, j, :width]], off)
                        pacer.wrote(fd, off, width)
        except BaseException as e:
            werrs.append(e)

    wt = threading.Thread(target=wb_writer, daemon=True)
    wt.start()
    try:
        inflight: list = []

        def drain_one():
            batch_offs, out_dev, crc_dev = inflight.pop(0)
            out = np.ascontiguousarray(np.asarray(out_dev))
            pool.note_d2h(out.nbytes, device=dev_label)
            raw = np.asarray(crc_dev)
            for k, off in enumerate(batch_offs):
                width = min(chunk, shard_size - off)
                fin = finalize(raw[k], chunk)
                for j, sid in enumerate(missing):
                    # chunks are full except possibly the last; a short
                    # final chunk was zero-padded on device, and CRCs of
                    # zero-extended data un-extend via combine algebra
                    chunk_crc = int(fin[j]) if width == chunk else \
                        crc_host.crc32c(out[k, j, :width].tobytes())
                    crcs[sid] = crc_host.crc32c_combine(
                        crcs[sid], chunk_crc, width)
            while True:  # `out` is fresh per drain — safe to hand off
                if werrs:
                    raise werrs[0]
                try:
                    wq.put((batch_offs, out), timeout=0.5)
                    return None
                except queue.Full:
                    continue

        for step_i, start in enumerate(range(0, len(offsets), b)):
            buf = slots[step_i % 2].payload
            batch_offs = offsets[start:start + b]
            for k, off in enumerate(batch_offs):
                width = min(chunk, shard_size - off)
                for i, f in enumerate(inputs):
                    f.seek(off)
                    view = memoryview(buf[k, i])[:width]
                    got = f.readinto(view)
                    if got < width:
                        buf[k, i, got:width] = 0
                    if width < chunk:
                        buf[k, i, width:] = 0
            dev = jax.device_put(buf, sharding)
            pool.note_h2d(buf.nbytes, device=dev_label)
            out_dev, crc_dev = step(dev)
            inflight.append((batch_offs, out_dev, crc_dev))
            if len(inflight) >= 2:
                drain_one()
        while inflight:
            drain_one()
    finally:
        for sl in slots:
            pool.release(sl)
        try:
            wq.put(None, timeout=5)
        except queue.Full:
            pass
        wt.join(timeout=120)
        for f in inputs:
            f.close()
        for fd in out_fds.values():
            os.close(fd)
    if werrs:
        raise werrs[0]
    return crcs
