"""Multi-chip sharded EC compute: pjit over a (volume, block) device mesh.

The reference has no analogue (per-volume sequential CPU encode,
ec_encoder.go:194-231); this is where the TPU build scales out.  The natural
parallel axes of RS coding:

  * "data"  — the volume/batch axis (independent volumes encode in
    parallel; data-parallel)
  * "block" — the byte-column axis within a shard row (RS parity is
    columnwise, so the L axis shards cleanly; the sequence-parallel
    analogue per SURVEY.md §5.7)

Parity needs no cross-device communication; the fused CRC32C integrity
pass reduces over the sharded block axis, so XLA inserts the collective
over ICI.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops import gf256
from ..ops.rs_jax import _bit_matrix_cached, _matrix_key
from ..util import glog


def make_mesh(devices=None, axes: tuple[str, str] = ("data", "block")
              ) -> Mesh:
    """Mesh over all devices: batch axis gets the larger factor."""
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    block = 1
    for cand in (2, 1):
        if n % cand == 0 and n // cand >= 1:
            block = cand
            break
    arr = np.array(devices).reshape(n // block, block)
    return Mesh(arr, axes)


def shard_devices(devices=None) -> list:
    """The device set the EC dispatch path shards batches over, governed
    by WEED_EC_DEVICE_SHARD:

      <int>  — exactly that many devices (clamped to what exists)
      "auto" / unset — every device on real accelerators; on CPU
               backends, min(devices, usable host cores).  XLA's virtual
               CPU devices beyond the physical core count only add
               partitioning overhead, and a 1-device mesh restores the
               zero-copy dlpack H2D path — on a 1-core box "auto"
               collapses the 8-way virtual mesh back to the fast path.
    """
    if devices is None:
        devices = jax.devices()
    raw = os.environ.get("WEED_EC_DEVICE_SHARD", "").strip().lower()
    n = len(devices)
    if raw and raw != "auto":
        try:
            n = max(1, min(len(devices), int(raw)))
        except ValueError:
            pass
    elif devices[0].platform == "cpu":
        try:
            cores = len(os.sched_getaffinity(0))
        except AttributeError:  # pragma: no cover - non-linux
            cores = os.cpu_count() or 1
        n = max(1, min(len(devices), cores))
    return list(devices)[:n]


def make_ec_mesh(devices=None) -> Mesh:
    """The EC dispatch mesh: shard_devices() laid out (n, 1) — batches
    shard over the "data" axis only.  The fused CRC reduces over a whole
    shard row, so the byte-column ("block") axis stays device-local and
    every per-row CRC completes without a cross-device combine."""
    devs = shard_devices(devices)
    return Mesh(np.array(devs).reshape(-1, 1), ("data", "block"))


def _parity_bits_matmul(bit_matrix, data):
    """(B, d, L) uint8 -> (B, p, L) uint8 parity via MXU bit-matmul."""
    b, d, length = data.shape
    shifts = jnp.arange(8, dtype=jnp.uint8)
    bits = ((data[:, :, None, :] >> shifts[None, None, :, None]) & 1
            ).astype(jnp.int8).reshape(b, d * 8, length)
    prod = jax.lax.dot_general(
        bit_matrix, bits,
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.int32,
    )  # (p*8, B, L)
    out_bits = (prod & 1).astype(jnp.uint8)
    p8 = out_bits.shape[0]
    out_bits = out_bits.reshape(p8 // 8, 8, b, length)
    weights = (jnp.uint8(1) << shifts)[None, :, None, None]
    parity = (out_bits * weights).sum(axis=1, dtype=jnp.uint8)  # (p, B, L)
    return parity.transpose(1, 0, 2)


def batched_swar_encode_step(consts, data):
    """CPU-device variant of the flagship step: SWAR parity (packed
    int32 ops, rs_jax._apply_swar — ~4x the bit-matmul's rate on a CPU
    core, where the 8x bit expansion is pure overhead) + the same fused
    CRC images.  Used by make_sharded_encoder on CPU meshes (the scale-
    validation and virtual-mesh surfaces); TPU meshes keep the MXU
    bit-matmul formulation."""
    from ..ops.crc_device import batched_crc32c_raw
    from ..ops.rs_jax import _apply_swar

    b, d, length = data.shape
    words = jax.lax.bitcast_convert_type(
        data.reshape(b, d, length // 4, 4), jnp.int32)
    out_w = jax.vmap(lambda v: _apply_swar(consts, v, consts.shape[0]))(
        words)
    parity = jax.lax.bitcast_convert_type(out_w, jnp.uint8).reshape(
        b, consts.shape[0], length)
    full = jnp.concatenate([data, parity], axis=1)
    return parity, batched_crc32c_raw(full)


def batched_encode_step(bit_matrix, data):
    """The flagship jittable step: batched parity + fused per-shard CRC32C.

    data: (B, 10, L) uint8 — B independent volume rows.
    Returns (parity (B, 4, L), crc_raw (B, 14) uint32): crc_raw are the raw
    GF(2)-linear CRC32C images of every shard chunk (10 data + 4 parity),
    computed on device by the bit-matmul kernel in ops/crc_device.py while
    the batch is HBM-resident (BASELINE config 5 — the reference CRCs on
    CPU at write time only, needle/crc.go:12-33).  Host side finalizes with
    crc32c.finalize_raw(raw, L) and chains chunks with crc32c_combine.
    """
    from ..ops.crc_device import batched_crc32c_raw

    parity = _parity_bits_matmul(bit_matrix, data)
    full = jnp.concatenate([data, parity], axis=1)  # (B, 14, L)
    crc_raw = batched_crc32c_raw(full)
    return parity, crc_raw


_ENCODER_CACHE: dict = {}
_APPLY_CACHE: dict = {}
_PALLAS_OK: dict = {}
_PARITY_STEP_CACHE: dict = {}


def make_parity_step(mesh: Mesh, data_shards: int = 10,
                     parity_shards: int = 4,
                     matrix=None, key=None, fused_crc: bool = False):
    """Persistent parity step for the pooled device dispatch path:
    (data32 (k, B, W) int32 packed bytes, out (p, B, W) int32 DONATED)
    -> (p, B, W) int32 parity words, plus — with fused_crc — the raw
    CRC32C images (k + p, B) uint32 of every data and parity row,
    computed on device over the same HBM-resident words the parity SWAR
    reads (host side finalizes with crc_device.finalize).

    The k axis is the COMPACTED data-row count: trailing all-zero shard
    rows (the format's zero-padded tail striping) contribute nothing to
    parity, so the caller slices them off and the step retraces per
    distinct k (bounded by data_shards shapes).  The donated `out` slot
    makes XLA alias the result into the same device buffer every batch,
    which is what lets the steady state run with zero per-batch device
    allocations.

    Multi-device meshes run the step through shard_map: the batch axis
    partitions over "data" with PartitionSpec, every device computes the
    parity (and fused CRC) of its own batch slice, and no collective is
    needed because a shard row's bytes never cross devices (the mesh's
    "block" axis must be 1 when fused_crc is set — the CRC reduces over
    the whole W axis).

    fused_crc=False keeps the CPU-mesh default: the host crc32c kernel
    is ~30x the GF(2) bit-matmul CRC's rate on CPU, so the pipeline CRCs
    on host while the next batch is in flight.  TPU meshes fuse.

    One jitted callable per (mesh, geometry, fused_crc), shared across
    encode calls; XLA's shape-keyed trace cache handles per-k retraces.

    matrix / key: an alternative GF(2^8) coefficient matrix (a code
    family's parity or lane generator rows) with an optional hashable
    cache identity (e.g. the family name); omitted, the classic RS
    Vandermonde parity rows are built.  Nothing else about the step —
    donation, sharding, the SWAR bit-plane kernel — changes, so every
    code family rides the same persistent jitted dispatch.
    """
    from ..ops.crc_device import batched_crc32c_raw
    from ..ops.rs_jax import _SPREAD, _bit_constants_cached

    if matrix is None:
        cache_key = (mesh, data_shards, parity_shards, fused_crc)
    else:
        matrix = np.ascontiguousarray(matrix, dtype=np.uint8)
        cache_key = (mesh, key if key is not None else matrix.tobytes(),
                     fused_crc)
    cached = _PARITY_STEP_CACHE.get(cache_key)
    if cached is not None:
        return cached
    if matrix is None:
        matrix = gf256.parity_matrix(data_shards,
                                     data_shards + parity_shards)
    consts = jnp.asarray(_bit_constants_cached(*_matrix_key(matrix)))
    if fused_crc and mesh.devices.shape[1] != 1:
        raise ValueError(
            "fused-CRC parity step needs a (n, 1) mesh: the CRC reduces "
            f"over the block axis, got mesh shape {mesh.devices.shape}")

    def _parity(data32, out):
        # SWAR over packed words, batched over (B, W): one set bit per
        # byte lane after the shift+mask, so the int32 multiply by the
        # per-bit GF constants stays within each byte (rs_jax._apply_swar
        # generalized to a batch axis, unrolled over k*8 bit planes)
        acc = out ^ out  # zeros that READ the donated slot: keeps the
        #                  buffer aliasable into the result
        for j in range(data32.shape[0]):
            x = data32[j]
            for bit in range(8):
                t = jax.lax.shift_right_logical(x, bit) & _SPREAD
                acc = acc ^ (t[None, :, :] * consts[:, j, bit][:, None, None])
        return acc

    def _fused(data32, out):
        parity = _parity(data32, out)
        full = jnp.concatenate([data32, parity], axis=0)  # (k+p, B, W)
        # int32 words -> the row's byte stream: little-endian byte order
        # within a word matches memory order, so the bitcast+reshape is
        # layout-free
        byts = jax.lax.bitcast_convert_type(full, jnp.uint8)
        byts = byts.reshape(full.shape[0], full.shape[1], -1)
        return parity, batched_crc32c_raw(byts)

    body = _fused if fused_crc else _parity
    if mesh.devices.size == 1:
        step = jax.jit(body, donate_argnums=(1,))
    elif fused_crc:
        sh = P(None, "data", None)
        step = jax.jit(
            shard_map(body, mesh=mesh, in_specs=(sh, sh),
                      out_specs=(sh, P(None, "data")), check_rep=False),
            donate_argnums=(1,))
    else:
        sh = P(None, "data", "block")
        step = jax.jit(
            shard_map(body, mesh=mesh, in_specs=(sh, sh), out_specs=sh,
                      check_rep=False),
            donate_argnums=(1,))
    _PARITY_STEP_CACHE[cache_key] = step
    return step


_COST_CACHE: dict = {}


def step_cost_analysis(step, key, *abstract_args):
    """XLA cost analysis (flops / bytes accessed) for `step` at the
    abstract shapes in `abstract_args`, computed once per `key` and
    published to the profiling layer's kernel-cost table.

    Uses ``Lowered.cost_analysis()`` — StableHLO-level, no backend
    compile (~10ms) — so capturing it always-on per compiled geometry is
    safe even inside the encode hot path.  Returns the entry dict, or
    None when analysis is unavailable on this jax build."""
    cached = _COST_CACHE.get(key)
    if cached is not None:
        return cached
    try:
        cost = step.lower(*abstract_args).cost_analysis()
        if isinstance(cost, (list, tuple)):  # older jax: one per device
            cost = cost[0] if cost else {}
        flops = float(cost.get("flops", 0.0))
        nbytes = float(cost.get("bytes accessed", 0.0))
    except Exception:  # cost analysis is telemetry, never fatal
        return None
    from .. import profiling

    entry = {"flops": flops, "bytes_accessed": nbytes}
    _COST_CACHE[key] = entry
    profiling.record_kernel_cost(str(key), flops, nbytes)
    return entry


def _pallas_fused_ok(matrix) -> bool:
    """One-time self-test (per matrix geometry) of the fused Mosaic
    kernel on this backend: compile+run at a production-representative
    shape (the production fused block with a multi-segment combine)
    checked against the host codec.  A Mosaic lowering regression then
    degrades the production encode path to the portable XLA step instead
    of crashing it."""
    from ..ops.rs_pallas import DEFAULT_FUSED_BLOCK

    m = np.ascontiguousarray(matrix, dtype=np.uint8)
    key = (m.tobytes(), m.shape)
    if key in _PALLAS_OK:
        return _PALLAS_OK[key]
    try:
        from ..ops.rs_pallas import fused_encode_words
        from ..ops.rs_numpy import gf_apply_matrix
        from ..ops import crc32c as crc_host

        rng = np.random.default_rng(0)
        # batch >= 2 so BOTH grid dimensions take nonzero indices on the
        # hardware — a bi>0-only miscompile must not pass the guard;
        # drive the exact production invocation (int32 word views)
        data = rng.integers(0, 256,
                            (2, m.shape[1], 2 * DEFAULT_FUSED_BLOCK),
                            dtype=np.uint8)
        parity_w, crcs = fused_encode_words(m, data.view(np.int32),
                                            interpret=False)
        parity = np.ascontiguousarray(np.asarray(parity_w)).view(np.uint8)
        parity = parity.reshape(data.shape[0], m.shape[0], -1)
        crcs = np.asarray(crcs)
        ok = True
        for bi in range(data.shape[0]):
            expect = gf_apply_matrix(m, data[bi])
            ok = ok and np.array_equal(parity[bi], expect)
            full = np.concatenate([data[bi], expect], axis=0)
            ok = ok and all(
                int(crcs[bi, s]) == crc_host.raw_update(
                    0, full[s].tobytes())
                for s in range(full.shape[0]))
        if not ok:
            glog.warningf(
                "fused pallas encode self-test MISMATCHED on this "
                "backend; falling back to the XLA step")
    except Exception as e:
        glog.warningf(
            "fused pallas encode unavailable (%s: %s); falling back to "
            "the XLA step", type(e).__name__, e)
        ok = False
    _PALLAS_OK[key] = ok
    return ok


def make_sharded_apply(mesh: Mesh, matrix: np.ndarray):
    """jit-compiled batched GF(2^8) matrix application with fused CRC32C
    over the OUTPUT rows: data (B, d, L) -> (out (B, k, L) uint8,
    crc_raw (B, k) uint32).  The generalization of the encoder step that
    rebuild uses with reconstruction matrices (survivors -> missing
    shards; RebuildEcFiles, ec_encoder.go:233-287)."""
    from ..ops.crc_device import batched_crc32c_raw

    m = np.ascontiguousarray(matrix, dtype=np.uint8)
    cache_key = (mesh, m.tobytes(), m.shape)
    cached = _APPLY_CACHE.get(cache_key)
    if cached is not None:
        return cached
    if len(_APPLY_CACHE) >= 32:
        # bounded: there are C(14,1..4) ~ 1470 distinct reconstruction
        # matrices — unbounded caching would pin a compiled executable
        # per missing-shard pattern forever
        _APPLY_CACHE.pop(next(iter(_APPLY_CACHE)))
    bit_matrix = jnp.asarray(_bit_matrix_cached(*_matrix_key(m)))
    data_sharding = NamedSharding(mesh, P("data", None, "block"))
    out_shardings = (
        NamedSharding(mesh, P("data", None, "block")),
        NamedSharding(mesh, P("data", None)),
    )

    @functools.partial(
        jax.jit,
        in_shardings=(data_sharding,),
        out_shardings=out_shardings,
        donate_argnums=(0,),
    )
    def step(data):
        out = _parity_bits_matmul(bit_matrix, data)
        return out, batched_crc32c_raw(out)

    _APPLY_CACHE[cache_key] = step
    return step


def words_capable(mesh: Mesh, chunk_len: int,
                  data_shards: int = 10, parity_shards: int = 4) -> bool:
    """True when the word-layout fused Pallas step can serve (single
    real-TPU device, fusable chunk length).  The words step moves packed
    int32 views host<->device with NO device bitcasts — the production
    fast path."""
    from ..ops.rs_pallas import fused_encode_block

    matrix = gf256.parity_matrix(data_shards, data_shards + parity_shards)
    return (mesh.devices.size == 1 and chunk_len % 4 == 0
            and bool(fused_encode_block(chunk_len))
            and mesh.devices.flat[0].platform == "tpu"
            and _pallas_fused_ok(matrix))


def make_sharded_encoder(mesh: Mesh, data_shards: int = 10,
                         parity_shards: int = 4, words: bool = False):
    """jit-compiled batched encoder with shardings over the mesh:
    batch -> "data" axis, byte columns -> "block" axis.  Cached per
    (mesh, geometry, layout) so repeated callers reuse the jit cache
    instead of recompiling every batch.

    words=False — portable XLA formulation on (B, d, L) uint8, which
    GSPMD partitions over multi-device meshes.
    words=True  — the fused word-layout Pallas kernel on (B, d, L//4)
    int32 views (gate with words_capable first): one VMEM bit expansion
    feeds parity AND CRC, packed words move in both directions, and the
    returned parity is (B, p, L//4) int32 to .view(np.uint8) on host."""
    cache_key = (mesh, data_shards, parity_shards, words)
    cached = _ENCODER_CACHE.get(cache_key)
    if cached is not None:
        return cached
    matrix = gf256.parity_matrix(
        data_shards, data_shards + parity_shards)
    bit_matrix = jnp.asarray(_bit_matrix_cached(*_matrix_key(matrix)))

    if words:
        from ..ops.rs_pallas import fused_encode_words

        @functools.partial(jax.jit, donate_argnums=(0,))
        def step(data_words):
            return fused_encode_words(matrix, data_words,
                                      interpret=False)
    else:
        data_sharding = NamedSharding(mesh, P("data", None, "block"))
        out_shardings = (
            NamedSharding(mesh, P("data", None, "block")),  # parity
            NamedSharding(mesh, P("data", None)),  # crc_raw
        )
        on_cpu_mesh = mesh.devices.flat[0].platform == "cpu"
        consts = None
        if on_cpu_mesh:
            from ..ops.rs_jax import _bit_constants_cached

            consts = jnp.asarray(
                _bit_constants_cached(*_matrix_key(matrix)))

        @functools.partial(
            jax.jit,
            in_shardings=(data_sharding,),
            out_shardings=out_shardings,
            donate_argnums=(0,),
        )
        def step(data):
            # SWAR packs 4 bytes per int32 lane; odd chunk lengths keep
            # the (length-agnostic) bit-matmul formulation
            if on_cpu_mesh and data.shape[-1] % 4 == 0:
                return batched_swar_encode_step(consts, data)
            return batched_encode_step(bit_matrix, data)

    _ENCODER_CACHE[cache_key] = step
    return step


def encode_batch(data: np.ndarray, mesh: Mesh | None = None):
    """Host convenience: shard a (B, 10, L) batch over the mesh and encode.

    Returns (parity (B, 4, L), crcs (B, 14) uint32) with the device CRC32C
    values finalized to standard form (crc32c of each shard chunk).
    """
    from ..ops.crc_device import finalize

    if mesh is None:
        mesh = make_mesh()
    data = np.ascontiguousarray(data).astype(np.uint8, copy=False)
    b, d, length = data.shape
    if words_capable(mesh, length):
        step = make_sharded_encoder(mesh, words=True)
        parity_w, crc_raw = step(jax.device_put(data.view(np.int32),
                                                mesh.devices.flat[0]))
        parity = np.ascontiguousarray(np.asarray(parity_w)) \
            .view(np.uint8).reshape(b, -1, length)
        return parity, finalize(crc_raw, length)
    step = make_sharded_encoder(mesh)
    sharding = NamedSharding(mesh, P("data", None, "block"))
    device_data = jax.device_put(jnp.asarray(data, dtype=jnp.uint8),
                                 sharding)
    parity, crc_raw = step(device_data)
    return np.asarray(parity), finalize(crc_raw, data.shape[-1])
