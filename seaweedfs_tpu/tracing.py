"""Distributed request tracing with hot-path span profiling.

One client request fans out across master, filer, volume and s3 daemons
over rpc/http_rpc.py; before this module each subsystem grew its own
ad-hoc stage stats (encode stage_stats, RecoverStats) and nothing tied a
slow reply to the hop or kernel stage that caused it.  Here:

  * trace context (trace id, parent span id, sampling bit) rides every
    outbound ``call``/``call_stream`` as ``X-Trace-Id`` / ``X-Span-Id`` /
    ``X-Trace-Sampled`` headers and is extracted in ``RpcServer``
    dispatch, so spans from all daemons in a request share one trace;
  * hot paths (needle read/write, fsync group commit, chunk assembly,
    EC encode stages, degraded-read fetch/decode/serve) open child spans
    under the enclosing server span;
  * a process-wide bounded recorder keeps whole traces: every sampled
    trace (probability ``WEED_TRACE_SAMPLE``), plus — always on — any
    trace containing a span slower than ``WEED_TRACE_SLOW_MS``.  Fast
    unsampled spans bypass the recorder entirely, so the steady-state
    cost with sampling off is just the duration measurement; a slow
    span promotes its trace from that span onward;
  * ``GET /debug/traces`` (recent index) and ``GET /debug/traces/<id>``
    (full span tree) are mounted on every daemon.

The daemons share one process in tests/bench (like stats.REGISTRY), so
the recorder is process-global and spans carry a ``service`` label —
"spans two daemons" means two distinct services in one trace.

Knobs (env, read live so daemons/tests flip them without restarts):
  WEED_TRACE_SAMPLE      probability a new trace is kept (default 0.01)
  WEED_TRACE_SLOW_MS     always-keep threshold per span (default 250)
  WEED_TRACE_MAX_TRACES  recorder trace capacity (default 256)
  WEED_TRACE_MAX_SPANS   per-trace span cap (default 512)
"""

from __future__ import annotations

import itertools
import os
import random
import threading
import time
from collections import OrderedDict
from typing import Optional

from .stats import metrics as _stats

TRACE_HEADER = "X-Trace-Id"
SPAN_HEADER = "X-Span-Id"
SAMPLED_HEADER = "X-Trace-Sampled"
SRC_HEADER = "X-Trace-Src"


# The knobs below are read on every span, which on the gateway hot path
# means several os.environ round-trips (str encode + wrapper dict) per
# request.  They must stay *live* (tests flip them mid-process), so the
# parse is memoized against the raw env value: same raw -> cached parse,
# changed raw -> reparse.  CPython keeps the authoritative bytes mapping
# in os.environ._data and os.environ.__setitem__ writes through to it,
# so a direct .get() there is live and one C dict lookup.
_ENV_DATA = getattr(os.environ, "_data", None)
_env_memo: dict = {}


def _env_live(key: str, key_b: bytes, parse, default):
    if _ENV_DATA is not None:
        raw = _ENV_DATA.get(key_b)
    else:  # non-CPython fallback
        raw = os.environ.get(key)
    memo = _env_memo.get(key)
    if memo is not None and memo[0] == raw:
        return memo[1]
    try:
        val = parse(raw) if raw else default
    except ValueError:
        val = default
    _env_memo[key] = (raw, val)
    return val


def sample_rate() -> float:
    return _env_live("WEED_TRACE_SAMPLE", b"WEED_TRACE_SAMPLE",
                     lambda raw: min(1.0, max(0.0, float(raw))), 0.01)


def slow_ms() -> float:
    return _env_live("WEED_TRACE_SLOW_MS", b"WEED_TRACE_SLOW_MS",
                     float, 250.0)


def _env_int(name: str, default: int) -> int:
    return _env_live(name, name.encode(), int, default)


# Sequential ids from a random 63-bit start: unique within the process
# (cross-process traces already share ids via the propagation headers)
# and much cheaper than 64 fresh random bits per span.
_ids = itertools.count(random.getrandbits(62))


def _new_id() -> str:
    return f"{next(_ids):016x}"


class Span:
    __slots__ = ("trace_id", "span_id", "parent_id", "name", "service",
                 "status", "tags", "start_ts", "duration", "sampled",
                 "is_root", "route", "_t0")

    def __init__(self, trace_id: str, span_id: str,
                 parent_id: Optional[str], name: str, service: str,
                 sampled: bool, is_root: bool,
                 tags: Optional[dict] = None):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.service = service
        # the enclosing RPC route ("GET /dir/assign"); dispatch spans
        # are born with name==route, children inherit it in start() —
        # this is what lets the profiler slice samples per route
        self.route = name
        self.status = "ok"
        self.tags = tags
        self.start_ts = time.time()
        self.duration: Optional[float] = None
        self.sampled = sampled
        self.is_root = is_root
        self._t0 = time.perf_counter()

    def set_tag(self, key: str, value):
        if self.tags is None:
            self.tags = {}
        self.tags[key] = value

    def finish(self, status: Optional[str] = None,
               duration: Optional[float] = None):
        """Close the span and hand it to the recorder.  ``duration``
        overrides the measured wall time (spans synthesised from
        externally-measured stage timers)."""
        if self.duration is not None:
            return  # already finished
        if status is not None:
            self.status = status
        self.duration = (duration if duration is not None
                         else time.perf_counter() - self._t0)
        RECORDER.record(self)

    def to_dict(self) -> dict:
        out = {
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "name": self.name,
            "service": self.service,
            "status": self.status,
            "start": round(self.start_ts, 6),
            "duration_ms": round((self.duration or 0.0) * 1000.0, 3),
        }
        if self.tags:
            out["tags"] = self.tags
        return out


_ctx = threading.local()

# mirror of every thread's installed span, keyed by thread ident.  The
# profiler samples OTHER threads' stacks from its own thread, where
# threading.local is unreadable — swap()/restore() keep this map exact
# (same writers, same order), and the sampler prunes dead idents.
_thread_spans: dict = {}


def current() -> Optional[Span]:
    return getattr(_ctx, "span", None)


def swap(span: Optional[Span]) -> Optional[Span]:
    """Install `span` as the thread's current span; returns the previous
    one for restore() (the non-context-manager form used by the server
    dispatch loop)."""
    prev = getattr(_ctx, "span", None)
    _ctx.span = span
    if span is not None:
        _thread_spans[threading.get_ident()] = span
    else:
        _thread_spans.pop(threading.get_ident(), None)
    return prev


def restore(prev: Optional[Span]):
    _ctx.span = prev
    if prev is not None:
        _thread_spans[threading.get_ident()] = prev
    else:
        _thread_spans.pop(threading.get_ident(), None)


def span_for_thread(tid: int) -> Optional[Span]:
    """The span installed on thread `tid`, if any (profiler cross-thread
    read; racy by design — a stale span only mislabels one sample)."""
    return _thread_spans.get(tid)


def prune_thread_spans(live_tids):
    """Drop mirror entries for threads that no longer exist (a pool
    thread that died with a span installed would pin it forever)."""
    dead = [tid for tid in list(_thread_spans) if tid not in live_tids]
    for tid in dead:
        _thread_spans.pop(tid, None)


def start(name: str, service: str = "", parent: Optional[Span] = None,
          tags: Optional[dict] = None) -> Span:
    """Create (but do not install) a span.  With no parent — explicit or
    thread-local — a new root trace starts and takes its sampling
    decision."""
    if parent is None:
        parent = current()
    if parent is not None:
        sp = Span(parent.trace_id, _new_id(), parent.span_id, name,
                  service or parent.service, parent.sampled, False, tags)
        sp.route = parent.route  # children keep the request route
        return sp
    return Span(_new_id(), _new_id(), None, name, service,
                random.random() < sample_rate(), True, tags)


def from_headers(name: str, service: str, headers) -> Span:
    """Server-side extraction: continue the caller's trace when the
    propagation headers are present, else open a new root."""
    trace_id = headers.get(TRACE_HEADER)
    if trace_id:
        return Span(trace_id, _new_id(), headers.get(SPAN_HEADER), name,
                    service, headers.get(SAMPLED_HEADER) == "1", False)
    return Span(_new_id(), _new_id(), None, name, service,
                random.random() < sample_rate(), True)


def tag_qos(span: Span, qos_class: str, tenant: str = "") -> None:
    """Stamp a span with its QoS class.  Background spans get a route
    suffix so the profiler's per-route sample shares (and `weed.py
    profile`) separate background CPU time — replication fan-out,
    curator jobs, deep scrub — from foreground request handling.
    Children inherit the suffixed route via start()."""
    if qos_class and qos_class != "standard":
        span.set_tag("qos_class", qos_class)
    if tenant:
        span.set_tag("qos_tenant", tenant)
    if qos_class == "background" and not span.route.endswith(" [bg]"):
        span.route = span.route + " [bg]"


def inject(headers: dict, span: Optional[Span] = None) -> dict:
    """Stamp the propagation headers for an outbound call (no-op when
    the calling thread carries no span)."""
    sp = span if span is not None else current()
    if sp is not None:
        headers.setdefault(TRACE_HEADER, sp.trace_id)
        headers.setdefault(SPAN_HEADER, sp.span_id)
        headers.setdefault(SAMPLED_HEADER, "1" if sp.sampled else "0")
        if sp.service:
            headers.setdefault(SRC_HEADER, sp.service)
    return headers


class _SpanCtx:
    """Class-based context manager: @contextmanager allocates a
    generator + _GeneratorContextManager per use, which shows up on the
    request hot path (two spans per gateway request)."""

    __slots__ = ("sp", "prev")

    def __init__(self, sp: Span):
        self.sp = sp

    def __enter__(self) -> Span:
        self.prev = swap(self.sp)
        return self.sp

    def __exit__(self, exc_type, exc, tb):
        sp = self.sp
        if exc_type is not None:
            sp.status = f"error: {exc_type.__name__}"
        restore(self.prev)
        sp.finish()
        return False


def span(name: str, service: str = "", parent: Optional[Span] = None,
         tags: Optional[dict] = None) -> _SpanCtx:
    """Open a child span of the thread's current (or explicit `parent`)
    span for the duration of the block.  Pass `parent` explicitly when
    the work runs on a pool thread that did not inherit the request
    thread's context (chunk fan-outs)."""
    return _SpanCtx(start(name, service, parent, tags))


def record_span(name: str, duration: float, service: str = "",
                parent: Optional[Span] = None, tags: Optional[dict] = None,
                status: str = "ok") -> Span:
    """Adopt an externally-measured duration as a finished span (the
    bridge for stage timers aggregated outside a with-block, e.g. the
    encode pipeline's per-stage busy seconds)."""
    sp = start(name, service, parent, tags)
    sp.start_ts -= duration
    sp.finish(status=status, duration=duration)
    return sp


class Recorder:
    """Bounded process-wide trace store.  Sampled traces and traces that
    ever contained a slow span are kept; other traces buffer until their
    root span finishes and are then discarded.  Both the trace count and
    the per-trace span count are capped, so memory is bounded no matter
    the request rate."""

    def __init__(self, max_traces: Optional[int] = None,
                 max_spans: Optional[int] = None):
        self._lock = threading.Lock()
        self._traces: "OrderedDict[str, dict]" = OrderedDict()
        self.max_traces = max_traces
        self.max_spans = max_spans

    def _caps(self) -> tuple[int, int]:
        return (self.max_traces or _env_int("WEED_TRACE_MAX_TRACES", 256),
                self.max_spans or _env_int("WEED_TRACE_MAX_SPANS", 512))

    def record(self, span: Span):
        slow = (span.duration or 0.0) * 1000.0 >= slow_ms()
        if not span.sampled and not slow and \
                span.trace_id not in self._traces:
            # Fast path for the steady state with sampling off: the
            # span can neither start nor join a kept trace, so skip
            # the lock + buffer entirely.  A later slow span still
            # promotes its trace from that point on; the pre-slow fast
            # spans of such a trace are the (deliberate) fidelity cost.
            if span.is_root:
                _stats.TraceRetentionCounter.labels("dropped").inc()
            return
        max_traces, max_spans = self._caps()
        kept = dropped = False
        with self._lock:
            rec = self._traces.get(span.trace_id)
            if rec is None:
                rec = self._traces[span.trace_id] = {
                    "spans": [], "kept": span.sampled, "slow": False,
                    "truncated": 0, "ts": span.start_ts}
            else:
                self._traces.move_to_end(span.trace_id)
                rec["ts"] = max(rec["ts"], span.start_ts)
            if len(rec["spans"]) < max_spans:
                rec["spans"].append(span)
            else:
                rec["truncated"] += 1
            if span.sampled:
                rec["kept"] = True
            if slow:
                rec["kept"] = rec["slow"] = True
            if span.is_root and not rec["kept"]:
                # fast unsampled trace complete: forget it
                del self._traces[span.trace_id]
                dropped = True
            else:
                kept = span.is_root and rec["kept"]
                while len(self._traces) > max_traces:
                    self._traces.popitem(last=False)
        if dropped:
            _stats.TraceRetentionCounter.labels("dropped").inc()
        elif kept:
            _stats.TraceRetentionCounter.labels("kept").inc()

    def index(self, limit: int = 100) -> list[dict]:
        """Most-recent-first summaries of the kept traces."""
        with self._lock:
            recs = [(tid, rec) for tid, rec in self._traces.items()
                    if rec["kept"]]
        out = []
        for tid, rec in reversed(recs[-limit:]):
            spans = rec["spans"]
            root = next((s for s in spans if s.parent_id is None), None)
            start_ts = min((s.start_ts for s in spans), default=0.0)
            end_ts = max((s.start_ts + (s.duration or 0.0) for s in spans),
                         default=start_ts)
            out.append({
                "trace_id": tid,
                "root": (root or spans[0]).name if spans else "",
                "services": sorted({s.service for s in spans if s.service}),
                "spans": len(spans) + rec["truncated"],
                "duration_ms": round((end_ts - start_ts) * 1000.0, 3),
                "start": round(start_ts, 6),
                "slow": rec["slow"],
            })
        return out

    def get(self, trace_id: str) -> Optional[dict]:
        """Full span tree for one trace: spans whose parent is absent
        (remote or still running) surface as roots."""
        with self._lock:
            rec = self._traces.get(trace_id)
            spans = list(rec["spans"]) if rec else None
        if spans is None:
            return None
        nodes = {s.span_id: dict(s.to_dict(), children=[]) for s in spans}
        roots = []
        for s in sorted(spans, key=lambda s: s.start_ts):
            node = nodes[s.span_id]
            parent = nodes.get(s.parent_id) if s.parent_id else None
            (parent["children"] if parent else roots).append(node)
        return {"trace_id": trace_id, "spans": len(spans),
                "truncated": rec["truncated"], "slow": rec["slow"],
                "tree": roots}

    def aggregate(self, prefix: str = "") -> dict:
        """Busy seconds + span counts per span name across every
        recorded trace — the trace-derived stage breakdown."""
        with self._lock:
            spans = [s for rec in self._traces.values()
                     for s in rec["spans"]]
        out: dict[str, dict] = {}
        for s in spans:
            if prefix and not s.name.startswith(prefix):
                continue
            agg = out.setdefault(s.name, {"count": 0, "seconds": 0.0})
            agg["count"] += 1
            agg["seconds"] += s.duration or 0.0
        for agg in out.values():
            agg["seconds"] = round(agg["seconds"], 6)
        return out

    def reset(self):
        with self._lock:
            self._traces.clear()


RECORDER = Recorder()


def traces_handler(req):
    """RpcServer route for GET /debug/traces (index) and
    GET /debug/traces/<id> (full tree).  Register with the bare prefix —
    longest-prefix matching routes both shapes here."""
    from .rpc.http_rpc import RpcError

    rest = req.path[len("/debug/traces"):].strip("/")
    if not rest:
        try:
            limit = int(req.param("limit") or 100)
        except ValueError:
            limit = 100
        return {"traces": RECORDER.index(limit=limit)}
    tree = RECORDER.get(rest)
    if tree is None:
        raise RpcError(f"trace {rest} not found (evicted or dropped)", 404)
    return tree
