"""Minimal Kafka wire-protocol client + in-repo stub broker.

The reference ships a Sarama-based kafka notification queue
(/root/reference/weed/notification/kafka/kafka_queue.go:1-100).  This
image has no kafka client library, so the kafka sink/consumer here
speak a small, self-contained subset of the real Kafka protocol
(api v0: Produce, Fetch, OffsetCommit, OffsetFetch, message format v0)
— enough to prove serialization, topic routing, and ack/offset
durability end to end.  `StubBroker` implements the same subset as an
in-process TCP server with an in-memory log and committed-offset table,
so tests exercise the kafka classes over a REAL socket with REAL wire
bytes, no external infrastructure.  When the kafka-python package is
installed, notification/__init__.py prefers it; this module is the
fallback (and the test surface).

Wire layout (Kafka protocol guide, v0 APIs):
  frame   := int32 size, payload
  request := int16 api_key, int16 api_version, int32 correlation_id,
             string client_id, body
  string  := int16 len, bytes     (len -1 = null)
  bytes   := int32 len, bytes     (len -1 = null)
  message := int64 offset, int32 size, int32 crc32(ieee, of the rest),
             int8 magic=0, int8 attrs=0, bytes key, bytes value
"""

from __future__ import annotations

import socket
import struct
import threading
import zlib
from typing import Optional

API_PRODUCE = 0
API_FETCH = 1
API_OFFSET_COMMIT = 8
API_OFFSET_FETCH = 9


# -- primitive codecs --------------------------------------------------------

def _s16(v: int) -> bytes:
    return struct.pack(">h", v)


def _s32(v: int) -> bytes:
    return struct.pack(">i", v)


def _s64(v: int) -> bytes:
    return struct.pack(">q", v)


def _string(s: Optional[str]) -> bytes:
    if s is None:
        return _s16(-1)
    b = s.encode()
    return _s16(len(b)) + b


def _bytes(b: Optional[bytes]) -> bytes:
    if b is None:
        return _s32(-1)
    return _s32(len(b)) + b


class _Reader:
    def __init__(self, data: bytes):
        self.data = data
        self.pos = 0

    def i8(self) -> int:
        v = self.data[self.pos]
        self.pos += 1
        return v

    def i16(self) -> int:
        v = struct.unpack_from(">h", self.data, self.pos)[0]
        self.pos += 2
        return v

    def i32(self) -> int:
        v = struct.unpack_from(">i", self.data, self.pos)[0]
        self.pos += 4
        return v

    def i64(self) -> int:
        v = struct.unpack_from(">q", self.data, self.pos)[0]
        self.pos += 8
        return v

    def string(self) -> Optional[str]:
        n = self.i16()
        if n < 0:
            return None
        v = self.data[self.pos:self.pos + n].decode()
        self.pos += n
        return v

    def bytes(self) -> Optional[bytes]:
        n = self.i32()
        if n < 0:
            return None
        v = self.data[self.pos:self.pos + n]
        self.pos += n
        return v

    def raw(self, n: int) -> bytes:
        v = self.data[self.pos:self.pos + n]
        self.pos += n
        return v


def encode_message(key: Optional[bytes], value: Optional[bytes],
                   offset: int = 0) -> bytes:
    """One v0 message with its CRC, wrapped with offset+size."""
    body = b"\x00\x00" + _bytes(key) + _bytes(value)  # magic0, attrs0
    msg = struct.pack(">I", zlib.crc32(body) & 0xFFFFFFFF) + body
    return _s64(offset) + _s32(len(msg)) + msg


def decode_message_set(data: bytes) -> list[tuple[int, bytes, bytes]]:
    """[(offset, key, value)] — tolerates a truncated trailing message
    (Kafka fetch semantics: partial messages at the end are normal)."""
    out = []
    r = _Reader(data)
    while r.pos + 12 <= len(data):
        offset = r.i64()
        size = r.i32()
        if r.pos + size > len(data):
            break
        m = _Reader(r.raw(size))
        crc = m.i32() & 0xFFFFFFFF
        body = m.data[4:]
        if zlib.crc32(body) & 0xFFFFFFFF != crc:
            raise ValueError("kafka message CRC mismatch")
        m.i8()  # magic
        m.i8()  # attributes
        key = m.bytes() or b""
        value = m.bytes() or b""
        out.append((offset, key, value))
    return out


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("kafka connection closed")
        buf += chunk
    return buf


def _roundtrip(sock: socket.socket, api: int, corr: int,
               body: bytes, client_id: str = "seaweedfs") -> _Reader:
    req = _s16(api) + _s16(0) + _s32(corr) + _string(client_id) + body
    sock.sendall(_s32(len(req)) + req)
    size = struct.unpack(">i", _recv_exact(sock, 4))[0]
    resp = _Reader(_recv_exact(sock, size))
    got_corr = resp.i32()
    if got_corr != corr:
        raise ValueError(f"correlation id mismatch {got_corr} != {corr}")
    return resp


# -- client ------------------------------------------------------------------

class MinimalKafkaClient:
    """One connection to one broker; partition 0 of one topic (the
    notification sink's usage — kafka_queue.go publishes to a single
    configured topic and lets the broker partition by key; this minimal
    client pins partition 0)."""

    def __init__(self, host: str, port: int, topic: str):
        self.topic = topic
        self._sock = socket.create_connection((host, port), timeout=30)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._corr = 0
        self._lock = threading.Lock()

    def _next_corr(self) -> int:
        self._corr += 1
        return self._corr

    def produce(self, key: bytes, value: bytes) -> int:
        """Send one message (acks=1); returns the assigned offset."""
        msg_set = encode_message(key, value)
        body = (_s16(1) + _s32(10000) +      # required_acks, timeout_ms
                _s32(1) + _string(self.topic) +
                _s32(1) + _s32(0) +          # one partition: 0
                _s32(len(msg_set)) + msg_set)
        with self._lock:
            r = _roundtrip(self._sock, API_PRODUCE, self._next_corr(), body)
        n_topics = r.i32()
        assert n_topics == 1
        r.string()
        n_parts = r.i32()
        assert n_parts == 1
        r.i32()                              # partition
        err = r.i16()
        if err:
            raise IOError(f"kafka produce error {err}")
        return r.i64()

    def fetch(self, offset: int, max_bytes: int = 1 << 20
              ) -> list[tuple[int, bytes, bytes]]:
        """[(offset, key, value)] from `offset` on partition 0."""
        body = (_s32(-1) + _s32(100) + _s32(1) +  # replica, max_wait, min
                _s32(1) + _string(self.topic) +
                _s32(1) + _s32(0) + _s64(offset) + _s32(max_bytes))
        with self._lock:
            r = _roundtrip(self._sock, API_FETCH, self._next_corr(), body)
        n_topics = r.i32()
        assert n_topics == 1
        r.string()
        n_parts = r.i32()
        assert n_parts == 1
        r.i32()                              # partition
        err = r.i16()
        if err:
            raise IOError(f"kafka fetch error {err}")
        r.i64()                              # high watermark
        set_len = r.i32()
        return decode_message_set(r.raw(set_len))

    def commit_offset(self, group: str, offset: int):
        body = (_string(group) + _s32(1) + _string(self.topic) +
                _s32(1) + _s32(0) + _s64(offset) + _string(""))
        with self._lock:
            r = _roundtrip(self._sock, API_OFFSET_COMMIT,
                           self._next_corr(), body)
        r.i32()
        r.string()
        r.i32()
        r.i32()
        err = r.i16()
        if err:
            raise IOError(f"kafka offset commit error {err}")

    def fetch_offset(self, group: str) -> int:
        """Last committed offset for the group (-1 = none)."""
        body = (_string(group) + _s32(1) + _string(self.topic) +
                _s32(1) + _s32(0))
        with self._lock:
            r = _roundtrip(self._sock, API_OFFSET_FETCH,
                           self._next_corr(), body)
        r.i32()
        r.string()
        r.i32()
        r.i32()                              # partition
        off = r.i64()
        r.string()                           # metadata
        err = r.i16()
        if err:
            raise IOError(f"kafka offset fetch error {err}")
        return off

    def close(self):
        try:
            self._sock.close()
        except OSError:
            pass


# -- stub broker -------------------------------------------------------------

class StubBroker:
    """In-process broker speaking the same v0 subset: per-topic
    append-only logs (partition 0) + a committed-offset table per
    consumer group.  Concurrent connections each get a thread."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._logs: dict[str, list[bytes]] = {}   # topic -> raw messages
        self._offsets: dict[tuple[str, str], int] = {}
        self._lock = threading.Lock()
        self._srv = socket.create_server((host, port))
        self.port = self._srv.getsockname()[1]
        self._stop = threading.Event()
        self._accept = threading.Thread(target=self._accept_loop,
                                        daemon=True)
        self._accept.start()

    def _accept_loop(self):
        while not self._stop.is_set():
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    def _serve(self, conn: socket.socket):
        try:
            while not self._stop.is_set():
                size = struct.unpack(">i", _recv_exact(conn, 4))[0]
                req = _Reader(_recv_exact(conn, size))
                api = req.i16()
                req.i16()                      # api_version (v0 only)
                corr = req.i32()
                req.string()                   # client_id
                resp = _s32(corr) + self._handle(api, req)
                conn.sendall(_s32(len(resp)) + resp)
        except (ConnectionError, OSError, struct.error):
            pass
        finally:
            conn.close()

    def _handle(self, api: int, r: _Reader) -> bytes:
        if api == API_PRODUCE:
            r.i16()                            # acks
            r.i32()                            # timeout
            n_topics = r.i32()
            out = _s32(n_topics)
            for _ in range(n_topics):
                topic = r.string() or ""
                n_parts = r.i32()
                out += _string(topic) + _s32(n_parts)
                for _ in range(n_parts):
                    r.i32()                    # partition (0)
                    set_len = r.i32()
                    msgs = decode_message_set(r.raw(set_len))
                    with self._lock:
                        log = self._logs.setdefault(topic, [])
                        base = len(log)
                        for _, key, value in msgs:
                            log.append(encode_message(
                                key, value, offset=len(log)))
                    out += _s32(0) + _s16(0) + _s64(base)
            return out
        if api == API_FETCH:
            r.i32(), r.i32(), r.i32()          # replica, wait, min_bytes
            n_topics = r.i32()
            out = _s32(n_topics)
            for _ in range(n_topics):
                topic = r.string() or ""
                n_parts = r.i32()
                out += _string(topic) + _s32(n_parts)
                for _ in range(n_parts):
                    r.i32()                    # partition
                    offset = r.i64()
                    max_bytes = r.i32()
                    with self._lock:
                        log = list(self._logs.get(topic, []))
                    chunk = b""
                    for raw in log[max(0, offset):]:
                        if len(chunk) + len(raw) > max_bytes and chunk:
                            break
                        chunk += raw
                    out += (_s32(0) + _s16(0) + _s64(len(log)) +
                            _s32(len(chunk)) + chunk)
            return out
        if api == API_OFFSET_COMMIT:
            group = r.string() or ""
            n_topics = r.i32()
            out = _s32(n_topics)
            for _ in range(n_topics):
                topic = r.string() or ""
                n_parts = r.i32()
                out += _string(topic) + _s32(n_parts)
                for _ in range(n_parts):
                    r.i32()                    # partition
                    offset = r.i64()
                    r.string()                 # metadata
                    with self._lock:
                        self._offsets[(group, topic)] = offset
                    out += _s32(0) + _s16(0)
            return out
        if api == API_OFFSET_FETCH:
            group = r.string() or ""
            n_topics = r.i32()
            out = _s32(n_topics)
            for _ in range(n_topics):
                topic = r.string() or ""
                n_parts = r.i32()
                out += _string(topic) + _s32(n_parts)
                for _ in range(n_parts):
                    r.i32()
                    with self._lock:
                        off = self._offsets.get((group, topic), -1)
                    out += _s32(0) + _s64(off) + _string("") + _s16(0)
            return out
        raise ValueError(f"stub broker: unsupported api {api}")

    def message_count(self, topic: str) -> int:
        with self._lock:
            return len(self._logs.get(topic, []))

    def close(self):
        self._stop.set()
        try:
            self._srv.close()
        except OSError:
            pass
