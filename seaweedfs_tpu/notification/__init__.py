"""External metadata-event notification (weed/notification).

The reference publishes every filer EventNotification to an optional
message queue configured in notification.toml (kafka or log
sinks; notification/configuration.go:9-40).  Same surface here: a
NotificationQueue receives (key, event-dict) pairs from the filer's
change log; implementations are a glog sink, a JSON-lines file sink, and
a kafka sink gated on the client library being installed (it is not
baked into this image).
"""

from __future__ import annotations

import json
import threading
from typing import Optional

from ..util import glog


class NotificationQueue:
    name = "none"

    def send(self, key: str, event: dict):
        raise NotImplementedError

    def close(self):
        pass


class LogQueue(NotificationQueue):
    """notification.log sink: events to the process log."""

    name = "log"

    def send(self, key: str, event: dict):
        glog.v(1).infof("notify %s: %s", key, json.dumps(event))


class FileQueue(NotificationQueue):
    """JSON-lines events appended to a file (useful stand-in for an
    external queue in air-gapped deployments)."""

    name = "file"

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()

    def send(self, key: str, event: dict):
        line = json.dumps({"key": key, **event})
        with self._lock, open(self.path, "a") as f:
            f.write(line + "\n")


class KafkaQueue(NotificationQueue):
    """notification.kafka sink; requires a kafka client library."""

    name = "kafka"

    def __init__(self, hosts: list[str], topic: str):
        try:
            from kafka import KafkaProducer  # type: ignore
        except ImportError as e:
            raise RuntimeError(
                "kafka notification sink needs the kafka-python package, "
                "which is not installed in this environment") from e
        self.topic = topic
        self.producer = KafkaProducer(bootstrap_servers=hosts)

    def send(self, key: str, event: dict):
        self.producer.send(self.topic, key=key.encode(),
                           value=json.dumps(event).encode())

    def close(self):
        self.producer.close()


def load_notification_queue(conf) -> Optional[NotificationQueue]:
    """Build the configured sink from notification.toml
    (configuration.go LoadConfiguration)."""
    if conf is None:
        return None
    if conf.get_bool("notification.log.enabled"):
        return LogQueue()
    if conf.get_bool("notification.file.enabled"):
        return FileQueue(str(conf.get("notification.file.path",
                                      "filer_events.jsonl")))
    if conf.get_bool("notification.kafka.enabled"):
        hosts = str(conf.get("notification.kafka.hosts",
                             "localhost:9092")).split(",")
        topic = str(conf.get("notification.kafka.topic", "seaweedfs"))
        return KafkaQueue(hosts, topic)
    return None
