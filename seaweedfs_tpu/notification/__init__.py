"""External metadata-event notification (weed/notification).

The reference publishes every filer EventNotification to an optional
message queue configured in notification.toml (kafka or log
sinks; notification/configuration.go:9-40).  Same surface here: a
NotificationQueue receives (key, event-dict) pairs from the filer's
change log; implementations are a glog sink, a JSON-lines file sink, and
a kafka sink gated on the client library being installed (it is not
baked into this image).
"""

from __future__ import annotations

import json
import threading
from typing import Optional

from ..util import glog


class NotificationQueue:
    name = "none"

    def send(self, key: str, event: dict):
        raise NotImplementedError

    def close(self):
        pass


class LogQueue(NotificationQueue):
    """notification.log sink: events to the process log."""

    name = "log"

    def send(self, key: str, event: dict):
        glog.v(1).infof("notify %s: %s", key, json.dumps(event))


class FileQueue(NotificationQueue):
    """JSON-lines events appended to a file (useful stand-in for an
    external queue in air-gapped deployments)."""

    name = "file"

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()

    def send(self, key: str, event: dict):
        line = json.dumps({"key": key, **event})
        with self._lock, open(self.path, "a") as f:
            f.write(line + "\n")


class KafkaQueue(NotificationQueue):
    """notification.kafka sink (kafka_queue.go:1-100).  Prefers the
    kafka-python package; without it, falls back to the in-repo minimal
    v0-protocol client (notification/kafka_wire.py) — single broker,
    partition 0 — so the kafka path works and is testable in
    environments with no kafka client library installed."""

    name = "kafka"

    def __init__(self, hosts: list[str], topic: str):
        self.topic = topic
        self.producer = None
        self._minimal = None
        try:
            from kafka import KafkaProducer  # type: ignore

            self.producer = KafkaProducer(bootstrap_servers=hosts)
        except ImportError:
            from .kafka_wire import MinimalKafkaClient

            host, _, port = hosts[0].partition(":")
            self._minimal = MinimalKafkaClient(
                host, int(port or 9092), topic)

    def send(self, key: str, event: dict):
        value = json.dumps(event).encode()
        if self.producer is not None:
            self.producer.send(self.topic, key=key.encode(), value=value)
        else:
            self._minimal.produce(key.encode(), value)

    def close(self):
        if self.producer is not None:
            self.producer.close()
        if self._minimal is not None:
            self._minimal.close()


def load_notification_queue(conf) -> Optional[NotificationQueue]:
    """Build the configured sink from notification.toml
    (configuration.go LoadConfiguration)."""
    if conf is None:
        return None
    if conf.get_bool("notification.log.enabled"):
        return LogQueue()
    if conf.get_bool("notification.file.enabled"):
        return FileQueue(str(conf.get("notification.file.path",
                                      "filer_events.jsonl")))
    if conf.get_bool("notification.kafka.enabled"):
        hosts = str(conf.get("notification.kafka.hosts",
                             "localhost:9092")).split(",")
        topic = str(conf.get("notification.kafka.topic", "seaweedfs"))
        return KafkaQueue(hosts, topic)
    return None


# -- notification INPUTS (weed/replication/sub): the consumer half ----------
# `weed filer.replicate` reads events back OUT of the queue and applies
# them through replication/replicator.py — the MQ-driven replication mode
# (command/filer_replication.go:24-100), vs filer.sync's direct
# subscribe-driven mode.


class NotificationInput:
    """Consumer interface (sub.NotificationInput): receive_message
    returns (key, event) or None when the queue is drained; ack()
    persists consumption so restarts resume where they left off."""

    name = "none"

    def receive_message(self) -> Optional[tuple[str, dict]]:
        raise NotImplementedError

    def ack(self):
        pass

    def close(self):
        pass


class FileQueueInput(NotificationInput):
    """Tail the FileQueue's JSON-lines file with a durable byte offset —
    the consumer half of the air-gapped queue stand-in."""

    name = "file"

    def __init__(self, path: str, offset_path: Optional[str] = None):
        self.path = path
        self.offset_path = offset_path or path + ".offset"
        self._offset = 0
        try:
            with open(self.offset_path) as f:
                self._offset = int(f.read().strip() or 0)
        except (FileNotFoundError, ValueError):
            pass
        self._pending: Optional[int] = None  # offset after unacked msg

    def receive_message(self) -> Optional[tuple[str, dict]]:
        try:
            with open(self.path, "rb") as f:
                f.seek(self._offset)
                line = f.readline()
        except FileNotFoundError:
            return None
        if not line or not line.endswith(b"\n"):
            return None  # nothing new / torn tail write — retry later
        self._pending = self._offset + len(line)
        record = json.loads(line)
        key = record.pop("key", "")
        return key, record

    def ack(self):
        if self._pending is None:
            return
        self._offset = self._pending
        self._pending = None
        with open(self.offset_path, "w") as f:
            f.write(str(self._offset))


class KafkaQueueInput(NotificationInput):
    """Kafka consumer input.  Prefers kafka-python; falls back to the
    in-repo minimal v0-protocol client with the same manual-commit
    semantics (ack() persists the consumed offset to the broker's
    group-offset table; a restarted consumer resumes after the last
    acked message, replaying unacked ones)."""

    name = "kafka"

    def __init__(self, hosts: list[str], topic: str,
                 group: str = "seaweedfs-replicate"):
        self.group = group
        self.consumer = None
        self._minimal = None
        try:
            from kafka import KafkaConsumer  # type: ignore

            self.consumer = KafkaConsumer(topic, bootstrap_servers=hosts,
                                          group_id=group,
                                          enable_auto_commit=False)
        except ImportError:
            from .kafka_wire import MinimalKafkaClient

            host, _, port = hosts[0].partition(":")
            self._minimal = MinimalKafkaClient(
                host, int(port or 9092), topic)
            committed = self._minimal.fetch_offset(group)
            self._next = committed if committed >= 0 else 0
            self._pending: Optional[int] = None

    def receive_message(self) -> Optional[tuple[str, dict]]:
        if self.consumer is not None:
            batch = self.consumer.poll(timeout_ms=1000, max_records=1)
            for records in batch.values():
                for r in records:
                    return (r.key or b"").decode(), json.loads(r.value)
            return None
        msgs = self._minimal.fetch(self._next)
        if not msgs:
            return None
        offset, key, value = msgs[0]
        self._pending = offset + 1
        self._next = offset + 1
        return key.decode(), json.loads(value)

    def ack(self):
        if self.consumer is not None:
            self.consumer.commit()
        elif self._pending is not None:
            self._minimal.commit_offset(self.group, self._pending)
            self._pending = None

    def close(self):
        if self.consumer is not None:
            self.consumer.close()
        if self._minimal is not None:
            self._minimal.close()


def load_notification_input(conf) -> Optional[NotificationInput]:
    """Consumer counterpart of load_notification_queue."""
    if conf is None:
        return None
    if conf.get_bool("notification.file.enabled"):
        path = str(conf.get("notification.file.path",
                            "filer_events.jsonl"))
        return FileQueueInput(path)
    if conf.get_bool("notification.kafka.enabled"):
        hosts = str(conf.get("notification.kafka.hosts",
                             "localhost:9092")).split(",")
        topic = str(conf.get("notification.kafka.topic", "seaweedfs"))
        return KafkaQueueInput(hosts, topic)
    return None
