"""Unified outbound RPC policy: retries, backoff, budgets, breakers,
hedging.

Replaces the ad-hoc failover loops that grew in MasterClient, the
volume server's master loop and the filer fan-outs with one shared
layer:

  * per-route idempotency classification — only idempotent requests
    retry after the send phase (a non-idempotent RPC may already be
    executing on the far side);
  * exponential backoff with FULL jitter (delay = U(0, min(cap,
    base * 2^attempt))) — synchronized retry waves are worse than the
    original failure;
  * a global retry-budget token bucket: every initial request deposits
    a fraction of a token, every retry withdraws one, so retries are
    capped at ~WEED_RPC_RETRY_BUDGET of live traffic and a brown-out
    cannot snowball into a retry storm;
  * per-destination circuit breakers with half-open probing
    (generalizing s3api/circuit_breaker.py's admission idea from
    per-bucket concurrency to per-peer failure state);
  * deadline propagation: deadline_scope() pins an absolute wall-clock
    deadline that call() forwards in X-Deadline and servers enforce, so
    work the client has already given up on is rejected, not executed;
  * hedged requests for idempotent reads: a second copy fired after an
    adaptive p95 delay, first success wins.

Knobs (env, read per call so tests flip them live):
  WEED_RPC_RETRIES        extra attempts for idempotent calls (def 2)
  WEED_RPC_BACKOFF_MS     backoff base (def 25)
  WEED_RPC_BACKOFF_CAP_MS backoff ceiling (def 2000)
  WEED_RPC_RETRY_BUDGET   retry/request token ratio (def 0.2)
  WEED_BREAKER_FAILURES   consecutive failures to open (def 5)
  WEED_BREAKER_OPEN_SECS  open-state cooldown before a probe (def 5)
  WEED_RPC_HEDGE_MS       hedge delay floor / cold default (def 25)
"""

from __future__ import annotations

import os
import queue
import random
import threading
import time
from typing import Callable, List, Optional, Sequence, Tuple

from ..stats import metrics as _stats
from ..qos import classify as _qos
from .http_rpc import (RpcError, call, current_deadline, deadline_scope,
                       set_deadline)

__all__ = [
    "is_idempotent", "retryable", "backoff_delay", "RetryBudget",
    "Breaker", "BREAKERS", "call_policy", "failover_call",
    "HedgeTracker", "HEDGE", "hedged", "deadline_scope",
]

# test seams: monkeypatch for fake-clock tests (no real sleeps)
sleep = time.sleep
now = time.monotonic


def _env_float(name: str, default: float) -> float:
    v = os.environ.get(name, "")
    return float(v) if v else default


def _env_int(name: str, default: int) -> int:
    v = os.environ.get(name, "")
    return int(v) if v else default


# -- idempotency classification ----------------------------------------------

# POST routes that are safe to re-send: pure lookups, status probes, and
# replication writes (needle replays dedup via the unchanged-content
# check in write_needle)
_IDEMPOTENT_POST_PREFIXES = (
    "/dir/lookup", "/dir/status", "/vol/status", "/cluster/status",
    "/stats", "/admin/ec/shard_locations",
)


def is_idempotent(method: str, path: str) -> bool:
    if method in ("GET", "HEAD"):
        return True
    if "type=replicate" in path:
        return True
    return any(path.startswith(p) for p in _IDEMPOTENT_POST_PREFIXES)


def retryable(err: Exception) -> bool:
    """Transport failures and overload/unavailable statuses retry;
    permanent 4xxs never do (satellite: RpcError now carries enough to
    tell them apart)."""
    if not isinstance(err, RpcError):
        return False
    if getattr(err, "transport", False):
        return True
    return err.status in (429, 502, 503)


def _dest_failure(err: RpcError) -> bool:
    """Does this error indict the DESTINATION (breaker-relevant)?  A 4xx
    is the caller's problem; the peer answered fine."""
    return getattr(err, "transport", False) or err.status >= 500


def _route_label(path: str) -> str:
    """Bounded-cardinality route label: the path sans query, collapsed
    to '/<fid>' for default-route object paths (digits/commas)."""
    p = path.split("?", 1)[0]
    seg = p.split("/", 2)[1] if "/" in p else p
    if seg and seg[0].isdigit():
        return "/<fid>"
    return "/" + "/".join(p.split("/")[1:3]) if p != "/" else "/"


def backoff_delay(attempt: int, base: Optional[float] = None,
                  cap: Optional[float] = None,
                  rand: Callable[[], float] = random.random) -> float:
    """Full-jitter exponential backoff (seconds) for retry `attempt`
    (1-based)."""
    if base is None:
        base = _env_float("WEED_RPC_BACKOFF_MS", 25.0) / 1000.0
    if cap is None:
        cap = _env_float("WEED_RPC_BACKOFF_CAP_MS", 2000.0) / 1000.0
    return rand() * min(cap, base * (2 ** (attempt - 1)))


class RetryBudget:
    """Token bucket bounding retries to a fraction of live traffic.
    Every initial request deposits `ratio` tokens (clamped to `cap`);
    every retry spends one.  When the bucket is dry the retry is simply
    not attempted — the original error propagates."""

    def __init__(self, ratio: Optional[float] = None, cap: float = 64.0):
        self._lock = threading.Lock()
        self._tokens = cap  # start full: cold-start retries allowed
        self.cap = cap
        self._ratio = ratio

    @property
    def ratio(self) -> float:
        if self._ratio is not None:
            return self._ratio
        return _env_float("WEED_RPC_RETRY_BUDGET", 0.2)

    def on_request(self):
        with self._lock:
            self._tokens = min(self.cap, self._tokens + self.ratio)

    def try_spend(self) -> bool:
        with self._lock:
            if self._tokens >= 1.0:
                self._tokens -= 1.0
                return True
            return False

    @property
    def tokens(self) -> float:
        with self._lock:
            return self._tokens


BUDGET = RetryBudget()


# -- per-destination circuit breakers ----------------------------------------

CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"
_STATE_VALUE = {CLOSED: 0.0, OPEN: 1.0, HALF_OPEN: 2.0}


class Breaker:
    """Per-destination failure breaker with half-open probing.  Opens
    after N consecutive destination failures; while open, allow() fails
    fast (no socket).  After the cooldown ONE caller is admitted as a
    probe (half-open); its success closes the breaker, its failure
    re-opens the cooldown."""

    def __init__(self, dst: str, failures: Optional[int] = None,
                 open_secs: Optional[float] = None):
        self.dst = dst
        self._lock = threading.Lock()
        self._failures = 0
        self._state = CLOSED
        self._opened_at = 0.0
        self._probing = False
        self._threshold = failures
        self._open_secs = open_secs

    @property
    def threshold(self) -> int:
        return self._threshold if self._threshold is not None else \
            _env_int("WEED_BREAKER_FAILURES", 5)

    @property
    def open_secs(self) -> float:
        return self._open_secs if self._open_secs is not None else \
            _env_float("WEED_BREAKER_OPEN_SECS", 5.0)

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def _set_state(self, state: str):
        self._state = state
        _stats.BreakerStateGauge.labels(self.dst).set(_STATE_VALUE[state])

    def allow(self) -> bool:
        with self._lock:
            if self._state == CLOSED:
                return True
            if self._state == OPEN:
                if now() - self._opened_at >= self.open_secs:
                    self._set_state(HALF_OPEN)
                    self._probing = True
                    return True  # this caller is the probe
                return False
            # HALF_OPEN: one probe at a time
            if self._probing:
                return False
            self._probing = True
            return True

    def on_success(self):
        with self._lock:
            self._failures = 0
            self._probing = False
            if self._state != CLOSED:
                self._set_state(CLOSED)

    def on_failure(self):
        with self._lock:
            self._failures += 1
            self._probing = False
            if self._state == HALF_OPEN or \
                    (self._state == CLOSED and
                     self._failures >= self.threshold):
                self._set_state(OPEN)
                self._opened_at = now()


class _BreakerBoard:
    def __init__(self):
        self._lock = threading.Lock()
        self._breakers: dict[str, Breaker] = {}

    def get(self, dst: str) -> Breaker:
        with self._lock:
            br = self._breakers.get(dst)
            if br is None:
                br = self._breakers[dst] = Breaker(dst)
            return br

    def reset(self):
        with self._lock:
            self._breakers.clear()


BREAKERS = _BreakerBoard()


# -- the unified call wrapper ------------------------------------------------

def call_policy(addr: str, path: str, payload: Optional[dict] = None,
                method: Optional[str] = None, timeout: float = 30.0,
                raw: Optional[bytes] = None,
                headers: Optional[dict] = None, parse: bool = True, *,
                idempotent: Optional[bool] = None,
                retries: Optional[int] = None,
                breaker: bool = True,
                budget: Optional[RetryBudget] = None):
    """call() with the full outbound policy applied: breaker admission,
    classified retries with full-jitter backoff, retry budget, and
    deadline awareness (never sleeps past the propagated deadline)."""
    if method is None:
        method = "POST" if (raw is not None or payload is not None) \
            else "GET"
    if idempotent is None:
        idempotent = is_idempotent(method, path)
    if retries is None:
        retries = _env_int("WEED_RPC_RETRIES", 2) if idempotent else 0
    budget = budget or BUDGET
    br = BREAKERS.get(addr) if breaker else None
    label = _route_label(path)
    last: Optional[RpcError] = None
    for attempt in range(retries + 1):
        if attempt:
            if not retryable(last):
                break
            dl = current_deadline()
            if dl is not None and dl - time.time() <= 0:
                _stats.RpcRetryCounter.labels(label, "deadline").inc()
                break
            if not budget.try_spend():
                _stats.RpcRetryCounter.labels(label, "budget_dry").inc()
                break
            delay = backoff_delay(attempt)
            if dl is not None:
                delay = min(delay, max(0.0, dl - time.time()))
            if delay > 0:
                sleep(delay)
            _stats.RpcRetryCounter.labels(label, "retry").inc()
        if br is not None and not br.allow():
            last = RpcError(f"circuit open to {addr}", 503, addr=addr,
                            route=path, transport=True)
            break  # the same destination stays open for open_secs
        budget.on_request()
        try:
            result = call(addr, path, payload=payload, method=method,
                          timeout=timeout, raw=raw, headers=headers,
                          parse=parse)
        except RpcError as e:
            last = e
            if br is not None:
                if _dest_failure(e):
                    br.on_failure()
                else:
                    br.on_success()
            continue
        if br is not None:
            br.on_success()
        return result
    raise last


def failover_call(addrs: Sequence[str], path: str,
                  payload: Optional[dict] = None,
                  method: Optional[str] = None, timeout: float = 30.0,
                  rounds: int = 2, headers: Optional[dict] = None,
                  parse: bool = True) -> Tuple[object, str]:
    """Ordered failover through `addrs` (first = preferred): try each
    once per round, skipping destinations whose breaker is open (unless
    every breaker is open — then all are tried, someone must probe).
    Full-jitter backoff between rounds only, so a healthy secondary is
    reached with zero added latency.  Returns (result, winning addr)."""
    last: Optional[RpcError] = None
    for rnd in range(rounds):
        if rnd:
            dl = current_deadline()
            delay = backoff_delay(rnd)
            if dl is not None:
                delay = min(delay, max(0.0, dl - time.time()))
            if delay > 0:
                sleep(delay)
        candidates = [a for a in addrs
                      if BREAKERS.get(a).state != OPEN] or list(addrs)
        for addr in candidates:
            try:
                return call_policy(
                    addr, path, payload=payload, method=method,
                    timeout=timeout, headers=headers, parse=parse,
                    retries=0), addr
            except RpcError as e:
                last = e
                if not retryable(e):
                    raise
    raise last


# -- hedged requests ---------------------------------------------------------

class HedgeTracker:
    """Adaptive per-route hedge delay: p95 of a small ring of recent
    latencies, floored at WEED_RPC_HEDGE_MS (also the cold default)."""

    def __init__(self, size: int = 64):
        self._lock = threading.Lock()
        self._rings: dict[str, List[float]] = {}
        self._pos: dict[str, int] = {}
        self.size = size

    def observe(self, key: str, seconds: float):
        with self._lock:
            ring = self._rings.setdefault(key, [])
            if len(ring) < self.size:
                ring.append(seconds)
            else:
                pos = self._pos.get(key, 0)
                ring[pos] = seconds
                self._pos[key] = (pos + 1) % self.size
            self._pos.setdefault(key, 0)

    def delay(self, key: str) -> float:
        floor = _env_float("WEED_RPC_HEDGE_MS", 25.0) / 1000.0
        with self._lock:
            ring = self._rings.get(key)
            if not ring:
                return floor
            s = sorted(ring)
            p95 = s[min(len(s) - 1, int(len(s) * 0.95))]
        return max(floor, p95)


HEDGE = HedgeTracker()


def reset_state():
    """Drop all process-global policy state: circuit breakers, the
    retry-budget bucket, and the hedge latency rings.  For bench/test
    phase isolation — breakers and budgets are keyed by address, and a
    later phase reusing an ephemeral port (or sharing the process) must
    not inherit an earlier phase's failures."""
    BREAKERS.reset()
    with BUDGET._lock:
        BUDGET._tokens = BUDGET.cap
    with HEDGE._lock:
        HEDGE._rings.clear()
        HEDGE._pos.clear()


def hedged(key: str, attempts: Sequence[Callable[[], object]]):
    """Run attempts[0]; if it hasn't answered after the adaptive p95
    delay (or fails), fire the next attempt.  First success wins, losers
    are abandoned (their sockets drain in their own threads).  Only for
    idempotent reads.  Raises the last error if all attempts fail."""
    if not attempts:
        raise ValueError("hedged: no attempts for %s" % key)
    if len(attempts) == 1:
        return attempts[0]()
    results: "queue.Queue[tuple]" = queue.Queue()
    label = _route_label(key)
    # racer threads have fresh locals: carry the caller's deadline and
    # QoS context over, same rule as the server dispatch loop
    dl = current_deadline()
    qcls, qtenant = _qos.current_class(), _qos.current_tenant()

    def run(i: int, fn: Callable[[], object]):
        set_deadline(dl)
        _qos.set_qos(qcls, qtenant)
        t0 = now()
        try:
            results.put((True, fn(), i, now() - t0))
        except Exception as e:
            results.put((False, e, i, now() - t0))

    delay = HEDGE.delay(key)
    launched = 1
    threading.Thread(target=run, args=(0, attempts[0]),
                     daemon=True).start()
    pending, last_err = 1, None
    while pending:
        try:
            timeout = delay if launched < len(attempts) else None
            ok, value, i, took = results.get(timeout=timeout)
        except queue.Empty:
            # primary is slow: fire the hedge
            threading.Thread(target=run,
                             args=(launched, attempts[launched]),
                             daemon=True).start()
            _stats.RpcHedgeCounter.labels(label, "fired").inc()
            launched += 1
            pending += 1
            continue
        pending -= 1
        if ok:
            HEDGE.observe(key, took)
            if i > 0:
                _stats.RpcHedgeCounter.labels(label, "win").inc()
            return value
        last_err = value
        if launched < len(attempts):  # fail fast: next attempt now
            threading.Thread(target=run,
                             args=(launched, attempts[launched]),
                             daemon=True).start()
            _stats.RpcHedgeCounter.labels(label, "fired").inc()
            launched += 1
            pending += 1
    raise last_err
