"""Multi-process gateway front end (SO_REUSEPORT worker sharding).

One Python interpreter is the throughput ceiling of every HTTP gateway
in the system: the GIL serializes request handling no matter how many
threads `ThreadingHTTPServer` spawns.  `WEED_HTTP_WORKERS=N` preforks
the serving tier the way nginx/haproxy do:

  * the parent process IS worker 0 — it keeps serving on the listener
    it already bound, so there is never a window where the port is
    bound but nobody accepts;
  * N-1 forked children each bind a fresh ``SO_REUSEPORT`` socket on
    the same (host, port), so the kernel load-balances accepts across
    the fleet.  Where SO_REUSEPORT is missing (old kernels, some BSDs)
    children fall back to accepting on the listening fd inherited over
    ``fork`` — the classic shared-accept prefork model;
  * a supervisor thread in the parent reaps crashed workers with
    per-pid ``waitpid(WNOHANG)`` (never ``waitpid(-1)``, which would
    steal exit statuses from unrelated subprocess children such as
    ``scale.up`` spawns) and respawns them;
  * every process additionally binds a loopback *sideband* listener
    sharing the same routes, registered in a small on-disk registry, so
    /metrics, /debug/qos and /debug/traces can be scrape-merged across
    the worker set and graceful drain (/admin/drain, /admin/leave) can
    fan out from whichever worker received it.

Consistency model: workers forward every non-GET/HEAD request to the
parent over the sideband (single-writer), and retry locally-404ing
GET/HEAD reads against the parent — a forked child's view of volume
indexes / filer stores is a snapshot, so reads of data written after
the fork miss locally and are served by the writer.  Volume workers
additionally tail the flushed .idx (see storage/needle_map.py) so the
hot read path stays local.

Prefork only engages for explicitly-bound ports.  Ephemeral port-0
servers (test fixtures, the embedded s3 filer, metrics sidecars) stay
single-process — which also guarantees the pytest/bench process, which
has JAX and a thread pool loaded, is never forked.
"""

from __future__ import annotations

import json
import os
import random
import shutil
import signal
import socket
import tempfile
import threading
import time
from typing import Optional

from ..stats import metrics as _stats

# Marks a request that already crossed a prefork hop (worker->parent
# forward, parent->worker fanout, or an aggregation scrape).  Any
# request carrying it is served strictly locally: never re-forwarded,
# never fanned out, never re-aggregated.
FWD_HEADER = "X-Weed-Prefork-Fwd"

_ROLE = "solo"  # "solo" | "parent" | "worker"
_WORKER_ID = 0


def worker_count() -> int:
    """The configured WEED_HTTP_WORKERS (>=1; bad values mean 1)."""
    raw = os.environ.get("WEED_HTTP_WORKERS", "")
    try:
        return max(1, int(raw)) if raw else 1
    except ValueError:
        return 1


def reuseport_available() -> bool:
    return hasattr(socket, "SO_REUSEPORT")


def fork_available() -> bool:
    return hasattr(os, "fork")


def role() -> str:
    return _ROLE


def worker_id() -> int:
    return _WORKER_ID


def is_worker() -> bool:
    return _ROLE == "worker"


def _set_role(role_: str, wid: int):
    global _ROLE, _WORKER_ID
    _ROLE = role_
    _WORKER_ID = wid


class PreforkGroup:
    """Supervisor owned by the parent RpcServer; forked children reuse
    the same object (inherited state) for addresses and registry."""

    def __init__(self, server, workers: int):
        self.server = server
        self.workers = workers
        self.dir = ""               # worker registry (w<id>.json files)
        self.control_addr = ""      # parent sideband workers forward to
        self._pids: dict[int, int] = {}  # wid -> pid (parent only)
        # wid -> monotonic deadline by which a freshly-forked child must
        # have written its registry entry (fork-deadlock watchdog)
        self._spawn_deadlines: dict[int, float] = {}
        try:
            self._spawn_grace = float(os.environ.get(
                "WEED_PREFORK_SPAWN_DEADLINE", "") or 15.0)
        except ValueError:
            self._spawn_grace = 15.0
        self._stopping = False
        self._reaper: Optional[threading.Thread] = None
        self._control = None        # parent sideband httpd
        self._control_thread = None
        self._child_httpd = None    # worker main listener (child only)
        self._child_sideband = None
        self.qos_shm = None

    # -- parent ---------------------------------------------------------

    def start(self):
        base = os.environ.get("WEED_PREFORK_DIR", "")
        if base:
            os.makedirs(base, exist_ok=True)
            self.dir = tempfile.mkdtemp(
                prefix=f"{self.server.service_name}-", dir=base)
        else:
            self.dir = tempfile.mkdtemp(
                prefix=f"weed-prefork-{self.server.service_name}-")
        self._init_qos_shm()
        # the control sideband exists BEFORE any fork so every child is
        # born knowing where writes go
        self._control = self.server._new_listener("127.0.0.1", 0)
        self.control_addr = f"127.0.0.1:{self._control.server_address[1]}"
        self._control_thread = threading.Thread(
            target=self._control.serve_forever, kwargs={"poll_interval": 0.5},
            daemon=True, name=f"{self.server.service_name}-prefork-control")
        self._control_thread.start()
        _set_role("parent", 0)
        self._install_aggregators()
        from .http_rpc import _POOL
        _POOL.configure_for_prefork(self.workers)
        self._write_entry(0, os.getpid(), self.control_addr)
        _stats.GatewayWorkersGauge.labels(self.server.service_name).set(
            float(self.workers))
        for wid in range(1, self.workers):
            self._fork(wid)
        self._reaper = threading.Thread(
            target=self._reap_loop, daemon=True,
            name=f"{self.server.service_name}-prefork-reaper")
        self._reaper.start()

    def _init_qos_shm(self):
        if os.environ.get("WEED_QOS_SHM", "auto") == "0":
            return
        try:
            from ..qos import shm as qshm
            self.qos_shm = qshm.create(self.workers)
        except Exception:
            self.qos_shm = None  # degrade to per-process QoS
        if self.qos_shm is not None:
            self._write_json("qos_shm.json", {"name": self.qos_shm.name})

    def _write_json(self, name: str, payload: dict):
        path = os.path.join(self.dir, name)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(payload, f)
        os.replace(tmp, path)

    def _write_entry(self, wid: int, pid: int, sideband: str):
        self._write_json(f"w{wid}.json",
                         {"wid": wid, "pid": pid, "sideband": sideband})

    def peers(self) -> list[dict]:
        """Every registered worker (including self), sorted by wid."""
        out = []
        try:
            names = os.listdir(self.dir)
        except OSError:
            return out
        for name in names:
            if not (name.startswith("w") and name.endswith(".json")):
                continue
            try:
                with open(os.path.join(self.dir, name)) as f:
                    out.append(json.load(f))
            except (OSError, ValueError):
                continue  # worker mid-respawn; its entry reappears
        out.sort(key=lambda e: e.get("wid", 0))
        return out

    def _fork(self, wid: int):
        pid = os.fork()
        if pid == 0:
            try:
                self._child_main(wid)
            finally:
                os._exit(0)
        self._pids[wid] = pid
        self._spawn_deadlines[wid] = time.monotonic() + self._spawn_grace

    def _entry(self, wid: int) -> Optional[dict]:
        try:
            with open(os.path.join(self.dir, f"w{wid}.json")) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def _kill_unregistered(self):
        """Fork-deadlock watchdog.  Children fork from a live,
        actively-serving multithreaded parent; post-fork code is
        written to never ACQUIRE inherited locks (structures are
        replaced instead), but on_worker_start hooks and library
        internals are beyond that guarantee.  A child that wedges
        before writing its registry entry is alive to waitpid yet
        serves nothing — silently shrunk capacity.  Kill it past the
        spawn deadline; the reap sweep then respawns it."""
        now = time.monotonic()
        for wid, deadline in list(self._spawn_deadlines.items()):
            pid = self._pids.get(wid)
            if pid is None:
                self._spawn_deadlines.pop(wid, None)
                continue
            ent = self._entry(wid)
            if ent is not None and ent.get("pid") == pid:
                self._spawn_deadlines.pop(wid, None)
            elif now >= deadline:
                self._spawn_deadlines.pop(wid, None)
                try:
                    os.kill(pid, signal.SIGKILL)
                except OSError:
                    pass

    def _reap_loop(self):
        service = self.server.service_name
        while not self._stopping:
            for wid, pid in list(self._pids.items()):
                try:
                    done, _status = os.waitpid(pid, os.WNOHANG)
                except ChildProcessError:
                    done = pid
                if done == 0 or self._stopping:
                    continue
                _stats.GatewayWorkerRespawnsCounter.labels(service).inc()
                from ..stats import events as _events

                _events.emit(_events.WORKER_RESPAWN, service=service,
                             node=self.server.address,
                             detail={"worker": wid, "pid": pid})
                try:
                    self._fork(wid)
                except OSError:
                    self._pids.pop(wid, None)  # retried next sweep? no:
                    # fork failure here means the host is in trouble;
                    # keep serving with the surviving fleet
            self._kill_unregistered()
            time.sleep(0.2)

    def stop(self, timeout: float = 5.0):
        self._stopping = True
        for pid in self._pids.values():
            try:
                os.kill(pid, signal.SIGTERM)
            except (OSError, ProcessLookupError):
                pass
        deadline = time.monotonic() + timeout
        for wid, pid in list(self._pids.items()):
            while time.monotonic() < deadline:
                try:
                    done, _ = os.waitpid(pid, os.WNOHANG)
                except ChildProcessError:
                    done = pid
                if done:
                    break
                time.sleep(0.05)
            else:
                try:
                    os.kill(pid, signal.SIGKILL)
                    os.waitpid(pid, 0)
                except (OSError, ChildProcessError):
                    pass
        self._pids.clear()
        self._spawn_deadlines.clear()
        if self._control is not None:
            try:
                self._control.shutdown()
                self._control.server_close()
            except OSError:
                pass
        if self.qos_shm is not None:
            try:
                from ..qos import shm as qshm
                qshm.destroy()
            except Exception:
                pass
            self.qos_shm = None
        shutil.rmtree(self.dir, ignore_errors=True)
        _set_role("solo", 0)

    # -- child ----------------------------------------------------------

    def _child_main(self, wid: int):
        server = self.server
        _set_role("worker", wid)
        signal.signal(signal.SIGINT, signal.SIG_IGN)
        signal.signal(signal.SIGTERM, self._child_term)
        random.seed(os.urandom(16))
        from . import http_rpc
        # The parent keeps serving while it forks, so ANY inherited lock
        # may have been captured mid-hold — post-fork code must never
        # acquire one.  Shared structures are REPLACED, not locked:
        # inherited pooled client sockets are shared with the parent
        # (reusing one would interleave two processes on one TCP stream)
        http_rpc._POOL.reinit_after_fork()
        http_rpc._POOL.configure_for_prefork(self.workers)
        # Inherited accepted connections belong to the parent's threads
        # (which do not exist post-fork).  Swap in a fresh lock + set,
        # then close() the old ones — close only drops this process's
        # reference; never shutdown(), the fds are shared.
        conns = getattr(server.httpd, "_conns", None)
        if conns is not None:
            server.httpd._conns_lock = threading.Lock()
            server.httpd._conns = set()
            for c in conns:
                try:
                    c.close()
                except OSError:
                    pass
        if self.qos_shm is not None:
            from ..qos import shm as qshm
            qshm.set_worker_id(wid)
            self.qos_shm.reinit_after_fork()
            # service-scoped: in a combined daemon another service's
            # worker shares this wid, and its live counters must survive
            self.qos_shm.reset_worker(wid, server.service_name)
        httpd = None
        if reuseport_available():
            try:
                httpd = server._new_listener(server.host, server.port,
                                             reuseport=True)
            except OSError:
                httpd = None
        if httpd is None:
            # fd-sharing fallback: accept on the inherited listener
            httpd = server.httpd
        else:
            try:
                server.httpd.socket.close()
            except OSError:
                pass
        self._child_httpd = httpd
        server.on_worker_start_fire(wid)
        sideband = server._new_listener("127.0.0.1", 0)
        self._child_sideband = sideband
        threading.Thread(target=sideband.serve_forever,
                         kwargs={"poll_interval": 0.5}, daemon=True,
                         name=f"{server.service_name}-w{wid}-sideband"
                         ).start()
        self._write_entry(wid, os.getpid(),
                          f"127.0.0.1:{sideband.server_address[1]}")
        httpd.serve_forever(poll_interval=0.2)

    def _child_term(self, _signum, _frame):
        # shutdown() deadlocks when called from the serve_forever
        # thread (the one signals land on), so drain from a helper
        def drain():
            try:
                if self._child_httpd is not None:
                    self._child_httpd.shutdown()
                    self._child_httpd.wait_connections_closed(3.0)
            except Exception:
                pass
            os._exit(0)

        threading.Thread(target=drain, daemon=True).start()

    # -- request forwarding --------------------------------------------

    def proxy(self, addr: str, method: str, raw_path: str,
              body: bytes, headers) -> "object":
        """Relay one request verbatim to `addr`, preserving status,
        content type and body bytes (call() would re-encode error
        bodies, mangling e.g. S3 XML error documents)."""
        from .http_rpc import RpcError, Response, _POOL
        hop = {"connection", "keep-alive", "transfer-encoding", "te",
               "upgrade", "proxy-connection", "host", "content-length"}
        fwd = {k: v for k, v in headers.items() if k.lower() not in hop}
        fwd[FWD_HEADER] = "1"
        conn = _POOL.get(addr, 60.0)
        try:
            conn.request(method, raw_path, body=body or None, headers=fwd)
            r = conn.getresponse()
            data = r.read()
        except Exception as e:
            try:
                conn.close()
            except OSError:
                pass
            raise RpcError(f"prefork forward to {addr} failed: {e}",
                           502, addr=addr, route=raw_path, transport=True)
        if r.will_close:
            conn.close()
        else:
            _POOL.put(addr, conn)
        drop = {"connection", "keep-alive", "transfer-encoding",
                "content-length", "content-type", "date", "server"}
        out = {k: v for k, v in r.getheaders() if k.lower() not in drop}
        ctype = r.headers.get("Content-Type") or "application/octet-stream"
        return Response(data, r.status, ctype, out)

    def forward_to_parent(self, method: str, raw_path: str, body: bytes,
                          headers):
        return self.proxy(self.control_addr, method, raw_path, body, headers)

    def fanout(self, method: str, raw_path: str, body: bytes, headers):
        """Re-deliver an admin request to every OTHER worker's sideband
        (graceful drain / leave must reach the whole fleet)."""
        me = worker_id()
        for peer in self.peers():
            if peer.get("wid") == me:
                continue
            try:
                self.proxy(peer["sideband"], method, raw_path, body, headers)
            except Exception:
                pass  # a respawning worker picks up state via its env

    # -- cross-worker observability ------------------------------------

    def _scrape(self, addr: str, path: str, parse: bool):
        from .http_rpc import call
        return call(addr, path, parse=parse, timeout=5.0,
                    headers={FWD_HEADER: "1"})

    def _install_aggregators(self):
        server = self.server
        routes = server.routes

        def wrap(method, prefix, make):
            orig = routes.get((method, prefix))
            if orig is not None:
                server.add(method, prefix, make(orig))

        wrap("GET", "/metrics", self._make_metrics_agg)
        wrap("GET", "/debug/qos", self._make_qos_agg)
        wrap("GET", "/debug/traces", self._make_traces_agg)

    def _others(self):
        me = worker_id()
        return [p for p in self.peers() if p.get("wid") != me]

    def _make_metrics_agg(self, orig):
        group = self

        def handler(req):
            from .http_rpc import Response
            local = orig(req)
            if FWD_HEADER in req.headers:
                return local
            body = local.body if hasattr(local, "body") else local
            if isinstance(body, (bytearray, memoryview)):
                body = bytes(body)
            text = body.decode() if isinstance(body, bytes) else str(body)
            parts = [(str(worker_id()), text)]
            for peer in group._others():
                try:
                    raw = group._scrape(peer["sideband"], "/metrics",
                                        parse=False)
                    parts.append((str(peer["wid"]), raw.decode()))
                except Exception:
                    continue
            merged = _stats.merge_expositions(parts)
            return Response(merged.encode(),
                            content_type="text/plain; version=0.0.4")

        return handler

    def _make_qos_agg(self, orig):
        group = self

        def handler(req):
            local = orig(req)
            if FWD_HEADER in req.headers or not isinstance(local, dict):
                return local
            out = dict(local)
            out["workers"] = {str(worker_id()): local}
            for peer in group._others():
                try:
                    out["workers"][str(peer["wid"])] = group._scrape(
                        peer["sideband"], "/debug/qos", parse=True)
                except Exception:
                    continue
            return out

        return handler

    def _make_traces_agg(self, orig):
        group = self

        def handler(req):
            from .http_rpc import RpcError
            rest = req.path[len("/debug/traces"):].strip("/")
            if FWD_HEADER in req.headers:
                return orig(req)
            if not rest:  # index: concatenation of every worker's list
                local = orig(req)
                if not isinstance(local, dict):
                    return local
                merged = dict(local)
                traces = list(local.get("traces", []))
                for peer in group._others():
                    try:
                        remote = group._scrape(peer["sideband"],
                                               "/debug/traces", parse=True)
                        traces.extend(remote.get("traces", []))
                    except Exception:
                        continue
                merged["traces"] = traces
                return merged
            try:
                return orig(req)
            except RpcError as local_err:
                for peer in group._others():
                    try:
                        return group._scrape(peer["sideband"], req.path,
                                             parse=True)
                    except Exception:
                        continue
                raise local_err

        return handler
