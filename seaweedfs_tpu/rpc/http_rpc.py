"""HTTP RPC substrate: the daemon-to-daemon communication backbone.

The reference runs gRPC over HTTP/2 with streaming for heartbeats, shard
reads and copies (weed/rpc/grpc_client_server.go:23-50).  This image has no
grpcio, and daemon traffic here is I/O-bound rather than latency-bound
(SURVEY.md §5.8), so the equivalent substrate is stdlib HTTP/1.1:
JSON-bodied control calls + raw-byte responses for data streams, served by
a threading server.  TPU-side collectives stay inside JAX (parallel/mesh.py)
— this layer never carries tensor traffic.
"""

from __future__ import annotations

import http.client
import json
import os
import socket
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
from http import HTTPStatus
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional

from .. import tracing
from ..qos import classify as _qos
from ..stats import metrics as _stats
from ..util import faults as _faults
from . import prefork as _prefork


class RpcError(Exception):
    """RPC failure carrying enough context for retry policy: the remote
    HTTP status (or 503 for transport failures), the destination and
    route, whether the error is a TRANSPORT failure (peer unreachable /
    connection died — the request may never have been delivered) vs a
    REMOTE response (the peer answered with >= 400), and optional extra
    response headers (Retry-After on shed responses)."""

    def __init__(self, message: str, status: int = 500, *,
                 addr: str = "", route: str = "",
                 transport: bool = False,
                 headers: Optional[dict] = None):
        super().__init__(message)
        self.status = status
        self.addr = addr
        self.route = route
        self.transport = transport
        self.headers = headers or {}


# -- deadline propagation ----------------------------------------------------

DEADLINE_HEADER = "X-Deadline"  # absolute wall-clock epoch seconds

_deadline_local = threading.local()


def current_deadline() -> Optional[float]:
    """The absolute (epoch seconds) deadline pinned on this thread, or
    None.  Set by deadline_scope() on clients and by the dispatch loop
    on servers, so nested outbound calls inherit the caller's budget."""
    return getattr(_deadline_local, "value", None)


def set_deadline(value: Optional[float]) -> Optional[float]:
    prev = getattr(_deadline_local, "value", None)
    _deadline_local.value = value
    return prev


class deadline_scope:
    """Context manager pinning an absolute deadline for everything this
    thread calls: `with deadline_scope(2.0): ...` caps all nested RPC
    timeouts and is forwarded in X-Deadline.  Never EXTENDS an already
    tighter inherited deadline."""

    def __init__(self, timeout: Optional[float] = None,
                 absolute: Optional[float] = None):
        dl = absolute if absolute is not None else (
            time.time() + timeout if timeout is not None else None)
        inherited = current_deadline()
        if dl is None or (inherited is not None and inherited < dl):
            dl = inherited
        self._dl = dl
        self._prev: Optional[float] = None

    def __enter__(self):
        self._prev = set_deadline(self._dl)
        return self._dl

    def __exit__(self, *exc):
        set_deadline(self._prev)
        return False


class Request:
    def __init__(self, handler: BaseHTTPRequestHandler, path: str,
                 query: dict, body: bytes):
        self.handler = handler
        self.path = path
        self.query = query  # dict[str, str] (first value wins)
        self.body = body
        self.headers = handler.headers

    def json(self) -> dict:
        if not self.body:
            return {}
        return json.loads(self.body)

    def param(self, name: str, default: Optional[str] = None) -> Optional[str]:
        value = self.query.get(name)
        # blank values ("?limit=") behave as absent for value params;
        # flag params ("?delete=") test membership via `in req.query`
        return default if value in (None, "") else value


class Response:
    """Return from a route: json dict, bytes, or a (status, headers, body).

    `body` may also be an ITERATOR of byte chunks — the server then
    streams it without buffering: with a Content-Length header the chunks
    are written raw; without one the reply uses HTTP/1.1 chunked
    transfer-encoding (the substrate for VolumeCopy/CopyFile-style
    streaming RPCs, volume_server.proto:49-53)."""

    def __init__(self, body=b"", status: int = 200,
                 content_type: str = "application/octet-stream",
                 headers: Optional[dict] = None):
        self.body = body
        self.status = status
        self.content_type = content_type
        self.headers = headers or {}


def stream_file(path: str, chunk_size: int = 4 << 20,
                headers: Optional[dict] = None) -> Response:
    """Response that streams a file with a fixed Content-Length snapshot
    (bytes appended mid-stream are not sent)."""
    import os

    length = os.path.getsize(path)

    def gen():
        left = length
        with open(path, "rb") as f:
            while left > 0:
                chunk = f.read(min(chunk_size, left))
                if not chunk:
                    break
                left -= len(chunk)
                yield chunk

    h = dict(headers or {})
    h["Content-Length"] = str(length)
    return Response(gen(), headers=h)


def sendfile_enabled() -> bool:
    """Zero-copy writeback is on unless WEED_SENDFILE=0 (or the platform
    has no os.sendfile — then FileSlice bodies take the pread path)."""
    return os.environ.get("WEED_SENDFILE", "1") != "0"


class FileSlice:
    """Zero-copy reply body: a byte range of an open file, written with
    os.sendfile straight from the page cache to the client socket — the
    data never crosses into Python.  Producers (volume .dat reads, disk
    cache hits) hand a dup'd fd with close_fd=True when the underlying
    file may be closed or replaced while the reply is in flight: the dup
    pins the inode, so the bytes stay valid.

    `on_close` fires exactly once when the reply path finishes with the
    slice (the _reply_file finally) — resource gates ride it (the
    volume download throttle holds its byte budget for the TRANSFER's
    lifetime, not just header construction)."""

    __slots__ = ("fd", "offset", "length", "_close_fd", "_on_close")

    def __init__(self, fd: int, offset: int, length: int,
                 close_fd: bool = False, on_close=None):
        self.fd = fd
        self.offset = offset
        self.length = length
        self._close_fd = close_fd
        self._on_close = on_close

    def read_bytes(self) -> bytes:
        """Materialize the slice (HEAD replies, fallback paths, tests)."""
        return os.pread(self.fd, self.length, self.offset)

    def close(self):
        if self._close_fd and self.fd >= 0:
            try:
                os.close(self.fd)
            except OSError:
                pass
            self.fd = -1
        cb, self._on_close = self._on_close, None
        if cb is not None:
            try:
                cb()
            except Exception:
                pass


_STATUS_PHRASES = {s.value: s.phrase for s in HTTPStatus}


class _LeanHeaders(dict):
    """Case-insensitive read view over headers parsed by the lean
    request parser.  Keys keep their wire casing (metadata copy loops
    and SigV2/V4 canonicalization see what the client sent); lookups
    try the exact key first — our own clients send canonical casing, so
    this is a single C dict probe — and fall back to a lazily-built
    lowercase index (probing absent optional headers like the trace and
    deadline carriers must not cost a case-folding scan per request)."""

    __slots__ = ("_lower",)

    def _fold(self, key: str):
        try:
            low = self._lower
        except AttributeError:
            low = self._lower = {k.lower(): v for k, v in self.items()}
        return low.get(key.lower())

    def get(self, key, default=None):
        v = dict.get(self, key)
        if v is None:
            v = self._fold(key)
        return v if v is not None else default

    def __getitem__(self, key):
        v = self.get(key)
        if v is None:
            raise KeyError(key)
        return v

    def __contains__(self, key):
        return dict.__contains__(self, key) or \
            self._fold(key) is not None


Route = Callable[[Request], object]


class RpcServer:
    """Route-table HTTP server.  Routes are matched by (method, prefix);
    the longest prefix wins.  A default route handles everything else
    (object GET/POST by fid on volume servers)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 service_name: str = "rpc"):
        self.routes: dict[tuple[str, str], Route] = {}
        self.default_route: Optional[Callable[[str, Request], object]] = None
        # daemon identity for trace spans and the hop-latency vector
        # (masters/filers/volume servers/s3 gateways set their own)
        self.service_name = service_name
        # precompiled route tables (rebuilt on add()): first-segment
        # buckets + the small list of prefixes that can match across a
        # segment boundary — _match then touches a handful of candidates
        # instead of linearly scanning every registered route
        self._match_by_seg: dict[tuple[str, str], list] = {}
        self._match_loose: dict[str, list] = {}
        # hoisted per-request metric child: one labels() lookup per
        # server instead of per request
        self._inflight = _stats.RpcInflightGauge.labels(service_name)
        self._sendfile_bytes = \
            _stats.GatewaySendfileBytesCounter.labels(service_name)
        # prefork (WEED_HTTP_WORKERS): only explicitly-bound ports shard
        # into worker processes — port-0 servers are ephemeral (test
        # fixtures, embedded sidecars) and must never fork the host
        # process (pytest/bench carry JAX + thread pools)
        self._prefork = None
        self._prefork_workers = (
            _prefork.worker_count()
            if port != 0 and _prefork.fork_available() else 1)
        # admin routes the parent re-delivers to every worker after
        # handling them itself (graceful drain / leave must reach the
        # whole fleet, whichever process accepted the request)
        self.fanout_prefixes: set[str] = set()
        # GET/HEAD routes workers must proxy to worker 0 anyway: state
        # that lives only in the parent process (raft leadership, the
        # heartbeat-fed topology) — a worker's fork-time copy would
        # answer with stale or leaderless state, not just miss new keys
        self.parent_prefixes: set[str] = set()
        self._on_worker_start: list[Callable[[int], None]] = []
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"
            # keep-alive + Nagle + delayed ACK = 40 ms quanta per
            # response; buffered wfile coalesces the status line +
            # headers + body into one send() (stdlib's default of 0
            # makes every header line its own syscall)
            wbufsize = 64 * 1024
            disable_nagle_algorithm = True
            # reap idle keep-alive connections: each one pins a handler
            # thread + fd; clients transparently retry a reaped socket
            timeout = 60
            _date_cache = (0, "")  # whole-second Date header memo

            def log_message(self, fmt, *args):
                pass

            def date_time_string(self, timestamp=None):
                # one strftime per second, not per response
                if timestamp is not None:
                    return super().date_time_string(timestamp)
                now = int(time.time())
                cached = Handler._date_cache
                if cached[0] == now:
                    return cached[1]
                rendered = super().date_time_string(now)
                Handler._date_cache = (now, rendered)
                return rendered

            def parse_request(self):
                # Lean fast path for plain HTTP/1.0-1.1 requests: the
                # stdlib routes every request's headers through
                # email.parser (feedparser + Message, whose .get()
                # lower()s each stored key per lookup) — ~0.1 ms of
                # pure GIL time per request.  Anything unusual in the
                # request line falls back to the stdlib parser.
                requestline = str(self.raw_requestline,
                                  "iso-8859-1").rstrip("\r\n")
                words = requestline.split()
                if len(words) != 3 or \
                        words[2] not in ("HTTP/1.1", "HTTP/1.0"):
                    return super().parse_request()
                self.requestline = requestline
                self.command, self.path, self.request_version = words
                self.close_connection = words[2] == "HTTP/1.0"
                headers = _LeanHeaders()
                setdefault = dict.setdefault  # no case-folding scans
                rl = self.rfile.readline
                last = None
                count = 0
                while True:
                    line = rl(65537)
                    if len(line) > 65536:
                        self.send_error(431, "Header line too long")
                        return False
                    if line in (b"\r\n", b"\n", b""):
                        break
                    count += 1
                    if count > 100:
                        self.send_error(431, "Too many headers")
                        return False
                    if line[0] in (32, 9):  # obs-fold continuation
                        if last is not None:
                            headers[last] = (
                                dict.__getitem__(headers, last) + " " +
                                line.strip().decode("iso-8859-1"))
                        continue
                    idx = line.find(b":")
                    if idx < 1:
                        continue
                    key = line[:idx].decode("iso-8859-1")
                    setdefault(headers, key,
                               line[idx + 1:].strip().decode("iso-8859-1"))
                    last = key
                self.headers = headers
                conntype = (headers.get("Connection") or "").lower()
                if conntype == "close":
                    self.close_connection = True
                elif conntype == "keep-alive":
                    self.close_connection = False
                if (headers.get("Expect") or "").lower() == \
                        "100-continue" and \
                        self.request_version == "HTTP/1.1":
                    if not self.handle_expect_100():
                        return False
                return True

            def _dispatch(self, method: str):
                raw_path = self.path
                if "?" in raw_path:
                    parsed = urllib.parse.urlsplit(raw_path)
                    path = parsed.path
                    query = {k: v[0] for k, v in
                             urllib.parse.parse_qs(
                                 parsed.query,
                                 keep_blank_values=True).items()}
                else:  # hot path: no query string, nothing to parse
                    path, query = raw_path, {}
                length = int(self.headers.get("Content-Length") or 0)
                body = self.rfile.read(length) if length else b""
                req = Request(self, path, query, body)
                pf = outer._prefork
                # admin routes that must reach the whole fleet: the
                # receiving process executes them locally and re-delivers
                # to every peer below — a worker must NOT forward them to
                # the parent, since the forwarded copy (FWD marked) is
                # served strictly locally and the fanout would be lost
                fanout_path = (
                    pf is not None and
                    _prefork.FWD_HEADER not in self.headers and
                    any(path.startswith(p)
                        for p in outer.fanout_prefixes))
                if pf is not None and _prefork.is_worker() and \
                        not fanout_path and \
                        _prefork.FWD_HEADER not in self.headers and \
                        not path.startswith("/debug/") and \
                        (method not in ("GET", "HEAD") or
                         any(path.startswith(p)
                             for p in outer.parent_prefixes)):
                    # prefork workers are read replicas of a fork-time
                    # snapshot: every mutation is relayed to the single
                    # writer (the parent) over its control sideband
                    try:
                        resp = pf.forward_to_parent(method, raw_path,
                                                    body, self.headers)
                    except RpcError as e:
                        resp = Response(
                            json.dumps({"error": str(e)}).encode(),
                            e.status, "application/json",
                            headers=dict(e.headers))
                    self._reply(resp)
                    return
                route, prefix = outer._match(method, path)
                # route label for the span name / hop vector: the matched
                # prefix ("*" = default route), never the raw path — label
                # cardinality must stay bounded
                label = prefix if route is not None else "*"
                service = outer.service_name
                sp = tracing.from_headers(f"{method} {label}", service,
                                          self.headers)
                # install the caller's QoS context (class + tenant) for
                # the handler's duration, exactly like the deadline; tag
                # the dispatch span so profiler route shares separate
                # background from foreground CPU time
                qcls, qtenant = _qos.from_headers(self.headers)
                tracing.tag_qos(sp, qcls, qtenant)
                prev_qos = _qos.set_qos(qcls, qtenant)
                src = self.headers.get(tracing.SRC_HEADER) or "client"
                outer._inflight.inc()
                t0 = time.perf_counter()
                prev = tracing.swap(sp)
                # honor the caller's propagated deadline: work it has
                # already abandoned is rejected, not executed, and the
                # remaining budget is pinned for nested outbound calls
                deadline = None
                dl_header = self.headers.get(DEADLINE_HEADER)
                if dl_header:
                    try:
                        deadline = float(dl_header)
                    except ValueError:
                        deadline = None
                prev_dl = set_deadline(deadline)
                try:
                    try:
                        if deadline is not None and \
                                time.time() >= deadline:
                            raise RpcError(
                                f"deadline exceeded before {method} "
                                f"{label} started", 504)
                        if _faults.ACTIVE:
                            try:
                                _faults.on_rpc("server", outer.address,
                                               path)
                            except _faults.FaultInjected as f:
                                raise RpcError(str(f), f.status) \
                                    from None
                        if route is None:
                            if outer.default_route is not None:
                                result = outer.default_route(method, req)
                            else:
                                raise RpcError(
                                    f"no route {method} {path}", 404)
                        else:
                            result = route(req)
                        resp = outer._coerce(result)
                    except RpcError as e:
                        resp = Response(
                            json.dumps({"error": str(e)}).encode(),
                            e.status, "application/json",
                            headers=dict(e.headers))
                    except Exception as e:  # internal errors as 500 JSON
                        resp = Response(
                            json.dumps({"error": f"{type(e).__name__}: {e}"}
                                       ).encode(), 500, "application/json")
                    if pf is not None and \
                            _prefork.FWD_HEADER not in self.headers:
                        if resp.status == 404 and _prefork.is_worker() \
                                and method in ("GET", "HEAD"):
                            # fork-snapshot miss: data written after this
                            # worker was born is visible to the parent
                            try:
                                resp = pf.forward_to_parent(
                                    method, raw_path, body, self.headers)
                            except RpcError:
                                pass  # keep the honest local 404
                        elif resp.status < 400 and fanout_path:
                            # whichever process accepted the admin
                            # request (with SO_REUSEPORT that is a
                            # non-parent worker (N-1)/N of the time)
                            # re-delivers it to every peer, parent
                            # included — drain/leave must never
                            # dead-end in one process
                            pf.fanout(method, raw_path, body, self.headers)
                    if resp.status >= 400:
                        sp.status = f"error {resp.status}"
                    if sp.sampled:
                        # hand the trace id back so callers can fetch the
                        # span tree from /debug/traces/<id>
                        resp.headers.setdefault(tracing.TRACE_HEADER,
                                                sp.trace_id)
                    self._reply(resp)
                finally:
                    _qos.set_qos(*prev_qos)
                    set_deadline(prev_dl)
                    tracing.restore(prev)
                    sp.finish()
                    outer._inflight.dec()
                    _stats.RpcHopHistogram.labels(src, service, label) \
                        .observe(time.perf_counter() - t0)

            _server_line = ""  # version_string() is constant; memoized

            def _reply(self, resp: Response):
                body = resp.body
                if isinstance(body, str):
                    body = body.encode()
                if isinstance(body, FileSlice):
                    self._reply_file(resp, body)
                    return
                if not isinstance(body, (bytes, bytearray, memoryview)):
                    # iterators stream; memoryview bodies (zero-copy
                    # cache hits) take the buffered single-write path —
                    # len() and wfile.write() both accept them directly
                    self._reply_stream(resp, body)
                    return
                # one formatted write into the buffered wfile instead
                # of send_response + N send_header calls (each its own
                # format + encode + buffer append)
                srv = Handler._server_line
                if not srv:
                    srv = Handler._server_line = self.version_string()
                status = resp.status
                extra = resp.headers
                head = [f"HTTP/1.1 {status} "
                        f"{_STATUS_PHRASES.get(status, '')}\r\n"
                        f"Server: {srv}\r\n"
                        f"Date: {self.date_time_string()}\r\n"
                        f"Content-Type: {resp.content_type}\r\n"]
                if not extra:
                    head.append(f"Content-Length: {len(body)}\r\n\r\n")
                else:
                    if "Content-Length" not in extra:
                        head.append(f"Content-Length: {len(body)}\r\n")
                    for k, v in extra.items():
                        head.append(f"{k}: {v}\r\n")
                        if k.lower() == "connection" and \
                                str(v).lower() == "close":
                            self.close_connection = True
                    head.append("\r\n")
                self.wfile.write("".join(head).encode("latin-1"))
                if self.command != "HEAD":
                    self.wfile.write(body)

            def _reply_file(self, resp: Response, fs: FileSlice):
                """Write a FileSlice body: buffered head, then
                os.sendfile from the source fd to the client socket
                (zero user-space copies).  Falls back to a pread loop
                when sendfile is disabled/unavailable or refuses the fd
                pair (e.g. non-regular files)."""
                try:
                    srv = Handler._server_line
                    if not srv:
                        srv = Handler._server_line = self.version_string()
                    head = [f"HTTP/1.1 {resp.status} "
                            f"{_STATUS_PHRASES.get(resp.status, '')}\r\n"
                            f"Server: {srv}\r\n"
                            f"Date: {self.date_time_string()}\r\n"
                            f"Content-Type: {resp.content_type}\r\n"]
                    if "Content-Length" not in resp.headers:
                        head.append(f"Content-Length: {fs.length}\r\n")
                    for k, v in resp.headers.items():
                        head.append(f"{k}: {v}\r\n")
                        if k.lower() == "connection" and \
                                str(v).lower() == "close":
                            self.close_connection = True
                    head.append("\r\n")
                    self.wfile.write("".join(head).encode("latin-1"))
                    if self.command == "HEAD":
                        return
                    self.wfile.flush()  # head must precede spliced bytes
                    sent = 0
                    if sendfile_enabled() and hasattr(os, "sendfile"):
                        out = self.connection.fileno()
                        try:
                            while sent < fs.length:
                                n = os.sendfile(out, fs.fd,
                                                fs.offset + sent,
                                                fs.length - sent)
                                if n == 0:
                                    break  # source truncated under us
                                sent += n
                        except OSError:
                            if sent:
                                # mid-transfer failure: the framing is
                                # already committed, sever the socket
                                self.close_connection = True
                                return
                            sent = -1  # untouched: safe to fall back
                        if sent > 0:
                            outer._sendfile_bytes.inc(sent)
                        if 0 < sent < fs.length:
                            self.close_connection = True  # short source
                        if sent >= 0:
                            return
                    # pread fallback (WEED_SENDFILE=0, platform without
                    # sendfile, or sendfile rejected the fd pair)
                    done = 0
                    while done < fs.length:
                        chunk = os.pread(fs.fd,
                                         min(1 << 20, fs.length - done),
                                         fs.offset + done)
                        if not chunk:
                            self.close_connection = True
                            break
                        self.wfile.write(chunk)
                        done += len(chunk)
                finally:
                    fs.close()

            def _reply_stream(self, resp: Response, chunks):
                """Stream an iterator body: raw writes under a known
                Content-Length, chunked transfer-encoding otherwise."""
                chunked = "Content-Length" not in resp.headers
                self.send_response(resp.status)
                self.send_header("Content-Type", resp.content_type)
                for k, v in resp.headers.items():
                    self.send_header(k, v)
                if chunked:
                    self.send_header("Transfer-Encoding", "chunked")
                self.end_headers()
                if self.command == "HEAD":
                    return
                try:
                    for chunk in chunks:
                        if not chunk:
                            continue
                        if chunked:
                            self.wfile.write(b"%x\r\n" % len(chunk))
                            self.wfile.write(chunk)
                            self.wfile.write(b"\r\n")
                        else:
                            self.wfile.write(chunk)
                        # push each chunk out now: the buffered wfile
                        # would otherwise hold early chunks hostage and
                        # void the first-byte win of streaming replies
                        self.wfile.flush()
                    if chunked:
                        self.wfile.write(b"0\r\n\r\n")
                except Exception:
                    # the body generator (or the peer's socket) failed
                    # after the status line went out: the only honest
                    # signal left is a severed connection — the framing
                    # (Content-Length short / missing terminal chunk)
                    # tells the client the transfer is truncated
                    self.close_connection = True

            def do_GET(self):
                self._dispatch("GET")

            def do_HEAD(self):
                self._dispatch("HEAD")

            def do_POST(self):
                self._dispatch("POST")

            def do_PUT(self):
                self._dispatch("PUT")

            def do_DELETE(self):
                self._dispatch("DELETE")

        class Server(ThreadingHTTPServer):
            # the stdlib default backlog of 5 causes 1s+ SYN-retransmit
            # stalls under modest concurrency (16 clients saturate it)
            request_queue_size = 128

            def __init__(s, *a, **kw):
                s._conns = set()
                s._conns_lock = threading.Lock()
                super().__init__(*a, **kw)

            # track established connections: shutdown() only stops the
            # accept loop, and a keep-alive handler thread would keep
            # serving a STOPPED daemon's state (zombie server) — stop()
            # must be able to sever them
            def process_request(s, request, client_address):
                with s._conns_lock:
                    s._conns.add(request)
                super().process_request(request, client_address)

            def shutdown_request(s, request):
                with s._conns_lock:
                    s._conns.discard(request)
                super().shutdown_request(request)

            def close_all_connections(s):
                with s._conns_lock:
                    conns = list(s._conns)
                for sock in conns:
                    try:
                        sock.shutdown(socket.SHUT_RDWR)
                    except OSError:
                        pass

            def wait_connections_closed(s, timeout: float = 5.0) -> bool:
                """Wait for in-flight handler threads to finish their
                current request and exit (they deregister the socket in
                shutdown_request) — callers tear down shared state next,
                and a handler mid-mutation must not race that."""
                deadline = time.monotonic() + timeout
                while time.monotonic() < deadline:
                    with s._conns_lock:
                        if not s._conns:
                            return True
                    time.sleep(0.01)
                return False

        self._handler_cls = Handler
        self._server_cls = Server
        self.httpd = Server((host, port), Handler, bind_and_activate=False)
        if self._prefork_workers > 1 and _prefork.reuseport_available():
            # ALL sockets on a port must set SO_REUSEPORT for a later
            # one to join, so the parent's main listener opts in up
            # front when workers will shard this port
            try:
                self.httpd.socket.setsockopt(
                    socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
            except OSError:
                pass
        try:
            self.httpd.server_bind()
            self.httpd.server_activate()
        except BaseException:
            self.httpd.server_close()
            raise
        self.httpd.daemon_threads = True
        self.host = host
        self.port = self.httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def _new_listener(self, host: str, port: int, reuseport: bool = False):
        """Another HTTP server sharing this RpcServer's routes: worker
        listeners on the shared port (SO_REUSEPORT) and the loopback
        sidebands the prefork group uses for forwarding/scraping."""
        srv = self._server_cls((host, port), self._handler_cls,
                               bind_and_activate=False)
        srv.daemon_threads = True
        if reuseport:
            srv.socket.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
        try:
            srv.server_bind()
            srv.server_activate()
        except BaseException:
            srv.server_close()
            raise
        return srv

    def on_worker_start(self, fn: Callable[[int], None]):
        """Register a post-fork hook (runs in each worker child before
        it starts accepting).  Daemons use this to reopen per-process
        resources — e.g. the filer's sqlite connection, which cannot be
        shared across a fork."""
        self._on_worker_start.append(fn)

    def on_worker_start_fire(self, wid: int):
        for fn in self._on_worker_start:
            try:
                fn(wid)
            except Exception:
                pass

    def _rebuild_match_tables(self):
        """Precompile the route set.  Prefixes with an interior slash
        ("/dir/assign") can only match a path whose first segment equals
        theirs, so they live in per-(method, segment) buckets; prefixes
        without one ("", "/", "/metrics") may match across a segment
        boundary ("/metricsfoo") and go to the small loose list.  Both
        are sorted longest-first so the first startswith hit wins, and
        the finished dicts are swapped in atomically — handler threads
        read them lock-free."""
        by_seg: dict[tuple[str, str], list] = {}
        loose: dict[str, list] = {}
        for (m, prefix), route in self.routes.items():
            cut = prefix.find("/", 1)
            if cut > 0:
                by_seg.setdefault((m, prefix[1:cut]), []) \
                    .append((prefix, route))
            else:
                loose.setdefault(m, []).append((prefix, route))
        for bucket in by_seg.values():
            bucket.sort(key=lambda pr: len(pr[0]), reverse=True)
        for bucket in loose.values():
            bucket.sort(key=lambda pr: len(pr[0]), reverse=True)
        self._match_by_seg = by_seg
        self._match_loose = loose

    def _match(self, method: str, path: str
               ) -> tuple[Optional[Route], str]:
        """(route, matched prefix); (None, "") when no prefix matches.
        Longest prefix wins, exactly like the linear scan this replaces,
        but via the precompiled tables."""
        cut = path.find("/", 1)
        seg = path[1:cut] if cut > 0 else path[1:]
        best, best_prefix = None, ""
        for prefix, route in self._match_by_seg.get((method, seg), ()):
            if path.startswith(prefix):
                best, best_prefix = route, prefix
                break  # longest-first order: first hit is the winner
        for prefix, route in self._match_loose.get(method, ()):
            if len(prefix) <= len(best_prefix):
                break  # longest-first: nothing longer remains
            if path.startswith(prefix):
                best, best_prefix = route, prefix
                break
        return best, best_prefix

    @staticmethod
    def _coerce(result) -> Response:
        if isinstance(result, Response):
            return result
        if isinstance(result, FileSlice):
            return Response(result)
        if isinstance(result, (dict, list)):
            return Response(json.dumps(result).encode(), 200,
                            "application/json")
        if isinstance(result, (bytes, bytearray)):
            return Response(bytes(result))
        if result is None:
            return Response(b"", 204)
        return Response(str(result).encode(), 200, "text/plain")

    def route(self, method: str, prefix: str):
        def deco(fn: Route):
            self.add(method, prefix, fn)
            return fn
        return deco

    def add(self, method: str, prefix: str, fn: Route):
        self.routes[(method, prefix)] = fn
        self._rebuild_match_tables()

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    def start(self):
        self._thread = threading.Thread(target=self.httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        if self._prefork_workers > 1 and self._prefork is None:
            # the parent keeps serving as worker 0 on the listener it
            # already owns; N-1 children shard the same port
            self._prefork = _prefork.PreforkGroup(self,
                                                  self._prefork_workers)
            self._prefork.start()

    def stop(self):
        if self._prefork is not None and not _prefork.is_worker():
            self._prefork.stop()
            self._prefork = None
        self.httpd.shutdown()
        # sever live keep-alive connections: their handler threads would
        # otherwise keep answering from this daemon's torn-down state
        # (clients transparently retry on a fresh connection) — then
        # drain in-flight requests before the caller tears down stores
        self.httpd.close_all_connections()
        self.httpd.wait_connections_closed()
        self.httpd.server_close()
        if self._thread:
            self._thread.join(timeout=5)


# -- client helpers ----------------------------------------------------------


class _NoDelayConnection(http.client.HTTPConnection):
    """HTTPConnection with TCP_NODELAY: headers and body go out as
    separate send()s, and Nagle would hold the second for the peer's
    delayed ACK (~40 ms) on every pooled reuse."""

    def connect(self):
        super().connect()
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)


class _ConnPool:
    """Keep-alive HTTP connection pool, shared process-wide — the
    analogue of the reference's cached gRPC client connections
    (rpc/grpc_client_server.go:27-41).  Bounded idle list per address;
    borrowed connections that error are closed, not returned."""

    def __init__(self, max_idle_per_addr: int = 16,
                 idle_ttl: float = 30.0):
        self._lock = threading.Lock()
        self._idle: dict[str, list] = {}  # addr -> [(conn, stored_at)]
        self.max_idle = self._env_max_idle(max_idle_per_addr)
        self.idle_ttl = idle_ttl
        self._last_sweep = 0.0

    @staticmethod
    def _env_max_idle(default: int) -> int:
        raw = os.environ.get("WEED_POOL_MAX_IDLE", "")
        try:
            return max(1, int(raw)) if raw else default
        except ValueError:
            return default

    def configure_for_prefork(self, workers: int):
        """Per-process-aware sizing: with N workers on this host, each
        process keeps 1/N of the per-peer idle budget (floor 2) and
        reaps idle sockets faster — otherwise N workers hold N full
        pools against every peer, multiplying its fd load by N."""
        if workers <= 1:
            return
        base = self._env_max_idle(16)
        trimmed = []
        with self._lock:
            self.max_idle = max(2, base // workers)
            self.idle_ttl = min(self.idle_ttl, 10.0)
            for idle in self._idle.values():
                while len(idle) > self.max_idle:
                    trimmed.append(idle.pop(0)[0])
        for conn in trimmed:
            conn.close()

    def reinit_after_fork(self):
        """Forget every pooled connection WITHOUT closing the sockets,
        and REPLACE the lock rather than acquire it.  Freshly-forked
        workers inherit the parent's pooled fds; reusing them would
        interleave two processes' requests on one TCP stream, and
        close()ing them here is unnecessary (the child drops its
        reference either way — the parent still owns the socket).  The
        lock must not be acquired: the parent keeps serving while it
        forks, so a child can inherit it mid-hold and would deadlock
        before ever binding its listener."""
        self._lock = threading.Lock()
        self._idle = {}
        self._last_sweep = 0.0

    def _sweep(self, now: float):
        """Background-free lazy reap: every get/put piggybacks a cheap
        periodic pass over ALL addresses, so idle sockets whose TTL
        expired while their address went quiet still get closed instead
        of pinning fds until the peer reaps them.  Expired connections
        are collected under the lock but closed outside it."""
        if now - self._last_sweep < min(5.0, self.idle_ttl / 2):
            return
        expired = []
        with self._lock:
            if now - self._last_sweep < min(5.0, self.idle_ttl / 2):
                return  # another thread swept while we waited
            self._last_sweep = now
            for addr in list(self._idle):
                kept = []
                for conn, stored_at in self._idle[addr]:
                    if now - stored_at > self.idle_ttl:
                        expired.append(conn)
                    else:
                        kept.append((conn, stored_at))
                if kept:
                    self._idle[addr] = kept
                else:
                    del self._idle[addr]
        for conn in expired:
            conn.close()

    @staticmethod
    def _dropped(conn) -> bool:
        """A healthy idle keep-alive socket has nothing to read; pending
        readability means the server closed it (FIN queued) or sent
        stray bytes — reusing it would fail mid-request, which for a
        non-idempotent RPC cannot be retried.  This also protects
        against the address being REBOUND by a different server."""
        sock = conn.sock
        if sock is None:
            return True
        try:
            # non-blocking MSG_PEEK instead of select(): select raises
            # ValueError past FD_SETSIZE (1024 fds).  The socket must be
            # put in true non-blocking mode — in timeout mode CPython
            # waits for readability BEFORE recv, so MSG_DONTWAIT alone
            # would still block for the full socket timeout
            sock.setblocking(False)
            sock.recv(1, socket.MSG_PEEK)
        except (BlockingIOError, InterruptedError):
            return False  # nothing queued: healthy idle keep-alive
        except OSError:
            return True
        return True  # EOF (b"") or stray queued bytes

    def get(self, addr: str, timeout: float):
        now = time.monotonic()
        self._sweep(now)
        while True:
            with self._lock:
                idle = self._idle.get(addr)
                item = idle.pop() if idle else None
            if item is None:
                host, _, port = addr.partition(":")
                return _NoDelayConnection(
                    host, int(port) if port else 80, timeout=timeout)
            conn, stored_at = item
            if now - stored_at > self.idle_ttl or self._dropped(conn):
                conn.close()
                continue
            conn.timeout = timeout
            if conn.sock is not None:
                conn.sock.settimeout(timeout)
            return conn

    def put(self, addr: str, conn):
        now = time.monotonic()
        evicted = None
        with self._lock:
            idle = self._idle.setdefault(addr, [])
            if len(idle) >= self.max_idle:
                # keep the connection just used (freshest, least likely
                # to be server-reaped) and evict the oldest idle one;
                # close it outside the lock — get() may be racing us
                evicted = idle.pop(0)[0]
            idle.append((conn, now))
        if evicted is not None:
            evicted.close()
        self._sweep(now)


_POOL = _ConnPool()

# pick up a WEED_FAULTS spec set before process start; daemons/tests
# that set it later reconfigure via faults.REGISTRY or /debug/faults
_faults.load_env()


def call(addr: str, path: str, payload: Optional[dict] = None,
         method: Optional[str] = None, timeout: float = 30.0,
         raw: Optional[bytes] = None, headers: Optional[dict] = None,
         parse: bool = True):
    """JSON RPC call; returns parsed JSON (or raw bytes for non-JSON).
    parse=False always returns the raw body — required when fetching
    stored object content whose mime may itself be application/json."""
    data = None
    req_headers = _qos.inject(tracing.inject(dict(headers or {})))
    if raw is not None:
        data = raw
    elif payload is not None:
        data = json.dumps(payload).encode()
        req_headers["Content-Type"] = "application/json"
    if method is None:
        method = "POST" if data is not None else "GET"
    # propagate the thread's deadline: cap this hop's timeout by the
    # remaining budget and forward the absolute value downstream
    deadline = current_deadline()
    if deadline is not None and DEADLINE_HEADER not in req_headers:
        remaining = deadline - time.time()
        if remaining <= 0:
            raise RpcError(
                f"deadline exceeded before call to {addr}{path}", 504,
                addr=addr, route=path)
        timeout = min(timeout, remaining)
        req_headers[DEADLINE_HEADER] = f"{deadline:.6f}"
    if _faults.ACTIVE:
        try:
            short = _faults.on_rpc("client", addr, path)
        except _faults.FaultInjected as f:
            if f.kind == "reset":
                raise RpcError(
                    f"cannot reach {addr}: injected connection reset",
                    503, addr=addr, route=path, transport=True) \
                    from None
            raise RpcError(str(f), f.status, addr=addr,
                           route=path) from None
        if short is not None:
            raise RpcError(
                f"truncated response from {addr}: injected short read",
                502, addr=addr, route=path, transport=True) from None
    # one retry, ONLY for a pooled connection the server closed while it
    # sat idle (keep-alive reap, restart): those fail with a reset /
    # disconnect before any response.  Timeouts and errors on fresh
    # connections never retry — re-sending a non-idempotent RPC that may
    # already be executing would double-apply the mutation
    stale_errors = (http.client.RemoteDisconnected,
                    http.client.BadStatusLine,
                    ConnectionResetError, BrokenPipeError)
    for attempt in (0, 1):
        if attempt == 0:
            conn = _POOL.get(addr, timeout)
        else:  # bypass the pool: it may hold MORE stale sockets
            host, _, port = addr.partition(":")
            conn = _NoDelayConnection(host, int(port) if port else 80,
                                      timeout=timeout)
        fresh = conn.sock is None
        try:
            # SEND phase: a reuse failure here means the server closed
            # the idle socket before receiving the request — safe to
            # retry any method, it was never fully delivered
            conn.request(method, path, body=data, headers=req_headers)
        except stale_errors as e:
            conn.close()
            if attempt == 0 and not fresh:
                continue
            raise RpcError(f"cannot reach {addr}: {e}", 503,
                           addr=addr, route=path,
                           transport=True) from None
        except (http.client.HTTPException, ConnectionError,
                socket.timeout, TimeoutError, OSError) as e:
            conn.close()
            raise RpcError(f"cannot reach {addr}: {e}", 503,
                           addr=addr, route=path,
                           transport=True) from None
        try:
            # RECEIVE phase: the request reached the server and may have
            # EXECUTED even though the response was lost — only
            # idempotent methods may retry here
            resp = conn.getresponse()
            body = resp.read()
            status = resp.status
            ctype = resp.headers.get("Content-Type", "")
            keep = not resp.will_close
        except stale_errors as e:
            conn.close()
            if attempt == 0 and not fresh and method in ("GET", "HEAD"):
                continue
            raise RpcError(f"cannot reach {addr}: {e}", 503,
                           addr=addr, route=path,
                           transport=True) from None
        except (http.client.HTTPException, ConnectionError,
                socket.timeout, TimeoutError, OSError) as e:
            conn.close()
            raise RpcError(f"cannot reach {addr}: {e}", 503,
                           addr=addr, route=path,
                           transport=True) from None
        if keep:
            _POOL.put(addr, conn)
        else:
            conn.close()
        if status >= 400:
            try:
                message = json.loads(body).get("error", body.decode())
            except Exception:
                message = body.decode(errors="replace")
            err_headers = {}
            retry_after = resp.headers.get("Retry-After")
            if retry_after:
                err_headers["Retry-After"] = retry_after
            # raft leader hint on not-leader rejections: clients retry
            # against the hinted address before the next failover round
            leader_hint = resp.headers.get("X-Raft-Leader")
            if leader_hint:
                err_headers["X-Raft-Leader"] = leader_hint
            raise RpcError(message, status, addr=addr, route=path,
                           headers=err_headers or None)
        if parse and "application/json" in ctype:
            return json.loads(body) if body else {}
        return body


def call_stream(addr: str, path: str, payload: Optional[dict] = None,
                method: Optional[str] = None, timeout: float = 600.0,
                chunk_size: int = 4 << 20,
                headers: Optional[dict] = None):
    """Like call() but returns an iterator of response-body chunks —
    nothing is buffered beyond one chunk (receiver side of the streaming
    RPCs; urllib decodes chunked transfer-encoding transparently).
    Errors before the first byte raise RpcError like call()."""
    url = f"http://{addr}{path}"
    data = None
    req_headers = _qos.inject(tracing.inject(dict(headers or {})))
    if payload is not None:
        data = json.dumps(payload).encode()
        req_headers["Content-Type"] = "application/json"
    if method is None:
        method = "POST" if data is not None else "GET"
    deadline = current_deadline()
    if deadline is not None and DEADLINE_HEADER not in req_headers:
        remaining = deadline - time.time()
        if remaining <= 0:
            raise RpcError(
                f"deadline exceeded before call to {addr}{path}", 504,
                addr=addr, route=path)
        timeout = min(timeout, remaining)
        req_headers[DEADLINE_HEADER] = f"{deadline:.6f}"
    short_rule = None
    if _faults.ACTIVE:
        try:
            short_rule = _faults.on_rpc("client", addr, path)
        except _faults.FaultInjected as f:
            if f.kind == "reset":
                raise RpcError(
                    f"cannot reach {addr}: injected connection reset",
                    503, addr=addr, route=path, transport=True) \
                    from None
            raise RpcError(str(f), f.status, addr=addr,
                           route=path) from None
    req = urllib.request.Request(url, data=data, method=method,
                                 headers=req_headers)
    try:
        resp = urllib.request.urlopen(req, timeout=timeout)
    except urllib.error.HTTPError as e:
        body = e.read()
        try:
            message = json.loads(body).get("error", body.decode())
        except Exception:
            message = body.decode(errors="replace")
        raise RpcError(message, e.code, addr=addr, route=path) from None
    except (urllib.error.URLError, socket.timeout, ConnectionError) as e:
        raise RpcError(f"cannot reach {addr}: {e}", 503, addr=addr,
                       route=path, transport=True) from None

    try:
        expected = int(resp.headers.get("Content-Length", ""))
    except ValueError:
        expected = -1  # absent or malformed: length unknown, no check

    # an injected short read truncates the body partway: the advertised
    # length check below then fails the stream exactly like a real
    # prematurely-closed transfer
    cut = None
    if short_rule is not None:
        cut = short_rule.nbytes or (
            expected // 2 if expected > 0 else 1)

    def gen():
        got = 0
        try:
            while True:
                try:
                    chunk = resp.read(chunk_size)
                except Exception as e:  # IncompleteRead, socket errors
                    raise RpcError(
                        f"stream from {addr} broke mid-body: {e}", 502,
                        addr=addr, route=path, transport=True)
                if not chunk:
                    break
                got += len(chunk)
                if cut is not None and got >= cut:
                    yield chunk[:max(0, len(chunk) - (got - cut))]
                    raise RpcError(
                        f"stream from {addr} broke mid-body: "
                        f"injected short read [{short_rule.id}]", 502,
                        addr=addr, route=path, transport=True)
                yield chunk
            # a prematurely-closed connection can look like EOF on
            # incremental reads; enforce the advertised length so a
            # truncated transfer NEVER passes as complete
            if 0 <= expected != got:
                raise RpcError(
                    f"truncated stream from {addr}: "
                    f"{got} of {expected} bytes", 502,
                    addr=addr, route=path, transport=True)
        finally:
            resp.close()

    return gen()
