"""AWS Signature V4 verification for the S3 gateway.

Parity with weed/s3api/auth_signature_v4.go (header-based signing and
presigned query auth) and auth_credentials.go's identity model: identities
with access/secret keys and allowed actions.  Anonymous access is allowed
when no identities are configured, mirroring the reference's behaviour
without a config.
"""

from __future__ import annotations

import hashlib
import hmac
import time
import urllib.parse
from dataclasses import dataclass, field
from typing import Optional

ALGORITHM = "AWS4-HMAC-SHA256"
MAX_CLOCK_SKEW_SECONDS = 15 * 60  # AWS allows +/-15 minutes


def _parse_amz_date(amz_date: str) -> float:
    try:
        return time.mktime(time.strptime(amz_date, "%Y%m%dT%H%M%SZ")) \
            - time.timezone
    except ValueError:
        raise AuthError("AccessDenied", f"malformed date {amz_date!r}", 403)

ACTION_READ = "Read"
ACTION_WRITE = "Write"
ACTION_LIST = "List"
ACTION_ADMIN = "Admin"


class AuthError(Exception):
    def __init__(self, code: str, message: str, status: int = 403):
        super().__init__(message)
        self.code = code
        self.status = status


@dataclass
class Identity:
    name: str
    access_key: str
    secret_key: str
    actions: list[str] = field(default_factory=lambda: [ACTION_ADMIN])

    def can(self, action: str, bucket: str = "") -> bool:
        for a in self.actions:
            if a == ACTION_ADMIN:
                return True
            base, _, target = a.partition(":")
            if base != action:
                continue
            if not target or target == bucket:
                return True
        return False


class IdentityAccessManagement:
    def __init__(self, identities: Optional[list[Identity]] = None):
        self.identities = {i.access_key: i for i in (identities or [])}

    @property
    def enabled(self) -> bool:
        return bool(self.identities)

    # -- sigv4 ---------------------------------------------------------------
    def verify(self, method: str, path: str, query: dict, headers,
               body: bytes) -> Optional[Identity]:
        """Verify the request; returns the Identity (None when auth is
        disabled).  Raises AuthError on failure."""
        if not self.enabled:
            return None
        auth_header = headers.get("Authorization", "")
        if auth_header.startswith(ALGORITHM):
            return self._verify_header(method, path, query, headers, body,
                                       auth_header)
        if query.get("X-Amz-Algorithm") == ALGORITHM:
            return self._verify_presigned(method, path, query, headers)
        raise AuthError("AccessDenied", "no valid authentication", 403)

    def _parse_auth_header(self, auth_header: str) -> dict:
        # AWS4-HMAC-SHA256 Credential=AK/date/region/s3/aws4_request,
        #   SignedHeaders=a;b;c, Signature=hex
        parts = auth_header[len(ALGORITHM):].strip().split(",")
        fields = {}
        for part in parts:
            k, _, v = part.strip().partition("=")
            fields[k] = v
        missing = {"Credential", "SignedHeaders", "Signature"} - set(fields)
        if missing:
            raise AuthError("AuthorizationHeaderMalformed",
                            f"missing {missing}", 400)
        return fields

    def _verify_header(self, method, path, query, headers, body,
                       auth_header) -> Identity:
        fields = self._parse_auth_header(auth_header)
        cred_parts = fields["Credential"].split("/")
        if len(cred_parts) != 5:
            raise AuthError("AuthorizationHeaderMalformed",
                            "bad credential scope", 400)
        access_key, datestamp, region, service, terminal = cred_parts
        identity = self.identities.get(access_key)
        if identity is None:
            raise AuthError("InvalidAccessKeyId",
                            f"unknown access key {access_key}", 403)
        signed_headers = fields["SignedHeaders"].split(";")
        amz_date = headers.get("X-Amz-Date", "")
        if abs(time.time() - _parse_amz_date(amz_date)) \
                > MAX_CLOCK_SKEW_SECONDS:
            raise AuthError("RequestTimeTooSkewed",
                            "request time too skewed", 403)
        payload_hash = headers.get("X-Amz-Content-Sha256", "")
        if payload_hash in ("", "UNSIGNED-PAYLOAD"):
            payload_hash = payload_hash or hashlib.sha256(body).hexdigest()
        elif payload_hash.startswith("STREAMING-"):
            pass  # chunked uploads sign the seed; body chunks carry their own
        canonical = self._canonical_request(
            method, path, query, headers, signed_headers, payload_hash)
        scope = f"{datestamp}/{region}/{service}/{terminal}"
        string_to_sign = "\n".join([
            ALGORITHM, amz_date, scope,
            hashlib.sha256(canonical.encode()).hexdigest()])
        signature = self._signature(identity.secret_key, datestamp, region,
                                    service, string_to_sign)
        if not hmac.compare_digest(signature, fields["Signature"]):
            raise AuthError("SignatureDoesNotMatch",
                            "signature mismatch", 403)
        return identity

    def _verify_presigned(self, method, path, query, headers) -> Identity:
        cred = query.get("X-Amz-Credential", "")
        cred_parts = cred.split("/")
        if len(cred_parts) != 5:
            raise AuthError("AuthorizationQueryParametersError",
                            "bad credential", 400)
        access_key, datestamp, region, service, terminal = cred_parts
        identity = self.identities.get(access_key)
        if identity is None:
            raise AuthError("InvalidAccessKeyId",
                            f"unknown access key {access_key}", 403)
        amz_date = query.get("X-Amz-Date", "")
        request_time = _parse_amz_date(amz_date)
        expires = int(query.get("X-Amz-Expires", "604800"))
        if time.time() > request_time + expires:
            raise AuthError("AccessDenied", "request has expired", 403)
        if time.time() + MAX_CLOCK_SKEW_SECONDS < request_time:
            raise AuthError("RequestTimeTooSkewed",
                            "request time too skewed", 403)
        signed_headers = query.get("X-Amz-SignedHeaders", "host").split(";")
        provided = query.get("X-Amz-Signature", "")
        q = {k: v for k, v in query.items() if k != "X-Amz-Signature"}
        canonical = self._canonical_request(
            method, path, q, headers, signed_headers, "UNSIGNED-PAYLOAD")
        scope = f"{datestamp}/{region}/{service}/{terminal}"
        string_to_sign = "\n".join([
            ALGORITHM, amz_date, scope,
            hashlib.sha256(canonical.encode()).hexdigest()])
        signature = self._signature(identity.secret_key, datestamp, region,
                                    service, string_to_sign)
        if not hmac.compare_digest(signature, provided):
            raise AuthError("SignatureDoesNotMatch",
                            "signature mismatch", 403)
        return identity

    @staticmethod
    def _canonical_request(method, path, query, headers, signed_headers,
                           payload_hash) -> str:
        canonical_uri = urllib.parse.quote(path, safe="/~")
        q_pairs = sorted(
            (urllib.parse.quote(k, safe="~"),
             urllib.parse.quote(str(v), safe="~"))
            for k, v in query.items())
        canonical_query = "&".join(f"{k}={v}" for k, v in q_pairs)
        header_lines = []
        for name in signed_headers:
            value = headers.get(name) or ""
            header_lines.append(f"{name}:{' '.join(value.split())}")
        return "\n".join([
            method, canonical_uri, canonical_query,
            "\n".join(header_lines) + "\n",
            ";".join(signed_headers), payload_hash])

    @staticmethod
    def _signature(secret, datestamp, region, service,
                   string_to_sign) -> str:
        def h(key, msg):
            return hmac.new(key, msg.encode(), hashlib.sha256).digest()

        k_date = h(("AWS4" + secret).encode(), datestamp)
        k_region = h(k_date, region)
        k_service = h(k_region, service)
        k_signing = h(k_service, "aws4_request")
        return hmac.new(k_signing, string_to_sign.encode(),
                        hashlib.sha256).hexdigest()
