"""AWS Signature V2/V4 verification for the S3 gateway.

Parity with weed/s3api/auth_signature_v4.go (header-based signing and
presigned query auth), auth_signature_v2.go (legacy HMAC-SHA1 scheme),
policy/post-policy validation (s3api_object_handlers_postpolicy.go), and
auth_credentials.go's identity model: identities with access/secret keys
and allowed actions.  Anonymous access is allowed when no identities are
configured, mirroring the reference's behaviour without a config.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import json
import threading
import time
import urllib.parse
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Optional

ALGORITHM = "AWS4-HMAC-SHA256"
CHUNK_ALGORITHM = "AWS4-HMAC-SHA256-PAYLOAD"
TRAILER_ALGORITHM = "AWS4-HMAC-SHA256-TRAILER"
# payload sentinels for sigv4 streaming uploads (auth_signature_v4.go:50-53;
# the -TRAILER forms are sent by SDKs with flexible checksums enabled)
STREAMING_PAYLOAD = "STREAMING-AWS4-HMAC-SHA256-PAYLOAD"
STREAMING_PAYLOAD_TRAILER = "STREAMING-AWS4-HMAC-SHA256-PAYLOAD-TRAILER"
STREAMING_UNSIGNED_TRAILER = "STREAMING-UNSIGNED-PAYLOAD-TRAILER"
SIGNED_STREAMING = (STREAMING_PAYLOAD, STREAMING_PAYLOAD_TRAILER)
ALL_STREAMING = SIGNED_STREAMING + (STREAMING_UNSIGNED_TRAILER,)
EMPTY_SHA256 = hashlib.sha256(b"").hexdigest()
MAX_CLOCK_SKEW_SECONDS = 15 * 60  # AWS allows +/-15 minutes

# sub-resources included in the V2 canonicalized resource
# (auth_signature_v2.go resourceList)
V2_SUBRESOURCES = {
    "acl", "delete", "lifecycle", "location", "logging", "notification",
    "partNumber", "policy", "requestPayment", "response-cache-control",
    "response-content-disposition", "response-content-encoding",
    "response-content-language", "response-content-type", "response-expires",
    "tagging", "torrent", "uploadId", "uploads", "versionId", "versioning",
    "versions", "website",
}


def _parse_amz_date(amz_date: str) -> float:
    try:
        return time.mktime(time.strptime(amz_date, "%Y%m%dT%H%M%SZ")) \
            - time.timezone
    except ValueError:
        raise AuthError("AccessDenied", f"malformed date {amz_date!r}", 403)

ACTION_READ = "Read"
ACTION_WRITE = "Write"
ACTION_LIST = "List"
ACTION_ADMIN = "Admin"


class AuthError(Exception):
    def __init__(self, code: str, message: str, status: int = 403):
        super().__init__(message)
        self.code = code
        self.status = status


@dataclass
class Identity:
    name: str
    access_key: str
    secret_key: str
    actions: list[str] = field(default_factory=lambda: [ACTION_ADMIN])

    def can(self, action: str, bucket: str = "") -> bool:
        for a in self.actions:
            if a == ACTION_ADMIN:
                return True
            base, _, target = a.partition(":")
            if base != action:
                continue
            if not target or target == bucket:
                return True
        return False


class IdentityAccessManagement:
    def __init__(self, identities: Optional[list[Identity]] = None):
        self.identities = {i.access_key: i for i in (identities or [])}

    @property
    def enabled(self) -> bool:
        return bool(self.identities)

    # -- sigv4 ---------------------------------------------------------------
    def verify(self, method: str, path: str, query: dict, headers,
               body: bytes) -> Optional[Identity]:
        """Verify the request; returns the Identity (None when auth is
        disabled).  Raises AuthError on failure."""
        if not self.enabled:
            return None
        auth_header = headers.get("Authorization", "")
        if auth_header.startswith(ALGORITHM):
            return self._verify_header(method, path, query, headers, body,
                                       auth_header)[0]
        if query.get("X-Amz-Algorithm") == ALGORITHM:
            return self._verify_presigned(method, path, query, headers)
        if auth_header.startswith("AWS "):
            return self._verify_v2_header(method, path, query, headers,
                                          auth_header)
        if "Signature" in query and "AWSAccessKeyId" in query:
            return self._verify_v2_presigned(method, path, query, headers)
        raise AuthError("AccessDenied", "no valid authentication", 403)

    def verify_and_decode(self, method: str, path: str, query: dict,
                          headers, body: bytes):
        """verify() plus streaming-upload handling: when the request is a
        sigv4 streaming upload (x-amz-content-sha256 ==
        STREAMING-AWS4-HMAC-SHA256-PAYLOAD, chunked_reader_v4.go), each
        aws-chunked frame's signature is verified against the seed
        signature chain and the decoded payload is returned.

        Returns (identity, body) where body is the decoded payload for
        streaming requests and the original bytes otherwise."""
        sentinel = headers.get("X-Amz-Content-Sha256", "")
        if not self.enabled:
            # no identities configured: SDKs still send aws-chunked framed
            # bodies — strip the framing (unverifiable without a secret);
            # a declared-but-missing trailer is still a truncation
            if sentinel in ALL_STREAMING:
                body = self._check_decoded_length(
                    headers, self._decode_streaming_body(
                        body,
                        declared_trailer=headers.get("X-Amz-Trailer", "")))
            return None, body
        auth_header = headers.get("Authorization", "")
        if not auth_header.startswith(ALGORITHM):
            # signed streaming requires header auth (AWS rejects it on
            # presigned/sigv2 requests): without the seed-signature chain
            # the chunk signatures are unverifiable, and silently
            # stripping them would advertise integrity we never checked
            if sentinel in SIGNED_STREAMING:
                raise AuthError(
                    "AccessDenied",
                    "signed streaming uploads require AWS4-HMAC-SHA256 "
                    "header authentication", 403)
            # presigned-v4 / sigv2 auth with the UNSIGNED trailer form:
            # SDK flexible-checksum mode frames the body — strip it
            identity = self.verify(method, path, query, headers, body)
            if sentinel in ALL_STREAMING:
                body = self._check_decoded_length(
                    headers, self._decode_streaming_body(
                        body,
                        declared_trailer=headers.get("X-Amz-Trailer", "")))
            return identity, body
        identity, seed, fields = self._verify_header(
            method, path, query, headers, body, auth_header)
        if sentinel not in ALL_STREAMING:
            return identity, body
        if sentinel in SIGNED_STREAMING:
            _, datestamp, region, service, _ = \
                fields["Credential"].split("/")
            scope = f"{datestamp}/{region}/{service}/aws4_request"
            key = self._signing_key(identity.secret_key, datestamp, region,
                                    service)
            decoded = self._decode_streaming_body(
                body, key, seed, headers.get("X-Amz-Date", ""), scope,
                allow_unsigned_final=(sentinel == STREAMING_PAYLOAD_TRAILER),
                declared_trailer=headers.get("X-Amz-Trailer", ""))
        else:  # STREAMING-UNSIGNED-PAYLOAD-TRAILER: frames carry no sigs
            decoded = self._decode_streaming_body(
                body, declared_trailer=headers.get("X-Amz-Trailer", ""))
        return identity, self._check_decoded_length(headers, decoded)

    @staticmethod
    def _check_decoded_length(headers, decoded: bytes) -> bytes:
        declared = headers.get("X-Amz-Decoded-Content-Length")
        if declared is None:
            # AWS mandates the header for aws-chunked uploads; without it
            # a truncation at a chunk boundary would be undetectable
            raise AuthError("MissingContentLength",
                            "streaming upload requires "
                            "x-amz-decoded-content-length", 411)
        try:
            expect = int(declared)
        except ValueError:
            raise AuthError("InvalidRequest",
                            "malformed x-amz-decoded-content-length", 400)
        if expect != len(decoded):
            raise AuthError("IncompleteBody",
                            "decoded length does not match "
                            "x-amz-decoded-content-length", 400)
        return decoded

    @staticmethod
    def _decode_streaming_body(body: bytes, signing_key: bytes = None,
                               seed_signature: str = "", amz_date: str = "",
                               scope: str = "",
                               allow_unsigned_final: bool = False,
                               declared_trailer: str = "") -> bytes:
        """Decode `<hex-size>[;chunk-signature=<sig>]\\r\\n<data>\\r\\n`
        frames.  With a signing_key, each chunk signature is verified
        against the running chain (sigv4-streaming spec;
        chunked_reader_v4.go getChunkSignature).  Trailer headers after
        the final zero-length frame are parsed: every name announced in
        x-amz-trailer must be present, and for the signed -TRAILER form
        the x-amz-trailer-signature is verified over the canonical
        trailer block (AWS4-HMAC-SHA256-TRAILER string-to-sign)."""
        verify_sigs = signing_key is not None
        out = bytearray()
        prev_sig = seed_signature
        pos = 0
        saw_final = False
        trailer_raw = b""
        while pos < len(body):
            eol = body.find(b"\r\n", pos)
            if eol < 0:
                raise AuthError("IncompleteBody",
                                "malformed chunk header", 400)
            header = body[pos:eol].decode("ascii", "replace")
            size_hex, _, ext = header.partition(";")
            try:
                size = int(size_hex, 16)
            except ValueError:
                raise AuthError("IncompleteBody",
                                f"bad chunk size {size_hex!r}", 400)
            chunk_sig = ""
            for token in ext.split(";"):
                k, _, v = token.partition("=")
                if k.strip() == "chunk-signature":
                    chunk_sig = v.strip()
            data = body[eol + 2:eol + 2 + size]
            if len(data) != size:
                raise AuthError("IncompleteBody", "truncated chunk", 400)
            pos = eol + 2 + size
            if body[pos:pos + 2] == b"\r\n":
                pos += 2
            elif size > 0:
                raise AuthError("IncompleteBody",
                                "missing chunk trailer", 400)
            if verify_sigs and not (size == 0 and not chunk_sig
                                    and allow_unsigned_final):
                string_to_sign = "\n".join([
                    CHUNK_ALGORITHM, amz_date, scope, prev_sig,
                    EMPTY_SHA256, hashlib.sha256(data).hexdigest()])
                expected = hmac.new(signing_key, string_to_sign.encode(),
                                    hashlib.sha256).hexdigest()
                if not hmac.compare_digest(expected, chunk_sig):
                    raise AuthError("SignatureDoesNotMatch",
                                    "chunk signature mismatch", 403)
                prev_sig = expected
            if size == 0:
                saw_final = True
                trailer_raw = body[pos:]
                break
            out += data
        if not saw_final:
            raise AuthError("IncompleteBody", "missing final chunk", 400)
        IdentityAccessManagement._check_trailer(
            trailer_raw, declared_trailer,
            signing_key if (verify_sigs and allow_unsigned_final) else None,
            prev_sig, amz_date, scope)
        return bytes(out)

    @staticmethod
    def _check_trailer(trailer_raw: bytes, declared: str,
                       signing_key, prev_sig: str, amz_date: str,
                       scope: str) -> None:
        """Validate the trailing-header block of an aws-chunked body.

        Every name announced in x-amz-trailer must appear (a dropped
        trailer checksum is a truncation, not a no-op), and when
        `signing_key` is set (the STREAMING-...-PAYLOAD-TRAILER form) the
        x-amz-trailer-signature must verify over the canonical
        `name:value\\n` block chained onto the last chunk signature."""
        entries: dict[str, str] = {}
        trailer_sig = ""
        canonical = []
        for line in trailer_raw.split(b"\r\n"):
            if not line:
                continue
            name, sep, value = line.decode("utf8", "replace").partition(":")
            if not sep:
                raise AuthError("IncompleteBody",
                                "malformed trailer header", 400)
            name = name.strip().lower()
            value = value.strip()
            if name == "x-amz-trailer-signature":
                trailer_sig = value
                continue
            entries[name] = value
            canonical.append(f"{name}:{value}\n")
        for want in declared.split(","):
            want = want.strip().lower()
            if want and want not in entries:
                raise AuthError("IncompleteBody",
                                f"missing declared trailer {want}", 400)
        if signing_key is not None:
            if not trailer_sig:
                raise AuthError("SignatureDoesNotMatch",
                                "missing x-amz-trailer-signature", 403)
            string_to_sign = "\n".join([
                TRAILER_ALGORITHM, amz_date, scope, prev_sig,
                hashlib.sha256("".join(canonical).encode()).hexdigest()])
            expected = hmac.new(signing_key, string_to_sign.encode(),
                                hashlib.sha256).hexdigest()
            if not hmac.compare_digest(expected, trailer_sig):
                raise AuthError("SignatureDoesNotMatch",
                                "trailer signature mismatch", 403)

    def _parse_auth_header(self, auth_header: str) -> dict:
        # AWS4-HMAC-SHA256 Credential=AK/date/region/s3/aws4_request,
        #   SignedHeaders=a;b;c, Signature=hex
        parts = auth_header[len(ALGORITHM):].strip().split(",")
        fields = {}
        for part in parts:
            k, _, v = part.strip().partition("=")
            fields[k] = v
        missing = {"Credential", "SignedHeaders", "Signature"} - set(fields)
        if missing:
            raise AuthError("AuthorizationHeaderMalformed",
                            f"missing {missing}", 400)
        return fields

    def _verify_header(self, method, path, query, headers, body,
                       auth_header) -> tuple[Identity, str, dict]:
        fields = self._parse_auth_header(auth_header)
        cred_parts = fields["Credential"].split("/")
        if len(cred_parts) != 5:
            raise AuthError("AuthorizationHeaderMalformed",
                            "bad credential scope", 400)
        access_key, datestamp, region, service, terminal = cred_parts
        identity = self.identities.get(access_key)
        if identity is None:
            raise AuthError("InvalidAccessKeyId",
                            f"unknown access key {access_key}", 403)
        signed_headers = fields["SignedHeaders"].split(";")
        amz_date = headers.get("X-Amz-Date", "")
        if abs(time.time() - _parse_amz_date(amz_date)) \
                > MAX_CLOCK_SKEW_SECONDS:
            raise AuthError("RequestTimeTooSkewed",
                            "request time too skewed", 403)
        payload_hash = headers.get("X-Amz-Content-Sha256", "")
        if payload_hash in ("", "UNSIGNED-PAYLOAD"):
            payload_hash = payload_hash or hashlib.sha256(body).hexdigest()
        elif payload_hash.startswith("STREAMING-"):
            pass  # chunked uploads sign the seed; body chunks carry their own
        else:
            # an explicit hex digest must bind the actual body, or the
            # signature doesn't cover the payload at all
            if not hmac.compare_digest(payload_hash,
                                       hashlib.sha256(body).hexdigest()):
                raise AuthError("XAmzContentSHA256Mismatch",
                                "x-amz-content-sha256 does not match the "
                                "request payload", 400)
        canonical = self._canonical_request(
            method, path, query, headers, signed_headers, payload_hash)
        scope = f"{datestamp}/{region}/{service}/{terminal}"
        string_to_sign = "\n".join([
            ALGORITHM, amz_date, scope,
            hashlib.sha256(canonical.encode()).hexdigest()])
        signature = self._signature(identity.secret_key, datestamp, region,
                                    service, string_to_sign)
        if not hmac.compare_digest(signature, fields["Signature"]):
            raise AuthError("SignatureDoesNotMatch",
                            "signature mismatch", 403)
        return identity, signature, fields

    def _verify_presigned(self, method, path, query, headers) -> Identity:
        cred = query.get("X-Amz-Credential", "")
        cred_parts = cred.split("/")
        if len(cred_parts) != 5:
            raise AuthError("AuthorizationQueryParametersError",
                            "bad credential", 400)
        access_key, datestamp, region, service, terminal = cred_parts
        identity = self.identities.get(access_key)
        if identity is None:
            raise AuthError("InvalidAccessKeyId",
                            f"unknown access key {access_key}", 403)
        amz_date = query.get("X-Amz-Date", "")
        request_time = _parse_amz_date(amz_date)
        expires = int(query.get("X-Amz-Expires", "604800"))
        if time.time() > request_time + expires:
            raise AuthError("AccessDenied", "request has expired", 403)
        if time.time() + MAX_CLOCK_SKEW_SECONDS < request_time:
            raise AuthError("RequestTimeTooSkewed",
                            "request time too skewed", 403)
        signed_headers = query.get("X-Amz-SignedHeaders", "host").split(";")
        provided = query.get("X-Amz-Signature", "")
        q = {k: v for k, v in query.items() if k != "X-Amz-Signature"}
        canonical = self._canonical_request(
            method, path, q, headers, signed_headers, "UNSIGNED-PAYLOAD")
        scope = f"{datestamp}/{region}/{service}/{terminal}"
        string_to_sign = "\n".join([
            ALGORITHM, amz_date, scope,
            hashlib.sha256(canonical.encode()).hexdigest()])
        signature = self._signature(identity.secret_key, datestamp, region,
                                    service, string_to_sign)
        if not hmac.compare_digest(signature, provided):
            raise AuthError("SignatureDoesNotMatch",
                            "signature mismatch", 403)
        return identity

    # -- sigv2 (auth_signature_v2.go) ----------------------------------------
    def _v2_string_to_sign(self, method, path, query, headers,
                           date_value: str) -> str:
        amz_headers = sorted(
            (k.lower(), " ".join(str(v).split()))
            for k, v in headers.items()
            if k.lower().startswith("x-amz-"))
        canonical_amz = "".join(f"{k}:{v}\n" for k, v in amz_headers)
        resource = urllib.parse.quote(path, safe="/~")
        subs = sorted(k for k in query if k in V2_SUBRESOURCES)
        if subs:
            pairs = []
            for k in subs:
                v = query[k]
                pairs.append(f"{k}={v}" if v not in ("", None) else k)
            resource += "?" + "&".join(pairs)
        return "\n".join([
            method,
            headers.get("Content-Md5", "") or headers.get("Content-MD5", ""),
            headers.get("Content-Type", "") or "",
            date_value,
            canonical_amz + resource])

    @staticmethod
    def _v2_signature(secret: str, string_to_sign: str) -> str:
        return base64.b64encode(
            hmac.new(secret.encode(), string_to_sign.encode(),
                     hashlib.sha1).digest()).decode()

    def _verify_v2_header(self, method, path, query, headers,
                          auth_header) -> Identity:
        try:
            access_key, provided = auth_header[4:].strip().split(":", 1)
        except ValueError:
            raise AuthError("AuthorizationHeaderMalformed",
                            "bad v2 authorization header", 400)
        identity = self.identities.get(access_key)
        if identity is None:
            raise AuthError("InvalidAccessKeyId",
                            f"unknown access key {access_key}", 403)
        # x-amz-date supersedes Date in the string-to-sign (v2 spec)
        date_value = "" if headers.get("X-Amz-Date") \
            else (headers.get("Date", "") or "")
        string_to_sign = self._v2_string_to_sign(method, path, query,
                                                 headers, date_value)
        expected = self._v2_signature(identity.secret_key, string_to_sign)
        if not hmac.compare_digest(expected, provided):
            raise AuthError("SignatureDoesNotMatch",
                            "v2 signature mismatch", 403)
        return identity

    def _verify_v2_presigned(self, method, path, query, headers) -> Identity:
        access_key = query.get("AWSAccessKeyId", "")
        identity = self.identities.get(access_key)
        if identity is None:
            raise AuthError("InvalidAccessKeyId",
                            f"unknown access key {access_key}", 403)
        expires = query.get("Expires", "0")
        try:
            if time.time() > int(expires):
                raise AuthError("AccessDenied", "request has expired", 403)
        except ValueError:
            raise AuthError("AccessDenied", "malformed Expires", 403)
        string_to_sign = self._v2_string_to_sign(method, path, query,
                                                 headers, expires)
        expected = self._v2_signature(identity.secret_key, string_to_sign)
        if not hmac.compare_digest(expected, query.get("Signature", "")):
            raise AuthError("SignatureDoesNotMatch",
                            "v2 signature mismatch", 403)
        return identity

    # -- POST policy (policy/post-policy, s3api postpolicy handlers) ---------
    def verify_post_policy(self, form: dict[str, str]) -> Identity:
        """Validate a browser-POST upload: signature over the base64 policy
        document, policy expiration, and its conditions against the form
        fields.  Returns the signing identity."""
        policy_b64 = form.get("policy", "")
        if not policy_b64:
            if not self.enabled:
                return None  # anonymous post without a policy
            raise AuthError("AccessDenied", "missing policy", 403)
        if "x-amz-signature" in form:  # v4-signed policy
            cred_parts = form.get("x-amz-credential", "").split("/")
            if len(cred_parts) != 5:
                raise AuthError("AuthorizationQueryParametersError",
                                "bad credential", 400)
            access_key, datestamp, region, service, _ = cred_parts
            identity = self.identities.get(access_key)
            if identity is None:
                raise AuthError("InvalidAccessKeyId",
                                f"unknown access key {access_key}", 403)
            expected = self._signature(identity.secret_key, datestamp,
                                       region, service, policy_b64)
            if not hmac.compare_digest(expected,
                                       form.get("x-amz-signature", "")):
                raise AuthError("SignatureDoesNotMatch",
                                "policy signature mismatch", 403)
        elif "signature" in form:  # v2-signed policy
            access_key = form.get("awsaccesskeyid", "")  # form keys lowered
            identity = self.identities.get(access_key)
            if identity is None:
                raise AuthError("InvalidAccessKeyId",
                                f"unknown access key {access_key}", 403)
            expected = self._v2_signature(identity.secret_key, policy_b64)
            if not hmac.compare_digest(expected, form.get("signature", "")):
                raise AuthError("SignatureDoesNotMatch",
                                "policy signature mismatch", 403)
        else:
            raise AuthError("AccessDenied", "unsigned policy", 403)
        self._check_policy_conditions(policy_b64, form)
        return identity

    @staticmethod
    def _check_policy_conditions(policy_b64: str, form: dict[str, str]):
        try:
            policy = json.loads(base64.b64decode(policy_b64))
        except (ValueError, TypeError):
            raise AuthError("InvalidPolicyDocument", "unparsable policy",
                            400)
        expiration = policy.get("expiration", "")
        try:
            exp_ts = time.mktime(time.strptime(
                expiration.split(".")[0].rstrip("Z"),
                "%Y-%m-%dT%H:%M:%S")) - time.timezone
        except ValueError:
            raise AuthError("InvalidPolicyDocument", "bad expiration", 400)
        if time.time() > exp_ts:
            raise AuthError("AccessDenied", "policy expired", 403)
        size = len(form.get("__file_bytes__", b""))
        for cond in policy.get("conditions", []):
            if isinstance(cond, dict):
                for k, v in cond.items():
                    if k.lower().startswith("x-ignore-"):
                        continue
                    have = form.get(k.lower(), form.get(k, ""))
                    if str(have) != str(v):
                        raise AuthError(
                            "AccessDenied",
                            f"policy condition failed: {k}", 403)
            elif isinstance(cond, list) and len(cond) == 3:
                op, name, value = cond[0], cond[1], cond[2]
                if op == "content-length-range":
                    try:
                        lo, hi = int(name), int(value)
                    except (TypeError, ValueError):
                        raise AuthError("InvalidPolicyDocument",
                                        "bad content-length-range", 400)
                    if not (lo <= size <= hi):
                        raise AuthError("EntityTooLarge" if size > hi
                                        else "EntityTooSmall",
                                        "content length out of range", 400)
                    continue
                name = str(name).lstrip("$").lower()
                if op == "eq":
                    if str(form.get(name, "")) != str(value):
                        raise AuthError("AccessDenied",
                                        f"eq condition failed: {name}", 403)
                elif op == "starts-with":
                    if not str(form.get(name, "")).startswith(str(value)):
                        raise AuthError(
                            "AccessDenied",
                            f"starts-with condition failed: {name}", 403)
                # unknown operators are ignored, like the reference

    @staticmethod
    def _canonical_request(method, path, query, headers, signed_headers,
                           payload_hash) -> str:
        canonical_uri = urllib.parse.quote(path, safe="/~")
        q_pairs = sorted(
            (urllib.parse.quote(k, safe="~"),
             urllib.parse.quote(str(v), safe="~"))
            for k, v in query.items())
        canonical_query = "&".join(f"{k}={v}" for k, v in q_pairs)
        header_lines = []
        for name in signed_headers:
            value = headers.get(name) or ""
            header_lines.append(f"{name}:{' '.join(value.split())}")
        return "\n".join([
            method, canonical_uri, canonical_query,
            "\n".join(header_lines) + "\n",
            ";".join(signed_headers), payload_hash])

    # derived-key memo: the 4-chained-HMAC key derivation depends only
    # on (secret, datestamp, region, service) — constant for a client
    # all day — so every request after the first skips it.  Keyed by the
    # full tuple, an evicted/rotated secret simply misses.
    _key_cache_lock = threading.Lock()
    _key_cache: "OrderedDict[tuple, bytes]" = OrderedDict()
    _KEY_CACHE_MAX = 512

    @classmethod
    def _signing_key(cls, secret, datestamp, region, service) -> bytes:
        from ..stats.metrics import S3SigV4KeyCacheCounter

        ck = (secret, datestamp, region, service)
        with cls._key_cache_lock:
            cached = cls._key_cache.get(ck)
            if cached is not None:
                cls._key_cache.move_to_end(ck)
        S3SigV4KeyCacheCounter.labels(
            "hit" if cached is not None else "miss").inc()
        if cached is not None:
            return cached

        def h(key, msg):
            return hmac.new(key, msg.encode(), hashlib.sha256).digest()

        k_date = h(("AWS4" + secret).encode(), datestamp)
        k_region = h(k_date, region)
        k_service = h(k_region, service)
        k_signing = h(k_service, "aws4_request")
        with cls._key_cache_lock:
            cls._key_cache[ck] = k_signing
            while len(cls._key_cache) > cls._KEY_CACHE_MAX:
                cls._key_cache.popitem(last=False)
        return k_signing

    @classmethod
    def _signature(cls, secret, datestamp, region, service,
                   string_to_sign) -> str:
        k_signing = cls._signing_key(secret, datestamp, region, service)
        return hmac.new(k_signing, string_to_sign.encode(),
                        hashlib.sha256).hexdigest()
