"""S3-compatible gateway over the filer.

Parity with weed/s3api/s3api_server.go's route table: bucket CRUD +
listing (v1/v2), object CRUD with Range/metadata/tagging, CopyObject,
multi-delete, and multipart uploads, with AWS SigV4 auth (auth.py) and
XML wire format.  Buckets live under /buckets/<name> in the filer
namespace, like the reference's filer integration (filer_multipart.go,
s3api_objects_*.go); multipart parts are staged under
/buckets/<b>/.uploads/<uploadId>/ and composed by chunk-list rebasing.
"""

from __future__ import annotations

import hashlib
import json
import threading
import time
import urllib.parse
import uuid
import xml.etree.ElementTree as ET
from typing import Optional

from ..filer.entry import Entry, FileChunk, new_directory_entry
from ..filer.filechunk_manifest import (has_chunk_manifest,
                                        resolve_chunk_manifest)
from ..filer.filer_store import NotFoundError
from ..filer.server import FilerServer
from .. import profiling, qos, tracing
from ..rpc.http_rpc import Request, Response, RpcError, RpcServer
from ..stats import access
from ..stats import events as events_mod
from ..stats import healthz
from ..stats import metrics as stats
from ..util import faults
from .auth import (ACTION_ADMIN, ACTION_LIST, ACTION_READ, ACTION_WRITE,
                   AuthError, Identity, IdentityAccessManagement)
from .circuit_breaker import CircuitBreaker, SlowDown
from .circuit_breaker import read_config as cb_read_config

BUCKETS_ROOT = "/buckets"
UPLOADS_DIR = ".uploads"


def parse_multipart_form(content_type: str, body: bytes) -> dict:
    """Minimal multipart/form-data parser for browser POST uploads.
    Returns field name -> str value, plus '__file_bytes__' (bytes) and
    '__file_name__' for the file part."""
    if "boundary=" not in content_type:
        raise RpcError("missing multipart boundary", 400)
    boundary = content_type.split("boundary=", 1)[1].split(";")[0].strip()
    boundary = boundary.strip('"')
    form: dict = {}
    delim = b"--" + boundary.encode()
    for part in body.split(delim):
        # each part is wrapped in exactly one CRLF on each side; strip only
        # those delimiters — trailing \r\n bytes may belong to the payload
        if part.startswith(b"\r\n"):
            part = part[2:]
        if part.endswith(b"\r\n"):
            part = part[:-2]
        if not part or part in (b"--", b"--\r\n"):
            continue
        head, _, payload = part.partition(b"\r\n\r\n")
        disposition = ""
        for line in head.decode("utf-8", "replace").splitlines():
            if line.lower().startswith("content-disposition:"):
                disposition = line
        name = ""
        filename = None
        for item in disposition.split(";"):
            item = item.strip()
            if item.startswith("name="):
                name = item[5:].strip('"')
            elif item.startswith("filename="):
                filename = item[9:].strip('"')
        if not name:
            continue
        if name == "file" or filename is not None:
            form["__file_bytes__"] = payload
            form["__file_name__"] = filename or ""
        else:
            form[name.lower()] = payload.decode("utf-8", "replace")
    return form


def _xml(tag: str, children) -> bytes:
    root = ET.Element(tag,
                      xmlns="http://s3.amazonaws.com/doc/2006-03-01/")
    _build(root, children)
    return (b'<?xml version="1.0" encoding="UTF-8"?>'
            + ET.tostring(root))


def _build(parent, children):
    if isinstance(children, dict):
        for k, v in children.items():
            if isinstance(v, list):
                for item in v:
                    node = ET.SubElement(parent, k)
                    _build(node, item)
            else:
                node = ET.SubElement(parent, k)
                _build(node, v)
    else:
        parent.text = "" if children is None else str(children)


def _error_xml(code: str, message: str, status: int,
               headers: Optional[dict] = None) -> Response:
    root = ET.Element("Error")
    ET.SubElement(root, "Code").text = code
    ET.SubElement(root, "Message").text = message
    resp = Response(ET.tostring(root), status, "application/xml")
    if headers:
        resp.headers.update(headers)
    return resp


class S3ApiServer:
    def __init__(self, filer: FilerServer, host: str = "127.0.0.1",
                 port: int = 0,
                 identities: Optional[list[Identity]] = None,
                 circuit_breaker: Optional[CircuitBreaker] = None):
        self.filer_server = filer
        self.filer = filer.filer
        self.iam = IdentityAccessManagement(identities)
        # filer-backed circuit breaker hot-reloads (the reference
        # subscribes to /etc/s3/circuit_breaker.json metadata changes;
        # here a 1 s TTL re-read, like the filer-conf cache)
        self._cb_from_filer = circuit_breaker is None
        self.circuit_breaker = circuit_breaker \
            or CircuitBreaker.load_from_filer(self.filer_server)
        self._cb_checked = time.time()
        self.server = RpcServer(host, port, service_name="s3")
        # shadow two reserved names in the bucket namespace, like the
        # filer's /metadata//remote//kv mounts shadow user paths
        self.server.add("GET", "/metrics", stats.metrics_handler)
        self.server.add("GET", "/debug/traces", tracing.traces_handler)
        faults.mount(self.server)
        profiling.mount(self.server)
        # weighted-fair front-end admission; the S3 access key is the
        # tenant key (WEED_QOS_S3_LIMIT; 0 = classify/count only)
        self.qos_gate = qos.AdmissionGate("s3",
                                          limit_env="WEED_QOS_S3_LIMIT")
        # workload analytics sketches for this gateway's object traffic
        self.access_recorder = access.AccessRecorder(node="s3")
        qos.mount(self.server, gate=self.qos_gate)
        events_mod.mount(self.server)
        access.mount(self.server, self.access_recorder)
        healthz.mount_health(self.server, ready=self._ready_checks)
        self.server.default_route = self._handle
        self._stop_event = threading.Event()
        self._register_thread: Optional[threading.Thread] = None

    @property
    def address(self) -> str:
        return self.server.address

    def _ready_checks(self):
        return [("filer", self.filer_server is not None,
                 getattr(self.filer_server, "address", "unknown")
                 if self.filer_server is not None else "no filer"),
                ("master", bool(getattr(self.filer_server,
                                        "master_address", "")),
                 getattr(self.filer_server, "master_address", "")
                 or "unknown"),
                healthz.gate_check(self.qos_gate)]

    def start(self):
        self.server.start()
        # announce in the master's cluster registry as type "s3" (the
        # filer does the same as type "filer") so cluster-wide tooling
        # — weed.py profile, /cluster/nodes?type=s3 — can discover
        # gateways; previously s3 daemons were invisible to discovery
        self._register_thread = threading.Thread(
            target=self._register_loop, daemon=True,
            name="s3-cluster-register")
        self._register_thread.start()

    def stop(self):
        self._stop_event.set()
        self.server.stop()

    def _register_loop(self):
        from ..rpc.http_rpc import RpcError, call

        interval = 5.0
        while not self._stop_event.is_set():
            try:
                r = call(self.filer_server.master_address,
                         "/cluster/register",
                         {"type": "s3", "address": self.address},
                         timeout=10)
                interval = min(5.0, float(r.get("pulse_seconds", 5.0)))
            except (RpcError, OSError):
                pass
            self._stop_event.wait(interval)

    def _maybe_reload_circuit_breaker(self):
        if not self._cb_from_filer or \
                time.time() - self._cb_checked < 1.0:
            return
        self._cb_checked = time.time()
        config = cb_read_config(self.filer_server)
        if config is None:
            return  # transient read failure: keep the current limits
        # load() swaps limits atomically; in-flight gauges survive
        self.circuit_breaker.load(config)

    # -- routing -------------------------------------------------------------
    def _handle(self, method: str, req: Request):
        parts = req.path.lstrip("/").split("/", 1)
        # bounded action label: bucket ops vs object ops by method
        action = ("%s_%s" % (method, "object" if len(parts) > 1 and
                             parts[1] else "bucket")).lower()
        with stats.S3RequestHistogram.labels(action).time():
            try:
                self._maybe_reload_circuit_breaker()
                resp = self._route(method, req)
            except AuthError as e:
                resp = _error_xml(e.code, str(e), e.status)
            except SlowDown as e:
                # retryable shed: tell SDK retry layers when to come
                # back — jittered so shed clients don't re-arrive in
                # one synchronized wave
                resp = _error_xml(
                    "SlowDown", str(e), 503,
                    headers={"Retry-After": qos.retry_after(1, 3)})
            except NotFoundError as e:
                resp = _error_xml("NoSuchKey", str(e), 404)
        code = resp.status if isinstance(resp, Response) else 200
        stats.S3RequestCounter.labels(action, code).inc()
        return resp

    def _route(self, method: str, req: Request):
        path = urllib.parse.unquote(req.path)
        parts = path.lstrip("/").split("/", 1)
        bucket = parts[0]
        key = parts[1] if len(parts) > 1 else ""

        content_type = req.headers.get("Content-Type") or ""
        if method == "POST" and bucket and not key \
                and content_type.startswith("multipart/form-data"):
            # browser-based POST policy upload: auth comes from the signed
            # policy document, not the Authorization header
            release = self.circuit_breaker.acquire(
                bucket, "Write", len(req.body or b""))
            try:
                return self._post_policy_upload(bucket, req)
            finally:
                release()

        action = ACTION_READ if method in ("GET", "HEAD") else ACTION_WRITE
        if method == "GET" and not key:
            action = ACTION_LIST
        identity, req.body = self.iam.verify_and_decode(
            method, path, req.query, req.headers, req.body)
        if identity is not None and not identity.can(action, bucket):
            raise AuthError("AccessDenied",
                            f"{action} not allowed on {bucket}", 403)

        qos_release = None
        prev_qos = None
        if qos.enabled():
            # tenant = S3 access key (fall back to the bucket); reads
            # classify interactive, writes standard, both overridable
            # per tenant via WEED_QOS_CLASS_MAP
            tenant = (identity.access_key if identity is not None
                      else bucket)
            cls = qos.INTERACTIVE \
                if action in (ACTION_READ, ACTION_LIST) else qos.STANDARD
            cls = qos.class_for_tenant(tenant, cls)
            try:
                qos_release = self.qos_gate.admit(cls, tenant)
            except RpcError as e:
                raise SlowDown(str(e)) from None
            prev_qos = qos.set_qos(cls, tenant)
        try:
            if prev_qos is not None and method == "PUT" and key \
                    and not qos.QUOTAS.allow(
                        bucket, ops=1, nbytes=len(req.body or b"")):
                raise SlowDown(
                    f"collection {bucket!r} over its byte/ops quota")
            release = self.circuit_breaker.acquire(
                bucket, "Read" if action in (ACTION_READ, ACTION_LIST)
                else "Write", len(req.body or b""))
            try:
                if not bucket:
                    if method == "GET":
                        return self._list_buckets()
                    raise RpcError("bad request", 400)
                if not key:
                    return self._bucket_op(method, bucket, req)
                return self._object_op(method, bucket, key, req)
            finally:
                release()
        finally:
            if prev_qos is not None:
                qos.set_qos(*prev_qos)
            if qos_release is not None:
                qos_release()

    # -- buckets -------------------------------------------------------------
    def _bucket_path(self, bucket: str) -> str:
        return f"{BUCKETS_ROOT}/{bucket}"

    def _list_buckets(self):
        try:
            entries = self.filer.list_directory(BUCKETS_ROOT, limit=10000)
        except NotFoundError:
            entries = []
        return Response(_xml("ListAllMyBucketsResult", {
            "Owner": {"ID": "seaweedfs_tpu"},
            "Buckets": {"Bucket": [
                {"Name": e.name,
                 "CreationDate": _iso(e.attr.crtime)}
                for e in entries if e.is_directory
            ]},
        }), 200, "application/xml")

    @staticmethod
    def _ttl_days(ttl: str) -> int:
        from ..storage.ttl import TTL

        try:
            minutes = TTL.parse(ttl).minutes()
        except ValueError:
            return 0
        # round sub-day TTLs UP: reporting "no lifecycle" for a 12h TTL
        # would claim nothing expires while the store deletes data
        return -(-minutes // (60 * 24)) if minutes else 0

    # -- bucket subresources with canned/conf-backed answers -----------------
    # (s3api_bucket_skip_handlers.go + the acl/location/lifecycle/
    # request-payment handlers in s3api_bucket_handlers.go): SDKs probe
    # these on startup, so graceful answers matter even where the feature
    # doesn't exist
    SUBRESOURCES = ("acl", "cors", "policy", "lifecycle", "location",
                    "versioning", "requestPayment", "object-lock")

    def _bucket_subresource(self, method: str, bucket: str, req: Request):
        q = req.query
        if any(k in q for k in self.SUBRESOURCES):
            self.filer.find_entry(self._bucket_path(bucket))  # NoSuchBucket
        if "object-lock" in q and method == "GET":
            return _error_xml("ObjectLockConfigurationNotFoundError",
                              "no object lock configuration", 404)
        if "acl" in q:
            if method == "GET":
                return self._get_bucket_acl(bucket)
            if method == "PUT":
                # persist the canned ACL (PutBucketAclHandler accepts
                # x-amz-acl canned values; grant XML bodies are not
                # supported, as in the reference — and must NOT be
                # silently swallowed as a reset to private)
                canned = req.headers.get("X-Amz-Acl", "")
                if not canned and req.body:
                    return _error_xml("NotImplemented",
                                      "grant-based ACL bodies are not "
                                      "supported; use x-amz-acl", 501)
                canned = canned or "private"
                if canned not in ("private", "public-read",
                                  "public-read-write",
                                  "authenticated-read"):
                    return _error_xml("InvalidArgument",
                                      f"unsupported ACL {canned}", 400)
                self._set_bucket_config(bucket, "s3-acl", canned)
                return Response(b"", 200)
            return _error_xml("NotImplemented", "acl", 501)
        if "cors" in q:
            if method == "GET":
                stored = self._get_bucket_config(bucket, "s3-cors")
                if not stored:
                    return _error_xml("NoSuchCORSConfiguration",
                                      "no CORS configuration", 404)
                return Response(stored.encode(), 200, "application/xml")
            if method == "DELETE":
                self._set_bucket_config(bucket, "s3-cors", None)
                return Response(b"", 204)
            if method == "PUT":
                try:  # reject malformed XML up front
                    ET.fromstring(req.body)
                except ET.ParseError:
                    return _error_xml("MalformedXML", "bad CORS XML", 400)
                self._set_bucket_config(bucket, "s3-cors",
                                        req.body.decode("utf8", "replace"))
                return Response(b"", 200)
            return _error_xml("NotImplemented", "cors", 501)
        if "policy" in q:
            if method == "GET":
                stored = self._get_bucket_config(bucket, "s3-policy")
                if not stored:
                    return _error_xml("NoSuchBucketPolicy",
                                      "no bucket policy", 404)
                return Response(stored.encode(), 200, "application/json")
            if method == "DELETE":
                self._set_bucket_config(bucket, "s3-policy", None)
                return Response(b"", 204)
            if method == "PUT":
                try:
                    if not req.body:
                        raise ValueError("empty policy")
                    json.loads(req.body)
                except ValueError:
                    return _error_xml("MalformedPolicy",
                                      "policy is not valid JSON", 400)
                self._set_bucket_config(bucket, "s3-policy",
                                        req.body.decode("utf8", "replace"))
                return Response(b"", 204)
            return _error_xml("NotImplemented", "policy", 501)
        if "lifecycle" in q:
            if method == "GET":
                return self._get_bucket_lifecycle(bucket)
            if method == "DELETE":
                return Response(b"", 204)
            return _error_xml("NotImplemented", "lifecycle", 501)
        if "location" in q and method == "GET":
            return Response(_xml("LocationConstraint", ""), 200,
                            "application/xml")
        if "versioning" in q and method == "GET":
            return Response(_xml("VersioningConfiguration", ""), 200,
                            "application/xml")
        if "requestPayment" in q and method == "GET":
            return Response(_xml("RequestPaymentConfiguration",
                                 {"Payer": "BucketOwner"}), 200,
                            "application/xml")
        if any(k in q for k in self.SUBRESOURCES):
            # unhandled method+subresource combo (e.g. PUT ?versioning):
            # never fall through to the plain bucket handlers, which would
            # create/delete the bucket itself under a config request
            return _error_xml("NotImplemented",
                              "subresource not implemented", 501)
        return None

    # -- persisted bucket configs (extended attrs on the bucket entry) -------
    def _set_bucket_config(self, bucket: str, key: str,
                           value: Optional[str]):
        # the read-modify-write of extended must be atomic: concurrent
        # config PUTs (cors vs policy) would otherwise lose updates
        with self.filer.lock:
            entry = self.filer.find_entry(self._bucket_path(bucket))
            entry.extended = dict(entry.extended or {})
            if value is None:
                entry.extended.pop(key, None)
            else:
                entry.extended[key] = value
            self.filer.update_entry(entry)

    def _get_bucket_config(self, bucket: str, key: str) -> Optional[str]:
        entry = self.filer.find_entry(self._bucket_path(bucket))
        value = (entry.extended or {}).get(key)
        return value if isinstance(value, str) else None

    def _get_bucket_acl(self, bucket: str):
        """Canned ACL from the identity table plus the persisted canned
        grant, if any (GetBucketAclHandler)."""
        canned = self._get_bucket_config(bucket, "s3-acl")
        owner = {"ID": "seaweedfs_tpu", "DisplayName": "seaweedfs_tpu"}
        grants = []
        for ident in self.iam.identities.values():
            if ident.can(ACTION_ADMIN, bucket):
                perms = ["FULL_CONTROL"]
                if owner["ID"] == "seaweedfs_tpu":  # first admin is owner
                    owner = {"ID": ident.access_key,
                             "DisplayName": ident.name}
            else:
                perms = []
                if ident.can(ACTION_READ, bucket):
                    perms.append("READ")
                if ident.can(ACTION_WRITE, bucket):
                    perms.append("WRITE")
            for perm in perms:
                grants.append({
                    "Grantee": {"ID": ident.access_key,
                                "DisplayName": ident.name},
                    "Permission": perm})
        if canned and canned.startswith("public-read"):
            grants.append({
                "Grantee": {"URI": "http://acs.amazonaws.com/groups/"
                                   "global/AllUsers"},
                "Permission": "READ"})
            if canned == "public-read-write":
                grants.append({
                    "Grantee": {"URI": "http://acs.amazonaws.com/groups/"
                                       "global/AllUsers"},
                    "Permission": "WRITE"})
        elif canned == "authenticated-read":
            grants.append({
                "Grantee": {"URI": "http://acs.amazonaws.com/groups/"
                                   "global/AuthenticatedUsers"},
                "Permission": "READ"})
        return Response(_xml("AccessControlPolicy", {
            "Owner": owner,
            "AccessControlList": {"Grant": grants},
        }), 200, "application/xml")

    def _get_bucket_lifecycle(self, bucket: str):
        """Expiration rules derived from filer-conf TTLs for the bucket
        (GetBucketLifecycleConfigurationHandler)."""
        conf = self.filer_server.filer_conf()
        bucket_root = f"{BUCKETS_ROOT}/{bucket}"
        rules = []
        for rule in conf.rules:
            # exact bucket path or below it — "/buckets/sr" must not
            # match bucket "s"; and report the BUCKET-RELATIVE key prefix
            if rule.location_prefix != bucket_root and \
                    not rule.location_prefix.startswith(bucket_root + "/"):
                continue
            if not rule.ttl:
                continue
            days = self._ttl_days(rule.ttl)
            if days:
                key_prefix = rule.location_prefix[len(bucket_root):] \
                    .lstrip("/")
                rules.append({
                    "Status": "Enabled",
                    "Filter": {"Prefix": key_prefix},
                    "Expiration": {"Days": days}})
        if not rules:
            return _error_xml("NoSuchLifecycleConfiguration",
                              "no lifecycle configuration", 404)
        return Response(_xml("LifecycleConfiguration", {"Rule": rules}),
                        200, "application/xml")

    def _bucket_op(self, method: str, bucket: str, req: Request):
        path = self._bucket_path(bucket)
        sub = self._bucket_subresource(method, bucket, req)
        if sub is not None:
            return sub
        if method == "PUT":
            self.filer.create_entry(new_directory_entry(path))
            return Response(b"", 200)
        if method == "HEAD":
            entry = self.filer.find_entry(path)  # raises NotFound
            return Response(b"", 200)
        if method == "DELETE":
            try:
                children = [e for e in
                            self.filer.list_directory(path, limit=2)
                            if e.name != UPLOADS_DIR]
                if children:
                    return _error_xml("BucketNotEmpty",
                                      f"{bucket} is not empty", 409)
                self.filer.delete_entry(path, recursive=True)
            except NotFoundError:
                return _error_xml("NoSuchBucket", bucket, 404)
            return Response(b"", 204)
        if method == "GET":
            self.filer.find_entry(path)  # 404 when missing
            if "uploads" in req.query:
                return self._list_multipart_uploads(bucket, req)
            return self._list_objects(bucket, req)
        if method == "POST" and "delete" in req.query:
            return self._multi_delete(bucket, req)
        raise RpcError(f"unsupported bucket op {method}", 405)

    def _post_policy_upload(self, bucket: str, req: Request):
        """Browser POST upload (s3api_object_handlers_postpolicy.go): the
        form carries the key, a signed policy document, and the file."""
        self.filer.find_entry(self._bucket_path(bucket))  # NoSuchBucket
        form = parse_multipart_form(
            req.headers.get("Content-Type") or "", req.body)
        form.setdefault("bucket", bucket)
        identity = self.iam.verify_post_policy(form)
        if identity is not None and not identity.can(ACTION_WRITE, bucket):
            raise AuthError("AccessDenied",
                            f"Write not allowed on {bucket}", 403)
        key = form.get("key", "")
        if not key:
            return _error_xml("InvalidArgument", "missing key field", 400)
        key = key.replace("${filename}", form.get("__file_name__", ""))
        body = form.get("__file_bytes__", b"")
        entry = self.filer_server.save_bytes(
            self._object_path(bucket, key), body,
            mime=form.get("content-type", ""))
        try:
            status = int(form.get("success_action_status", "204"))
        except ValueError:
            status = 204
        if status not in (200, 201, 204):
            status = 204
        if status == 201:
            return Response(_xml("PostResponse", {
                "Bucket": bucket, "Key": key,
                "ETag": f'"{entry.attr.md5}"',
            }), 201, "application/xml")
        return Response(b"", status,
                        headers={"ETag": f'"{entry.attr.md5}"'})

    def _list_multipart_uploads(self, bucket: str, req: Request):
        """GET /bucket?uploads (ListMultipartUploads)."""
        uploads_root = f"{self._bucket_path(bucket)}/{UPLOADS_DIR}"
        try:
            pending = self.filer.list_directory(uploads_root, limit=10000)
        except NotFoundError:
            pending = []
        return Response(_xml("ListMultipartUploadsResult", {
            "Bucket": bucket,
            "Upload": [
                {"Key": u.extended.get("key", ""),
                 "UploadId": u.name,
                 "Initiated": _iso(u.attr.crtime)}
                for u in pending if u.is_directory
            ],
        }), 200, "application/xml")

    # -- object listing ------------------------------------------------------
    def _walk(self, dir_path: str, rel_prefix: str = ""):
        """Yield (key, entry) for all files under dir_path, sorted."""
        for e in self.filer.list_directory(dir_path, limit=100000):
            if e.name == UPLOADS_DIR:
                continue
            rel = rel_prefix + e.name
            if e.is_directory:
                yield from self._walk(e.full_path, rel + "/")
            else:
                yield rel, e

    def _list_objects(self, bucket: str, req: Request):
        prefix = req.param("prefix", "") or ""
        delimiter = req.param("delimiter", "") or ""
        max_keys = int(req.param("max-keys", "1000"))
        v2 = req.param("list-type") == "2"
        marker = (req.param("continuation-token")
                  or req.param("start-after")
                  or req.param("marker") or "")

        contents, common = [], []
        seen_prefixes = set()
        truncated = False
        last_emitted = ""
        for key, entry in self._walk(self._bucket_path(bucket)):
            if prefix and not key.startswith(prefix):
                continue
            if marker and key <= marker:
                continue
            if delimiter:
                rest = key[len(prefix):]
                if delimiter in rest:
                    cp = prefix + rest.split(delimiter)[0] + delimiter
                    if cp in seen_prefixes:
                        continue
                    if len(contents) + len(common) >= max_keys:
                        truncated = True
                        break
                    seen_prefixes.add(cp)
                    common.append(cp)
                    last_emitted = cp
                    continue
            if len(contents) + len(common) >= max_keys:
                truncated = True
                break
            contents.append((key, entry))
            last_emitted = key

        result = {
            "Name": bucket,
            "Prefix": prefix,
            "MaxKeys": max_keys,
            "IsTruncated": str(truncated).lower(),
            "Contents": [
                {"Key": k,
                 "LastModified": _iso(e.attr.mtime),
                 "ETag": f'"{e.attr.md5}"',
                 "Size": e.size(),
                 "StorageClass": "STANDARD"} for k, e in contents
            ],
            "CommonPrefixes": [{"Prefix": p} for p in common],
        }
        if v2:
            # KeyCount counts keys + common prefixes (AWS semantics)
            result["KeyCount"] = len(contents) + len(common)
            if truncated and last_emitted:
                result["NextContinuationToken"] = last_emitted
        else:
            result["Marker"] = marker
        return Response(_xml("ListBucketResult", result), 200,
                        "application/xml")

    # -- objects -------------------------------------------------------------
    def _object_path(self, bucket: str, key: str) -> str:
        return f"{BUCKETS_ROOT}/{bucket}/{key}"

    def _object_op(self, method: str, bucket: str, key: str, req: Request):
        self.filer.find_entry(self._bucket_path(bucket))  # NoSuchBucket
        # object ACL / retention / legal-hold probes
        # (s3api_object_skip_handlers.go) — but only for keys that exist
        if method in ("GET", "PUT") and any(
                k in req.query for k in ("acl", "retention",
                                         "legal-hold")):
            entry = self.filer.find_entry(self._object_path(bucket, key))
            if entry.is_directory:
                raise NotFoundError(key)
            if method == "GET" and "acl" in req.query:
                return self._get_bucket_acl(bucket)  # same canned policy
            return Response(b"", 204)
        if method == "PUT":
            if "partNumber" in req.query and "uploadId" in req.query:
                return self._upload_part(bucket, key, req)
            if req.headers.get("X-Amz-Copy-Source"):
                return self._copy_object(bucket, key, req)
            if "tagging" in req.query:
                return self._put_tagging(bucket, key, req)
            return self._put_object(bucket, key, req)
        if method == "POST":
            if "uploads" in req.query:
                return self._create_multipart(bucket, key, req)
            if "uploadId" in req.query:
                return self._complete_multipart(bucket, key, req)
            raise RpcError("bad POST", 400)
        if method in ("GET", "HEAD"):
            if "uploadId" in req.query:
                return self._list_parts(bucket, key, req)
            if "tagging" in req.query:
                return self._get_tagging(bucket, key)
            return self._get_object(bucket, key, req, method)
        if method == "DELETE":
            if "uploadId" in req.query:
                return self._abort_multipart(bucket, key, req)
            if "tagging" in req.query:
                return self._delete_tagging(bucket, key)
            return self._delete_object(bucket, key)
        raise RpcError(f"unsupported object op {method}", 405)

    def _record_access(self, op: str, bucket: str, key: str, nbytes: int,
                       t0: float):
        """Workload analytics at the S3 door: objects are keyed
        bucket/key here (the volume layer tracks the same access by
        fid); the tenant is whatever sigv4 identity _route attributed
        to the QoS context."""
        self.access_recorder.record(
            op, collection=bucket, tenant=qos.current_tenant(),
            fid=f"{bucket}/{key}", nbytes=nbytes,
            latency_s=time.monotonic() - t0,
            qos_class=qos.current_class())

    def _put_object(self, bucket: str, key: str, req: Request):
        t0 = time.monotonic()
        extended = {f"x-amz-meta-{k[11:].lower()}": v
                    for k, v in req.headers.items()
                    if k.lower().startswith("x-amz-meta-")}
        entry = self.filer_server.save_bytes(
            self._object_path(bucket, key), req.body,
            mime=req.headers.get("Content-Type") or "",
            extended=extended)
        self._record_access("write", bucket, key, len(req.body or b""), t0)
        return Response(b"", 200, headers={"ETag": f'"{entry.attr.md5}"'})

    def _get_object(self, bucket: str, key: str, req: Request, method: str):
        t0 = time.monotonic()
        entry = self.filer.find_entry(self._object_path(bucket, key))
        if entry.is_directory:
            raise NotFoundError(key)
        size = entry.size()
        start, length, status = 0, size, 200
        headers = {"ETag": f'"{entry.attr.md5}"',
                   "Last-Modified": _http_date(entry.attr.mtime),
                   "Accept-Ranges": "bytes"}
        for k, v in entry.extended.items():
            if k.startswith("x-amz-meta-"):
                headers[k] = v
        range_header = req.headers.get("Range")
        if range_header and range_header.startswith("bytes="):
            lo_s, _, hi_s = range_header[6:].split(",")[0].partition("-")
            lo = int(lo_s) if lo_s else None
            hi = int(hi_s) if hi_s else None
            if lo is None:
                start = max(0, size - (hi or 0))
                length = size - start
            else:
                start = lo
                length = (min(hi, size - 1) - lo + 1) if hi is not None \
                    else size - lo
            if start >= size or length <= 0:
                return _error_xml("InvalidRange", "range not satisfiable",
                                  416)
            status = 206
            headers["Content-Range"] = \
                f"bytes {start}-{start + length - 1}/{size}"
        content_type = entry.attr.mime or "application/octet-stream"
        if method == "HEAD":
            headers["Content-Length"] = str(length)
            return Response(b"", status, content_type, headers)
        # record at first-byte time: every reply path below serves
        # exactly `length` payload bytes
        self._record_access("read", bucket, key, length, t0)
        # single-chunk objects resident in the disk cache tier go out
        # zero-copy via sendfile, same as the filer read path
        zero = self.filer_server._sendfile_read(
            entry, start, length, status, content_type, headers)
        if zero is not None:
            return zero
        # multi-chunk objects stream through the filer's bounded-window
        # prefetch pipeline: first byte goes out after one chunk fetch
        # regardless of object size
        streamed = self.filer_server.read_stream(entry, start, length)
        if streamed is not None:
            body_iter, n = streamed
            headers["Content-Length"] = str(n)
            return Response(body_iter, status, content_type, headers)
        # buffered path: zero-copy memoryview parts over cached chunk
        # bytes, written straight into the socket send
        parts, n = self.filer_server.read_view(entry, start, length)
        headers["Content-Length"] = str(n)
        body = parts[0] if len(parts) == 1 else iter(parts)
        return Response(body, status, content_type, headers)

    def _delete_object(self, bucket: str, key: str):
        t0 = time.monotonic()
        try:
            self.filer.delete_entry(self._object_path(bucket, key))
        except NotFoundError:
            pass  # S3 delete is idempotent
        except ValueError as e:
            return _error_xml("InvalidRequest", str(e), 400)
        self._record_access("delete", bucket, key, 0, t0)
        return Response(b"", 204)

    def _copy_object(self, bucket: str, key: str, req: Request):
        source = urllib.parse.unquote(
            req.headers.get("X-Amz-Copy-Source", "")).lstrip("/")
        src_bucket, _, src_key = source.partition("/")
        src = self.filer.find_entry(self._object_path(src_bucket, src_key))
        body = self.filer_server.read_bytes(src)
        entry = self.filer_server.save_bytes(
            self._object_path(bucket, key), body,
            mime=src.attr.mime, extended=dict(src.extended))
        return Response(_xml("CopyObjectResult", {
            "ETag": f'"{entry.attr.md5}"',
            "LastModified": _iso(entry.attr.mtime),
        }), 200, "application/xml")

    def _multi_delete(self, bucket: str, req: Request):
        root = ET.fromstring(req.body)
        ns = ""
        if root.tag.startswith("{"):
            ns = root.tag[:root.tag.index("}") + 1]
        deleted, errors = [], []
        for obj in root.findall(f"{ns}Object"):
            key_el = obj.find(f"{ns}Key")
            if key_el is None or not key_el.text:
                continue
            try:
                self.filer.delete_entry(
                    self._object_path(bucket, key_el.text))
                deleted.append(key_el.text)
            except NotFoundError:
                deleted.append(key_el.text)  # S3: missing counts as deleted
            except ValueError as e:
                errors.append((key_el.text, str(e)))
        return Response(_xml("DeleteResult", {
            "Deleted": [{"Key": k} for k in deleted],
            "Error": [{"Key": k, "Code": "InvalidRequest", "Message": m}
                      for k, m in errors],
        }), 200, "application/xml")

    # -- tagging -------------------------------------------------------------
    def _put_tagging(self, bucket: str, key: str, req: Request):
        entry = self.filer.find_entry(self._object_path(bucket, key))
        root = ET.fromstring(req.body)
        ns = root.tag[:root.tag.index("}") + 1] if \
            root.tag.startswith("{") else ""
        tags = {}
        for tag_el in root.iter(f"{ns}Tag"):
            k = tag_el.find(f"{ns}Key")
            v = tag_el.find(f"{ns}Value")
            if k is not None and v is not None:
                tags[k.text] = v.text or ""
        entry.extended = {k: v for k, v in entry.extended.items()
                          if not k.startswith("x-amz-tag-")}
        for k, v in tags.items():
            entry.extended[f"x-amz-tag-{k}"] = v
        self.filer.update_entry(entry)
        return Response(b"", 200)

    def _get_tagging(self, bucket: str, key: str):
        entry = self.filer.find_entry(self._object_path(bucket, key))
        tags = [(k[len("x-amz-tag-"):], v)
                for k, v in entry.extended.items()
                if k.startswith("x-amz-tag-")]
        return Response(_xml("Tagging", {
            "TagSet": {"Tag": [{"Key": k, "Value": v} for k, v in tags]},
        }), 200, "application/xml")

    def _delete_tagging(self, bucket: str, key: str):
        entry = self.filer.find_entry(self._object_path(bucket, key))
        entry.extended = {k: v for k, v in entry.extended.items()
                          if not k.startswith("x-amz-tag-")}
        self.filer.update_entry(entry)
        return Response(b"", 204)

    # -- multipart (filer_multipart.go) --------------------------------------
    def _upload_dir(self, bucket: str, upload_id: str) -> str:
        return f"{BUCKETS_ROOT}/{bucket}/{UPLOADS_DIR}/{upload_id}"

    def _create_multipart(self, bucket: str, key: str, req: Request):
        upload_id = uuid.uuid4().hex
        marker = new_directory_entry(self._upload_dir(bucket, upload_id))
        marker.extended["key"] = key
        marker.extended["mime"] = req.headers.get("Content-Type") or ""
        self.filer.create_entry(marker)
        return Response(_xml("InitiateMultipartUploadResult", {
            "Bucket": bucket, "Key": key, "UploadId": upload_id,
        }), 200, "application/xml")

    def _upload_part(self, bucket: str, key: str, req: Request):
        upload_id = req.param("uploadId")
        part = int(req.param("partNumber"))
        self.filer.find_entry(self._upload_dir(bucket, upload_id))
        entry = self.filer_server.save_bytes(
            f"{self._upload_dir(bucket, upload_id)}/{part:05d}.part",
            req.body)
        return Response(b"", 200,
                        headers={"ETag": f'"{entry.attr.md5}"'})

    def _complete_multipart(self, bucket: str, key: str, req: Request):
        upload_id = req.param("uploadId")
        upload_dir = self._upload_dir(bucket, upload_id)
        marker = self.filer.find_entry(upload_dir)
        staged = {int(e.name.split(".")[0]): e
                  for e in self.filer.list_directory(upload_dir,
                                                     limit=10001)
                  if e.name.endswith(".part")}
        requested = self._requested_part_numbers(req.body)
        if requested is not None:
            missing = [n for n in requested if n not in staged]
            if missing:
                return _error_xml("InvalidPart",
                                  f"parts {missing} not uploaded", 400)
            part_numbers = requested  # the client's list is authoritative
        else:
            part_numbers = sorted(staged)
        parts = [staged[n] for n in part_numbers]
        if not parts:
            return _error_xml("InvalidPart", "no parts uploaded", 400)
        final = Entry(full_path=self._object_path(bucket, key))
        final.attr.mtime = final.attr.crtime = time.time()
        final.attr.mime = marker.extended.get("mime", "")
        offset = 0
        md5s = b""
        for p in parts:
            md5s += bytes.fromhex(p.attr.md5)
            if p.content:
                # inlined small part: push it to a volume chunk so
                # composition stays a pure chunk-list operation
                source_chunks = self._force_chunk(p.content)
            else:
                source_chunks = p.chunks
            if has_chunk_manifest(source_chunks):
                # manifest blobs serialize part-RELATIVE offsets; shifting
                # the outer chunk would leave the nested ones unshifted,
                # so compose from the flattened plain chunks instead
                source_chunks = resolve_chunk_manifest(
                    self.filer_server._fetch_chunk, source_chunks)
            for c in sorted(source_chunks, key=lambda c: c.offset):
                final.chunks.append(FileChunk(
                    fid=c.fid, offset=offset + c.offset, size=c.size,
                    etag=c.etag, modified_ts_ns=time.time_ns(),
                    is_chunk_manifest=c.is_chunk_manifest,
                    cipher_key=c.cipher_key))
            offset += p.size()
        final.attr.file_size = offset
        etag = f"{hashlib.md5(md5s).hexdigest()}-{len(parts)}"
        final.attr.md5 = etag
        self.filer.create_entry(final)
        # drop the staging dir without reclaiming chunks now owned by the
        # final entry; exclusion happens inside _delete_chunks AFTER
        # manifest expansion, so a part's manifest blob is reclaimed while
        # the data chunks it lists (now the final entry's) survive
        saved_hook = self.filer.on_delete_chunks
        final_fids = {c.fid for c in final.chunks}
        self.filer.on_delete_chunks = lambda chunks: \
            self.filer_server._delete_chunks(chunks,
                                             exclude_fids=final_fids)
        try:
            self.filer.delete_entry(upload_dir, recursive=True)
        finally:
            self.filer.on_delete_chunks = saved_hook
        return Response(_xml("CompleteMultipartUploadResult", {
            "Bucket": bucket, "Key": key, "ETag": f'"{etag}"',
        }), 200, "application/xml")

    @staticmethod
    def _requested_part_numbers(body: bytes):
        """Parse CompleteMultipartUpload XML -> ordered part numbers, or
        None when the client sent no body (lenient mode)."""
        if not body:
            return None
        try:
            root = ET.fromstring(body)
        except ET.ParseError:
            return None
        ns = root.tag[:root.tag.index("}") + 1] if \
            root.tag.startswith("{") else ""
        numbers = [int(el.text) for el in root.iter(f"{ns}PartNumber")
                   if el.text]
        return numbers or None

    def _force_chunk(self, content: bytes) -> list[FileChunk]:
        # the filer's uploader so encrypt-at-rest and JWT forwarding apply
        # to inlined small parts too
        return [self.filer_server._upload_blob(content)]

    def _abort_multipart(self, bucket: str, key: str, req: Request):
        upload_id = req.param("uploadId")
        try:
            self.filer.delete_entry(self._upload_dir(bucket, upload_id),
                                    recursive=True)
        except NotFoundError:
            return _error_xml("NoSuchUpload", upload_id, 404)
        return Response(b"", 204)

    def _list_parts(self, bucket: str, key: str, req: Request):
        upload_id = req.param("uploadId")
        upload_dir = self._upload_dir(bucket, upload_id)
        self.filer.find_entry(upload_dir)
        parts = [e for e in self.filer.list_directory(upload_dir,
                                                      limit=10001)
                 if e.name.endswith(".part")]
        parts.sort(key=lambda e: int(e.name.split(".")[0]))
        return Response(_xml("ListPartsResult", {
            "Bucket": bucket, "Key": key, "UploadId": upload_id,
            "Part": [
                {"PartNumber": int(p.name.split(".")[0]),
                 "ETag": f'"{p.attr.md5}"',
                 "Size": p.size()} for p in parts
            ],
        }), 200, "application/xml")


def _iso(ts: float) -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%S.000Z", time.gmtime(ts))


def _http_date(ts: float) -> str:
    return time.strftime("%a, %d %b %Y %H:%M:%S GMT", time.gmtime(ts))
