"""Request/byte concurrency limits for the S3 gateway.

Parity with weed/s3api/s3api_circuit_breaker.go: global and per-bucket
limits on simultaneous request count and in-flight upload/download bytes,
split by read/write action.  Exceeding a limit returns 503 SlowDown.  The
reference stores limits in the filer at /etc/s3/circuit_breaker.json and
hot-reloads; here the config is the same JSON shape, loadable from the
filer or passed directly.
"""

from __future__ import annotations

import json
import threading
from typing import Optional

CONFIG_PATH = "/etc/s3/circuit_breaker.json"

# limit kinds (s3_pb CircuitBreakerConfig actions)
LIMIT_COUNT = "Count"
LIMIT_BYTES = "MB"  # configured in megabytes like the reference shell


class SlowDown(Exception):
    """Raised when a limit trips; maps to S3 503 SlowDown."""


class _Gauge:
    __slots__ = ("count", "bytes")

    def __init__(self):
        self.count = 0
        self.bytes = 0


class CircuitBreaker:
    def __init__(self, config: Optional[dict] = None):
        self._lock = threading.Lock()
        self._global = _Gauge()
        self._buckets: dict[str, _Gauge] = {}
        self.enabled = False
        self.global_limits: dict[str, int] = {}
        self.bucket_limits: dict[str, dict[str, int]] = {}
        if config:
            self.load(config)

    def load(self, config: dict):
        """Config shape (circuit_breaker.json):
        {"global": {"enabled": true, "actions": {"Read:Count": 100,
         "Write:MB": 512, ...}},
         "buckets": {"b1": {"enabled": true, "actions": {...}}}}

        Built off to the side and swapped under the lock: hot-reload runs
        on a request thread while other requests are admitting."""
        glob = config.get("global", {})
        enabled = bool(glob.get("enabled"))
        global_limits = {k: int(v)
                         for k, v in glob.get("actions", {}).items()}
        bucket_limits: dict[str, dict[str, int]] = {}
        for bucket, conf in config.get("buckets", {}).items():
            if conf.get("enabled"):
                bucket_limits[bucket] = {
                    k: int(v) for k, v in conf.get("actions", {}).items()}
        with self._lock:
            self.enabled = enabled
            self.global_limits = global_limits
            self.bucket_limits = bucket_limits

    @classmethod
    def load_from_filer(cls, filer_server) -> "CircuitBreaker":
        return cls(read_config(filer_server) or {})

    # -- admission ----------------------------------------------------------
    def _check(self, limits: dict[str, int], gauge: _Gauge, action: str,
               nbytes: int):
        count_limit = limits.get(f"{action}:{LIMIT_COUNT}")
        if count_limit is not None and gauge.count + 1 > count_limit:
            raise SlowDown(f"too many concurrent {action} requests")
        byte_limit = limits.get(f"{action}:{LIMIT_BYTES}")
        if byte_limit is not None and \
                gauge.bytes + nbytes > byte_limit * (1 << 20):
            raise SlowDown(f"too many concurrent {action} bytes")

    def acquire(self, bucket: str, action: str, nbytes: int = 0):
        """Admit a request or raise SlowDown.  Returns a release handle."""
        with self._lock:
            # read the whole configuration under the same lock load()
            # swaps it under: one admission, ONE consistent config
            enabled = self.enabled
            bucket_rules = self.bucket_limits.get(bucket)
            if not enabled and bucket_rules is None:
                return lambda: None
            # only limited buckets need a gauge; unknown bucket names
            # must not grow the map unboundedly
            bucket_gauge = self._buckets.setdefault(bucket, _Gauge()) \
                if bucket_rules is not None else None
            if enabled:
                self._check(self.global_limits, self._global, action, nbytes)
            if bucket_rules is not None:
                self._check(bucket_rules, bucket_gauge, action, nbytes)
                bucket_gauge.count += 1
                bucket_gauge.bytes += nbytes
            self._global.count += 1
            self._global.bytes += nbytes

        released = threading.Event()

        def release():
            if released.is_set():
                return
            released.set()
            with self._lock:
                self._global.count -= 1
                self._global.bytes -= nbytes
                if bucket_gauge is not None:
                    bucket_gauge.count -= 1
                    bucket_gauge.bytes -= nbytes

        return release


def read_config(filer_server) -> Optional[dict]:
    """Fetch /etc/s3/circuit_breaker.json through the filer's full read
    path — configs past the inline limit live in chunks, so
    entry.content alone would silently read as empty.

    Returns {} when no config exists, and None on a TRANSIENT read
    failure: a hot-reloading caller must keep its current limits rather
    than silently dropping all throttles."""
    from ..filer.filer_store import NotFoundError
    from ..rpc.http_rpc import RpcError

    try:
        entry = filer_server.filer.find_entry(CONFIG_PATH)
        return json.loads(filer_server.read_bytes(entry).decode())
    except (NotFoundError, ValueError):
        return {}
    except RpcError:
        return None
