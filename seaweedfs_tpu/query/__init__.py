"""Structured queries over needle content (weed/query)."""

from .json_query import (Query, filter_record, get_path, query_csv,
                         query_json_lines)

__all__ = ["Query", "filter_record", "get_path", "query_csv",
           "query_json_lines"]
