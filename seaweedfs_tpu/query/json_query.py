"""SQL-ish SELECT over JSON-lines needle content.

Parity with weed/query/json/query_json.go: each line of a stored object
is one JSON record; a query has a dotted field path, an operator, and a
value; passing records are projected down to the selected fields.  Type
semantics mirror filterJson(): string/number/bool comparisons are
type-directed by the *record's* value, `%`/`!%` are glob matches on
strings, an empty operator tests mere existence, and a missing field
never matches.  The reference leaves CSV input unimplemented
(volume_grpc_query.go:38 empty branch); here CSV-with-header is
supported as well since the request schema advertises it.
"""

from __future__ import annotations

import csv
import io
import json
from dataclasses import dataclass
from typing import Any, Iterable, Optional


@dataclass
class Query:
    field: str = ""
    op: str = ""
    value: str = ""


_MISSING = object()


def get_path(obj: Any, path: str) -> Any:
    """Resolve a gjson-style dotted path (list elements by integer
    index); None when the path is absent."""
    found, value = _lookup(obj, path)
    return value if found else None


def _lookup(obj: Any, path: str) -> tuple[bool, Any]:
    cur = obj
    if not path:
        return False, None
    for part in path.split("."):
        if isinstance(cur, dict):
            if part not in cur:
                return False, None
            cur = cur[part]
        elif isinstance(cur, list):
            try:
                cur = cur[int(part)]
            except (ValueError, IndexError):
                return False, None
        else:
            return False, None
    return True, cur


def _glob_match(s: str, pattern: str) -> bool:
    """tidwall/match semantics: `*` any run, `?` one char (no [] classes)."""
    # iterative two-pointer with backtracking
    si = pi = 0
    star = -1
    mark = 0
    while si < len(s):
        if pi < len(pattern) and pattern[pi] in ("?", s[si]):
            si += 1
            pi += 1
        elif pi < len(pattern) and pattern[pi] == "*":
            star, mark = pi, si
            pi += 1
        elif star != -1:
            pi = star + 1
            mark += 1
            si = mark
        else:
            return False
    while pi < len(pattern) and pattern[pi] == "*":
        pi += 1
    return pi == len(pattern)


def filter_record(record: Any, query: Query) -> bool:
    """Type-directed comparison per query_json.go filterJson()."""
    found, value = _lookup(record, query.field)
    if not found:
        return False
    if query.op == "":
        return True  # existence test
    op, rpv = query.op, query.value
    if isinstance(value, str):
        table = {
            "=": value == rpv, "!=": value != rpv,
            "<": value < rpv, "<=": value <= rpv,
            ">": value > rpv, ">=": value >= rpv,
            "%": _glob_match(value, rpv),
            "!%": not _glob_match(value, rpv),
        }
        return table.get(op, False)
    if isinstance(value, bool):  # before number: bool is an int subclass
        if value:
            return {"=": rpv == "true", "!=": rpv != "true",
                    ">": rpv == "false", ">=": True}.get(op, False)
        return {"=": rpv == "false", "!=": rpv != "false",
                "<": rpv == "true", "<=": True}.get(op, False)
    if isinstance(value, (int, float)):
        try:
            rpvn = float(rpv)
        except ValueError:
            rpvn = 0.0
        num = float(value)
        return {"=": num == rpvn, "!=": num != rpvn,
                "<": num < rpvn, "<=": num <= rpvn,
                ">": num > rpvn, ">=": num >= rpvn}.get(op, False)
    return False


def _project(record: Any, selections: list[str]) -> Any:
    if not selections:
        return record
    out = {}
    for sel in selections:
        found, value = _lookup(record, sel)
        if found:
            # last path segment names the output column (gjson behavior
            # of ToJson naming by selection)
            out[sel] = value
    return out


def query_json_lines(data: bytes, selections: list[str],
                     query: Query) -> list[dict]:
    """Run the filter+projection over JSON-lines content; skips
    unparseable lines like gjson.ForEachLine does."""
    results = []
    for line in data.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            record = json.loads(line)
        except ValueError:
            continue
        if filter_record(record, query):
            results.append(_project(record, selections))
    return results


def query_csv(data: bytes, selections: list[str], query: Query,
              file_header_info: str = "USE") -> list[dict]:
    """CSV input: rows become dicts keyed by header (USE) or _1.._n
    (NONE/IGNORE), then share the JSON filter/projection path."""
    text = data.decode(errors="replace")
    rows: Iterable[list[str]] = csv.reader(io.StringIO(text))
    rows = list(rows)
    if not rows:
        return []
    if file_header_info.upper() == "USE":
        header, body = rows[0], rows[1:]
    else:
        width = max(len(r) for r in rows)
        header = [f"_{i + 1}" for i in range(width)]
        body = rows if file_header_info.upper() == "NONE" else rows[1:]
    results = []
    for row in body:
        record: dict[str, Any] = {}
        for key, cell in zip(header, row):
            try:
                record[key] = json.loads(cell)  # numbers/bools pass through
            except ValueError:
                record[key] = cell
        if filter_record(record, query):
            results.append(_project(record, selections))
    return results
