"""Amortized fid leasing: batch `/dir/assign` calls into per-key leases.

The filer's write path historically paid one synchronous master round
trip per chunk (`/dir/assign?count=1`).  The master already supports
`count=N` — it returns a base fid plus N-1 derived fids
(``<base>_<delta>``, needle id = base + delta), all on the same volume
and all covered by the same write JWT.  This module caches one such
batch per (collection, replication, ttl) key and hands out fids locally,
so steady-state writes hit the master ~1/N as often.

Correctness hinges on three invalidation paths:

- **TTL expiry**: leased fids go stale when the master-side assign TTL
  (or the write JWT riding with the batch) expires; every lease carries
  a deadline and expired leases are dropped on the next take.
- **Leader change**: a new master has a new sequence space and may have
  re-planned volume placement; ``MasterClient`` calls
  :func:`invalidate_all` whenever the watch feed identity changes.
- **Stale-fid upload failure**: the volume backing a lease can fill up
  or move between refills; callers that see a 4xx/5xx on a leased fid
  call :meth:`FidLeaseCache.invalidate` and retry once with a direct
  assign (see ``filer/server.py:_upload_blob``).

Refills are single-flight per key: one thread performs the master call
while concurrent missers wait on the key's condition variable, and a
low-water mark triggers an asynchronous refill so steady-state writers
rarely block on the master at all.
"""

from __future__ import annotations

import os
import threading
import time
import weakref
from collections import deque
from typing import Callable, Optional

from ..stats import metrics as _stats
from ..util import glog

_DEFAULT_LEASE = 16
_DEFAULT_TTL = 8.0
# safety margin subtracted from the master-reported auth expiry so a fid
# taken just under the wire still has time to reach the volume server
_AUTH_SLACK = 2.0


def lease_count() -> int:
    """Batch size N per master assign; <= 1 disables leasing."""
    raw = os.environ.get("WEED_FILER_ASSIGN_LEASE", "")
    if not raw:
        return _DEFAULT_LEASE
    try:
        return int(raw)
    except ValueError:
        return _DEFAULT_LEASE


def lease_ttl() -> float:
    raw = os.environ.get("WEED_FILER_ASSIGN_LEASE_TTL", "")
    if not raw:
        return _DEFAULT_TTL
    try:
        return float(raw)
    except ValueError:
        return _DEFAULT_TTL


# every live cache registers here so master failover (detected by any
# MasterClient watch loop in the process) can drop all leased fids
_registry_lock = threading.Lock()
_caches: "weakref.WeakSet[FidLeaseCache]" = weakref.WeakSet()


def invalidate_all(reason: str = "leader_change"):
    with _registry_lock:
        caches = list(_caches)
    for cache in caches:
        cache.invalidate(reason=reason)


class _Lease:
    __slots__ = ("base_fid", "url", "public_url", "auth", "count",
                 "next_index", "expires_at")

    def __init__(self, reply: dict, count: int, expires_at: float):
        self.base_fid = reply["fid"]
        self.url = reply.get("url", "")
        self.public_url = reply.get("publicUrl", self.url)
        self.auth = reply.get("auth", "")
        self.count = min(count, int(reply.get("count", count)) or count)
        self.next_index = 0
        self.expires_at = expires_at

    def remaining(self) -> int:
        return self.count - self.next_index

    def take(self) -> dict:
        i = self.next_index
        self.next_index += 1
        fid = self.base_fid if i == 0 else f"{self.base_fid}_{i}"
        out = {"fid": fid, "url": self.url, "publicUrl": self.public_url,
               "count": 1, "leased": True}
        if self.auth:
            out["auth"] = self.auth
        return out


class _KeyState:
    __slots__ = ("cond", "leases", "refilling")

    def __init__(self):
        self.cond = threading.Condition()
        self.leases: deque[_Lease] = deque()
        self.refilling = False


class FidLeaseCache:
    """Per-(replication, collection, ttl) cache of batched assigns.

    ``assign_fn(count, replication, collection, ttl) -> dict`` performs
    the actual master call and must raise on failure.
    """

    def __init__(self, assign_fn: Callable[..., dict], name: str = "filer"):
        self._assign_fn = assign_fn
        self.name = name
        self._lock = threading.Lock()  # guards _states map itself
        self._states: dict[tuple, _KeyState] = {}
        with _registry_lock:
            _caches.add(self)

    def _state(self, key: tuple) -> _KeyState:
        with self._lock:
            st = self._states.get(key)
            if st is None:
                st = self._states[key] = _KeyState()
            return st

    # -- take ---------------------------------------------------------------
    def get(self, replication: str = "", collection: str = "",
            ttl: str = "", wait_timeout: float = 30.0) -> dict:
        n = lease_count()
        if n <= 1:
            return self._assign_fn(1, replication, collection, ttl)
        key = (replication, collection, ttl)
        st = self._state(key)
        deadline = time.monotonic() + wait_timeout
        with st.cond:
            while True:
                got = self._take_locked(st)
                if got is not None:
                    _stats.FilerFidLeaseCounter.labels("hit").inc()
                    if self._remaining_locked(st) < max(1, n // 4) \
                            and not st.refilling:
                        self._spawn_refill_locked(st, key, n)
                    return got
                if not st.refilling:
                    st.refilling = True
                    break  # this thread performs the refill
                # single-flight: another thread is already at the master
                remaining = deadline - time.monotonic()
                if remaining <= 0 or not st.cond.wait(remaining):
                    # refill wedged — don't pile up behind it
                    _stats.FilerFidLeaseCounter.labels("miss").inc()
                    return self._assign_fn(1, replication, collection, ttl)
        _stats.FilerFidLeaseCounter.labels("miss").inc()
        try:
            lease = self._fetch_lease(key, n)
        except Exception:
            with st.cond:
                st.refilling = False
                st.cond.notify_all()
            raise
        with st.cond:
            st.refilling = False
            st.leases.append(lease)
            got = self._take_locked(st)
            st.cond.notify_all()
        _stats.FilerFidLeaseCounter.labels("refill").inc()
        return got if got is not None else self._assign_fn(
            1, replication, collection, ttl)

    def _take_locked(self, st: _KeyState) -> Optional[dict]:
        now = time.monotonic()
        while st.leases:
            lease = st.leases[0]
            if lease.expires_at <= now:
                st.leases.popleft()
                _stats.FilerFidLeaseCounter.labels("expired").inc()
                continue
            if lease.remaining() <= 0:
                st.leases.popleft()
                continue
            return lease.take()
        return None

    @staticmethod
    def _remaining_locked(st: _KeyState) -> int:
        now = time.monotonic()
        return sum(l.remaining() for l in st.leases if l.expires_at > now)

    # -- refill -------------------------------------------------------------
    def _fetch_lease(self, key: tuple, n: int) -> _Lease:
        replication, collection, ttl = key
        reply = self._assign_fn(n, replication, collection, ttl)
        expires = time.monotonic() + lease_ttl()
        auth_ttl = reply.get("authExpiresSeconds")
        if reply.get("auth") and auth_ttl:
            expires = min(expires,
                          time.monotonic() + float(auth_ttl) - _AUTH_SLACK)
        return _Lease(reply, n, expires)

    def _spawn_refill_locked(self, st: _KeyState, key: tuple, n: int):
        st.refilling = True
        threading.Thread(target=self._refill_async, args=(st, key, n),
                         daemon=True, name=f"fid-lease-{self.name}").start()

    def _refill_async(self, st: _KeyState, key: tuple, n: int):
        try:
            lease = self._fetch_lease(key, n)
        except Exception as e:
            glog.v(1).infof("fid lease refill for %s failed: %s", key, e)
            with st.cond:
                st.refilling = False
                st.cond.notify_all()
            return
        with st.cond:
            st.refilling = False
            st.leases.append(lease)
            st.cond.notify_all()
        _stats.FilerFidLeaseCounter.labels("refill").inc()

    # -- invalidation -------------------------------------------------------
    def invalidate(self, reason: str = "stale"):
        """Drop every leased fid (leader change, stale-fid failure)."""
        with self._lock:
            states = list(self._states.values())
        dropped = 0
        for st in states:
            with st.cond:
                dropped += sum(1 for l in st.leases if l.remaining() > 0)
                st.leases.clear()
        if dropped:
            _stats.FilerFidLeaseCounter.labels("invalidated").inc()
            glog.v(1).infof("fid lease cache %s invalidated (%s), "
                            "%d leases dropped", self.name, reason, dropped)

    def stats(self) -> dict:
        with self._lock:
            states = dict(self._states)
        out = {}
        for key, st in states.items():
            with st.cond:
                out[key] = self._remaining_locked(st)
        return out
