"""Minimal SigV4-signing S3 client (path-style).

The reference links the AWS Go SDK for its s3 sink and remote-storage
provider (replication/sink/s3sink, remote_storage/s3); this environment
has no SDK and no egress, so replication/remote-storage speak to any
S3-compatible endpoint — including this framework's own s3api gateway —
through this hand-rolled client.
"""

from __future__ import annotations

import hashlib
import hmac
import time
import urllib.parse
from typing import Optional

from ..rpc.http_rpc import RpcError, call

ALGORITHM = "AWS4-HMAC-SHA256"


class S3Client:
    def __init__(self, endpoint: str, access_key: str = "",
                 secret_key: str = "", region: str = "us-east-1"):
        self.endpoint = endpoint  # host:port
        self.access_key = access_key
        self.secret_key = secret_key
        self.region = region

    # -- signing -------------------------------------------------------------
    def _sign(self, method: str, path: str, query: dict,
              body: bytes, payload_hash: str = "",
              extra_headers: Optional[dict] = None) -> dict:
        """SigV4 header auth.  payload_hash overrides the body digest
        (for the streaming sentinel); extra_headers join the SIGNED
        header set.  Also returns the computed signature and scope under
        private "_sig"/"_scope"/"_datestamp" keys (popped before
        sending) so the streaming path can chain chunk signatures."""
        if not self.access_key:
            return {}
        now = time.gmtime()
        amz_date = time.strftime("%Y%m%dT%H%M%SZ", now)
        datestamp = time.strftime("%Y%m%d", now)
        payload_hash = payload_hash or hashlib.sha256(body).hexdigest()
        headers = {
            "Host": self.endpoint,
            "X-Amz-Date": amz_date,
            "X-Amz-Content-Sha256": payload_hash,
            **(extra_headers or {}),
        }
        signed = sorted(k.lower() for k in headers)
        canonical_uri = urllib.parse.quote(path, safe="/~")
        canonical_query = self._canonical_query(query)
        lower = {k.lower(): v for k, v in headers.items()}
        header_lines = [f"{name}:{' '.join(str(lower[name]).split())}"
                        for name in signed]
        canonical = "\n".join([
            method, canonical_uri, canonical_query,
            "\n".join(header_lines) + "\n", ";".join(signed), payload_hash])
        scope = f"{datestamp}/{self.region}/s3/aws4_request"
        string_to_sign = "\n".join([
            ALGORITHM, amz_date, scope,
            hashlib.sha256(canonical.encode()).hexdigest()])

        signature = hmac.new(self._signing_key(datestamp),
                             string_to_sign.encode(),
                             hashlib.sha256).hexdigest()
        headers["Authorization"] = (
            f"{ALGORITHM} Credential={self.access_key}/{scope}, "
            f"SignedHeaders={';'.join(signed)}, Signature={signature}")
        headers["_sig"] = signature
        headers["_scope"] = scope
        headers["_amz_date"] = amz_date
        headers["_datestamp"] = datestamp
        return headers

    def _signing_key(self, datestamp: str) -> bytes:
        def h(key, msg):
            return hmac.new(key, msg.encode(), hashlib.sha256).digest()

        k = h(("AWS4" + self.secret_key).encode(), datestamp)
        for part in (self.region, "s3", "aws4_request"):
            k = h(k, part)
        return k

    @staticmethod
    def _canonical_query(query: dict) -> str:
        """AWS canonical query: sorted pairs, %20 percent-encoding (never
        urlencode's '+', which decodes as a space but signs as a literal
        plus).  The SAME string is signed and sent, by construction."""
        return "&".join(
            f"{k}={v}" for k, v in sorted(
                (urllib.parse.quote(k, safe="~"),
                 urllib.parse.quote(str(v), safe="~"))
                for k, v in query.items()))

    @staticmethod
    def _strip_private(headers: dict) -> dict:
        return {k: v for k, v in headers.items()
                if not k.startswith("_")}

    def _request(self, method: str, path: str,
                 query: Optional[dict] = None, body: bytes = b"",
                 content_type: str = "", parse: bool = True):
        query = query or {}
        headers = self._strip_private(self._sign(method, path, query, body))
        if content_type:
            headers["Content-Type"] = content_type
        qs = self._canonical_query(query)
        full = urllib.parse.quote(path, safe="/~") + ("?" + qs if qs else "")
        return call(self.endpoint, full, raw=body if body else None,
                    method=method, headers=headers, timeout=120,
                    parse=parse)

    # -- object ops ----------------------------------------------------------
    def create_bucket(self, bucket: str):
        try:
            self._request("PUT", f"/{bucket}")
        except RpcError as e:
            if e.status != 409:  # BucketAlreadyExists is fine
                raise

    def delete_bucket(self, bucket: str):
        self._request("DELETE", f"/{bucket}")

    def put_object(self, bucket: str, key: str, data: bytes,
                   content_type: str = "application/octet-stream"):
        self._request("PUT", f"/{bucket}/{key.lstrip('/')}", body=data,
                      content_type=content_type)

    def put_object_streaming(self, bucket: str, key: str, data,
                             chunk_size: int = 64 << 10,
                             content_type: str =
                             "application/octet-stream"):
        """Upload with sigv4 streaming chunk signatures (aws-chunked,
        STREAMING-AWS4-HMAC-SHA256-PAYLOAD): each frame is individually
        signed against the seed chain.  `data` is bytes-like or an
        iterable of byte chunks (empty chunks are skipped — a zero
        frame terminates the stream)."""
        if isinstance(data, (bytes, bytearray, memoryview)):
            data = bytes(data)
            pieces = [data[i:i + chunk_size]
                      for i in range(0, len(data), chunk_size)]
        else:
            pieces = [bytes(p) for p in data if len(p)]
        if not self.access_key:
            # unsigned gateways take a plain PUT
            return self.put_object(bucket, key, b"".join(pieces),
                                   content_type)
        total = sum(len(p) for p in pieces)
        path = f"/{bucket}/{key.lstrip('/')}"
        headers = self._sign(
            "PUT", path, {}, b"",
            payload_hash="STREAMING-AWS4-HMAC-SHA256-PAYLOAD",
            extra_headers={
                "Content-Encoding": "aws-chunked",
                "X-Amz-Decoded-Content-Length": str(total),
                "Content-Type": content_type,
            })
        k = self._signing_key(headers["_datestamp"])
        amz_date, scope = headers["_amz_date"], headers["_scope"]
        prev = headers["_sig"]
        headers = self._strip_private(headers)
        empty = hashlib.sha256(b"").hexdigest()
        frames = bytearray()
        pieces.append(b"")  # terminator frame
        while pieces:  # consume as we frame: one resident copy, not two
            piece = pieces.pop(0)
            sts = "\n".join([
                "AWS4-HMAC-SHA256-PAYLOAD", amz_date, scope, prev, empty,
                hashlib.sha256(piece).hexdigest()])
            sig = hmac.new(k, sts.encode(), hashlib.sha256).hexdigest()
            frames += f"{len(piece):x};chunk-signature={sig}\r\n".encode()
            frames += piece + b"\r\n"
            prev = sig
        call(self.endpoint, urllib.parse.quote(path, safe="/~"),
             raw=bytes(frames), method="PUT", headers=headers,
             timeout=300)

    def get_object(self, bucket: str, key: str) -> bytes:
        body = self._request("GET", f"/{bucket}/{key.lstrip('/')}",
                             parse=False)
        return body if isinstance(body, bytes) else b""

    def get_object_range(self, bucket: str, key: str, offset: int,
                         size: int) -> bytes:
        """Ranged GET (unsigned Range header rides alongside SigV4)."""
        path = f"/{bucket}/{key.lstrip('/')}"
        headers = self._sign("GET", path, {}, b"")
        headers["Range"] = f"bytes={offset}-{offset + size - 1}"
        body = call(self.endpoint,
                    urllib.parse.quote(path, safe="/~"), method="GET",
                    headers=headers, timeout=120, parse=False)
        return body if isinstance(body, bytes) else b""

    def delete_object(self, bucket: str, key: str):
        try:
            self._request("DELETE", f"/{bucket}/{key.lstrip('/')}")
        except RpcError as e:
            if e.status != 404:
                raise

    def list_objects(self, bucket: str,
                     prefix: str = "") -> list[dict]:
        """ListObjectsV2 with pagination; returns
        [{key, size, etag, last_modified}]."""
        import xml.etree.ElementTree as ET

        def text(node, tag):
            child = node.find(f"{{*}}{tag}")
            return child.text or "" if child is not None else ""

        objects: list[dict] = []
        start_after = ""
        while True:
            query = {"list-type": "2", "prefix": prefix}
            if start_after:
                query["start-after"] = start_after
            body = self._request("GET", f"/{bucket}", query=query)
            if not isinstance(body, bytes):
                break
            root = ET.fromstring(body)
            page = root.findall("{*}Contents")
            for node in page:
                objects.append({
                    "key": text(node, "Key"),
                    "size": int(text(node, "Size") or 0),
                    "etag": text(node, "ETag").strip('"'),
                    "last_modified": text(node, "LastModified"),
                })
            if not page or text(root, "IsTruncated") != "true":
                break
            start_after = objects[-1]["key"]
        return objects

    def list_keys(self, bucket: str, prefix: str = "") -> list[str]:
        return [o["key"] for o in self.list_objects(bucket, prefix)]
