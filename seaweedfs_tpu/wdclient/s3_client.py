"""Minimal SigV4-signing S3 client (path-style).

The reference links the AWS Go SDK for its s3 sink and remote-storage
provider (replication/sink/s3sink, remote_storage/s3); this environment
has no SDK and no egress, so replication/remote-storage speak to any
S3-compatible endpoint — including this framework's own s3api gateway —
through this hand-rolled client.
"""

from __future__ import annotations

import hashlib
import hmac
import time
import urllib.parse
from typing import Optional

from ..rpc.http_rpc import RpcError, call

ALGORITHM = "AWS4-HMAC-SHA256"


class S3Client:
    def __init__(self, endpoint: str, access_key: str = "",
                 secret_key: str = "", region: str = "us-east-1"):
        self.endpoint = endpoint  # host:port
        self.access_key = access_key
        self.secret_key = secret_key
        self.region = region

    # -- signing -------------------------------------------------------------
    def _sign(self, method: str, path: str, query: dict,
              body: bytes) -> dict:
        if not self.access_key:
            return {}
        now = time.gmtime()
        amz_date = time.strftime("%Y%m%dT%H%M%SZ", now)
        datestamp = time.strftime("%Y%m%d", now)
        payload_hash = hashlib.sha256(body).hexdigest()
        headers = {
            "Host": self.endpoint,
            "X-Amz-Date": amz_date,
            "X-Amz-Content-Sha256": payload_hash,
        }
        signed = ["host", "x-amz-content-sha256", "x-amz-date"]
        canonical_uri = urllib.parse.quote(path, safe="/~")
        canonical_query = self._canonical_query(query)
        lower = {k.lower(): v for k, v in headers.items()}
        header_lines = [f"{name}:{' '.join(lower[name].split())}"
                        for name in signed]
        canonical = "\n".join([
            method, canonical_uri, canonical_query,
            "\n".join(header_lines) + "\n", ";".join(signed), payload_hash])
        scope = f"{datestamp}/{self.region}/s3/aws4_request"
        string_to_sign = "\n".join([
            ALGORITHM, amz_date, scope,
            hashlib.sha256(canonical.encode()).hexdigest()])

        def h(key, msg):
            return hmac.new(key, msg.encode(), hashlib.sha256).digest()

        k = h(("AWS4" + self.secret_key).encode(), datestamp)
        for part in (self.region, "s3", "aws4_request"):
            k = h(k, part)
        signature = hmac.new(k, string_to_sign.encode(),
                             hashlib.sha256).hexdigest()
        headers["Authorization"] = (
            f"{ALGORITHM} Credential={self.access_key}/{scope}, "
            f"SignedHeaders={';'.join(signed)}, Signature={signature}")
        return headers

    @staticmethod
    def _canonical_query(query: dict) -> str:
        """AWS canonical query: sorted pairs, %20 percent-encoding (never
        urlencode's '+', which decodes as a space but signs as a literal
        plus).  The SAME string is signed and sent, by construction."""
        return "&".join(
            f"{k}={v}" for k, v in sorted(
                (urllib.parse.quote(k, safe="~"),
                 urllib.parse.quote(str(v), safe="~"))
                for k, v in query.items()))

    def _request(self, method: str, path: str,
                 query: Optional[dict] = None, body: bytes = b"",
                 content_type: str = "", parse: bool = True):
        query = query or {}
        headers = self._sign(method, path, query, body)
        if content_type:
            headers["Content-Type"] = content_type
        qs = self._canonical_query(query)
        full = urllib.parse.quote(path, safe="/~") + ("?" + qs if qs else "")
        return call(self.endpoint, full, raw=body if body else None,
                    method=method, headers=headers, timeout=120,
                    parse=parse)

    # -- object ops ----------------------------------------------------------
    def create_bucket(self, bucket: str):
        try:
            self._request("PUT", f"/{bucket}")
        except RpcError as e:
            if e.status != 409:  # BucketAlreadyExists is fine
                raise

    def delete_bucket(self, bucket: str):
        self._request("DELETE", f"/{bucket}")

    def put_object(self, bucket: str, key: str, data: bytes,
                   content_type: str = "application/octet-stream"):
        self._request("PUT", f"/{bucket}/{key.lstrip('/')}", body=data,
                      content_type=content_type)

    def get_object(self, bucket: str, key: str) -> bytes:
        body = self._request("GET", f"/{bucket}/{key.lstrip('/')}",
                             parse=False)
        return body if isinstance(body, bytes) else b""

    def get_object_range(self, bucket: str, key: str, offset: int,
                         size: int) -> bytes:
        """Ranged GET (unsigned Range header rides alongside SigV4)."""
        path = f"/{bucket}/{key.lstrip('/')}"
        headers = self._sign("GET", path, {}, b"")
        headers["Range"] = f"bytes={offset}-{offset + size - 1}"
        body = call(self.endpoint,
                    urllib.parse.quote(path, safe="/~"), method="GET",
                    headers=headers, timeout=120, parse=False)
        return body if isinstance(body, bytes) else b""

    def delete_object(self, bucket: str, key: str):
        try:
            self._request("DELETE", f"/{bucket}/{key.lstrip('/')}")
        except RpcError as e:
            if e.status != 404:
                raise

    def list_objects(self, bucket: str,
                     prefix: str = "") -> list[dict]:
        """ListObjectsV2 with pagination; returns
        [{key, size, etag, last_modified}]."""
        import xml.etree.ElementTree as ET

        def text(node, tag):
            child = node.find(f"{{*}}{tag}")
            return child.text or "" if child is not None else ""

        objects: list[dict] = []
        start_after = ""
        while True:
            query = {"list-type": "2", "prefix": prefix}
            if start_after:
                query["start-after"] = start_after
            body = self._request("GET", f"/{bucket}", query=query)
            if not isinstance(body, bytes):
                break
            root = ET.fromstring(body)
            page = root.findall("{*}Contents")
            for node in page:
                objects.append({
                    "key": text(node, "Key"),
                    "size": int(text(node, "Size") or 0),
                    "etag": text(node, "ETag").strip('"'),
                    "last_modified": text(node, "LastModified"),
                })
            if not page or text(root, "IsTruncated") != "true":
                break
            start_after = objects[-1]["key"]
        return objects

    def list_keys(self, bucket: str, prefix: str = "") -> list[str]:
        return [o["key"] for o in self.list_objects(bucket, prefix)]
