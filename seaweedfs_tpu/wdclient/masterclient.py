"""Master client with a vid→locations cache kept fresh by the watch feed.

Parity with weed/wdclient: MasterClient holds a vidMap refreshed by the
KeepConnected stream's VolumeLocation deltas (masterclient.go:20-120); here
the stream is the master's /dir/watch long-poll.  Lookup misses fall back
to /dir/lookup and populate the cache (vid_map.go:38-120).
"""

from __future__ import annotations

import random
import threading
from typing import Optional

from . import fid_lease
from ..rpc import policy
from ..rpc.http_rpc import RpcError
from ..util import glog


class VidMap:
    """vid -> [location dicts]; thread-safe."""

    def __init__(self):
        self._lock = threading.Lock()
        self._map: dict[int, list[dict]] = {}

    def get(self, vid: int) -> list[dict]:
        with self._lock:
            return list(self._map.get(vid, []))

    def set(self, vid: int, locations: list[dict]):
        with self._lock:
            self._map[vid] = list(locations)

    def add(self, vid: int, url: str, public_url: str):
        with self._lock:
            locs = self._map.setdefault(vid, [])
            if not any(l["url"] == url for l in locs):
                locs.append({"url": url, "publicUrl": public_url})

    def remove(self, vid: int, url: str):
        with self._lock:
            locs = self._map.get(vid)
            if locs is None:
                return
            self._map[vid] = [l for l in locs if l["url"] != url]
            if not self._map[vid]:
                del self._map[vid]

    def clear(self):
        with self._lock:
            self._map.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._map)


class MasterClient:
    def __init__(self, masters: list[str] | str, name: str = "client"):
        self.masters = ([masters] if isinstance(masters, str)
                        else list(masters))
        self.name = name
        self.vid_map = VidMap()
        self.current_master = self.masters[0]
        self._seq = 0
        self._feed_id = ""  # sequence-space identity of the watched master
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lookup (vid_map.go LookupVolumeServerUrl) ---------------------------
    def lookup(self, vid: int) -> list[dict]:
        cached = self.vid_map.get(vid)
        if cached:
            return cached
        found = self._call_any(f"/dir/lookup?volumeId={vid}")
        locations = found.get("locations", [])
        if locations:
            self.vid_map.set(vid, locations)
        return locations

    def lookup_file_id(self, fid: str) -> list[str]:
        vid = int(fid.split(",")[0])
        locations = self.lookup(vid)
        if not locations:
            raise RpcError(f"volume {vid} not found", 404)
        return [f"{l['url']}/{fid}" for l in locations]

    def assign(self, count: int = 1, replication: str = "",
               collection: str = "", ttl: str = "") -> dict:
        query = f"count={count}"
        if replication:
            query += f"&replication={replication}"
        if collection:
            query += f"&collection={collection}"
        if ttl:
            query += f"&ttl={ttl}"
        return self._call_any(f"/dir/assign?{query}")

    def call(self, path: str, payload: Optional[dict] = None,
             timeout: float = 30):
        """Public failover call: any master-side route, leader hints
        honored (for callers like the filer that speak routes beyond
        assign/lookup)."""
        return self._call_any(path, payload=payload, timeout=timeout)

    def _call_any(self, path: str, payload: Optional[dict] = None,
                  timeout: float = 30):
        """Try current master first, fail over through the list
        (masterclient.go tryAllMasters) — via the shared policy layer:
        per-master circuit breakers skip known-dead peers, full-jitter
        backoff separates failover rounds, and the propagated deadline
        caps the whole sweep."""
        masters = [self.current_master] + [
            m for m in self.masters if m != self.current_master]
        try:
            result, winner = policy.failover_call(
                masters, path, payload=payload, timeout=timeout)
        except RpcError as e:
            # a non-leader master names the leader in its rejection:
            # honor the hint directly instead of burning another
            # failover round guessing through the list
            hint = (e.headers or {}).get("X-Raft-Leader", "")
            if not hint or hint == getattr(e, "addr", ""):
                raise
            result = policy.call_policy(hint, path, payload=payload,
                                        timeout=timeout, retries=0)
            self.current_master = hint
            return result
        self.current_master = winner
        return result

    # -- keep-connected watch loop (masterclient.go KeepConnected) -----------
    def start(self):
        self._thread = threading.Thread(target=self._watch_loop, daemon=True)
        self._thread.start()

    def stop(self):
        self._stop.set()

    def _watch_loop(self):
        while not self._stop.is_set():
            try:
                r = policy.call_policy(
                    self.current_master,
                    f"/dir/watch?since={self._seq}&timeout=15",
                    timeout=20, retries=0)
            except RpcError:
                # re-aim at a master whose breaker isn't open (the
                # failed poll just fed that breaker via call_policy)
                healthy = [m for m in self.masters
                           if policy.BREAKERS.get(m).state
                           != policy.OPEN] or self.masters
                self.current_master = random.choice(healthy)
                self._stop.wait(1.0)
                continue
            self._apply_watch_reply(r)

    def _apply_watch_reply(self, r: dict):
        """Fold one /dir/watch reply into the cache (factored out of the
        loop so failover handling is testable without a live master)."""
        feed_id = r.get("feed_id", "")
        if feed_id != self._feed_id:
            # different master (failover) = different sequence space:
            # restart the cursor and drop everything cached — including
            # any batched fid leases minted against the old leader
            if self._feed_id:
                self.vid_map.clear()
                self._seq = 0
                self._feed_id = feed_id
                fid_lease.invalidate_all(reason="leader_change")
                return  # re-poll from 0 on the new feed
            self._feed_id = feed_id
        if r.get("resync"):
            # fell off the retained delta window: drop the cache and
            # let lookups repopulate it
            self.vid_map.clear()
        for d in r.get("deltas", []):
            if d["op"] == "add":
                self.vid_map.add(d["volume"], d["url"],
                                 d.get("publicUrl", d["url"]))
            else:
                self.vid_map.remove(d["volume"], d["url"])
        self._seq = max(self._seq, r.get("seq", self._seq))
        leader = r.get("leader")
        if leader and leader not in self.masters:
            # the cluster grew under us (raft membership change):
            # adopt the new master so failover can reach it, then
            # follow it like any other leader announcement
            glog.infof("adopting new master %s announced as leader",
                       leader)
            self.masters.append(leader)
        if leader and leader != self.current_master:
            # follow the announced leader so the next assign goes
            # straight there instead of bouncing off a 409
            self.current_master = leader
