"""Generic bounded resource pool (wdclient/resource_pool, the Dropbox
net2-derived pool the reference vendors): borrow/return with a cap on
open resources, idle reuse, and broken-resource disposal."""

from __future__ import annotations

import threading
import time
from typing import Callable, Generic, Optional, TypeVar

T = TypeVar("T")


class PoolClosedError(Exception):
    pass


class ResourcePool(Generic[T]):
    def __init__(self, factory: Callable[[], T],
                 close_fn: Optional[Callable[[T], None]] = None,
                 max_open: int = 16, max_idle: int = 4,
                 borrow_timeout: float = 30.0):
        self._factory = factory
        self._close_fn = close_fn or (lambda r: None)
        self._max_open = max_open
        self._max_idle = max_idle
        self._borrow_timeout = borrow_timeout
        self._idle: list[T] = []
        self._open_count = 0
        self._closed = False
        self._cond = threading.Condition()

    def borrow(self) -> T:
        deadline = time.monotonic() + self._borrow_timeout
        with self._cond:
            while True:
                if self._closed:
                    raise PoolClosedError("pool is closed")
                if self._idle:
                    return self._idle.pop()
                if self._open_count < self._max_open:
                    self._open_count += 1
                    break  # create outside the lock
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TimeoutError("no pooled resource available")
                self._cond.wait(remaining)
        try:
            return self._factory()
        except Exception:
            with self._cond:
                self._open_count -= 1
                self._cond.notify()
            raise

    def give_back(self, resource: T, broken: bool = False):
        with self._cond:
            if broken or self._closed \
                    or len(self._idle) >= self._max_idle:
                self._open_count -= 1
                self._cond.notify()
                to_close = resource
            else:
                self._idle.append(resource)
                self._cond.notify()
                return
        self._close_fn(to_close)

    def use(self):
        """Context manager: with pool.use() as r: ..."""
        pool = self

        class _Ctx:
            def __enter__(self):
                self.resource = pool.borrow()
                return self.resource

            def __exit__(self, exc_type, exc, tb):
                pool.give_back(self.resource, broken=exc_type is not None)
                return False

        return _Ctx()

    def close(self):
        with self._cond:
            self._closed = True
            idle, self._idle = self._idle, []
            self._open_count -= len(idle)
            self._cond.notify_all()
        for resource in idle:
            self._close_fn(resource)

    @property
    def stats(self) -> dict:
        with self._cond:
            return {"open": self._open_count, "idle": len(self._idle),
                    "max_open": self._max_open}
