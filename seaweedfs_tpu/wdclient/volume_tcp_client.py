"""TCP fast path for volume reads (wdclient/volume_tcp_client.go).

HTTP adds per-request header parsing on the hottest path — the
reference's experimental TCP mode trades it for a trivial framed
protocol on a dedicated port (http port + 20000).  Frame format:

  request:  "G <fid>[ <jwt>]\n"          (read needle; jwt when the
                                          cluster signs reads)
  response: u32be status | u32be length | payload
            status 0 = ok, 401 = unauthorized, 404 = not found,
            500 = error

Connections are pooled per server address via ResourcePool.
"""

from __future__ import annotations

import socket
import struct
import threading
from typing import Optional

from .resource_pool import ResourcePool

TCP_PORT_OFFSET = 20000  # mirrors the reference's port+20000 convention


class VolumeTcpError(Exception):
    def __init__(self, message: str, status: int = 500):
        super().__init__(message)
        self.status = status


class VolumeTcpClient:
    """Pooled TCP connections to volume servers' fast-path ports."""

    def __init__(self, max_conns_per_server: int = 8):
        self._pools: dict[str, ResourcePool[socket.socket]] = {}
        self._resolved: dict[str, str] = {}  # http url -> tcp addr
        self._lock = threading.Lock()
        self._max = max_conns_per_server

    def _pool(self, tcp_addr: str) -> ResourcePool:
        with self._lock:
            pool = self._pools.get(tcp_addr)
            if pool is None:
                host, port = tcp_addr.rsplit(":", 1)

                def factory(host=host, port=int(port)):
                    s = socket.create_connection((host, port), timeout=30)
                    s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                    return s

                pool = ResourcePool(
                    factory, close_fn=lambda s: s.close(),
                    max_open=self._max, max_idle=self._max)
                self._pools[tcp_addr] = pool
            return pool

    def tcp_address(self, http_url: str) -> str:
        """port+20000 by convention; when that overflows (ephemeral test
        ports) ask the server's /admin/status for its actual tcp_port."""
        host, port = http_url.rsplit(":", 1)
        wanted = int(port) + TCP_PORT_OFFSET
        if wanted <= 65535:
            return f"{host}:{wanted}"
        with self._lock:
            cached = self._resolved.get(http_url)
        if cached:
            return cached
        from ..rpc.http_rpc import call

        status = call(http_url, "/admin/status", timeout=10)
        tcp_port = status.get("tcp_port", 0)
        if not tcp_port:
            raise VolumeTcpError(
                f"{http_url} does not serve the TCP fast path", 503)
        resolved = f"{host}:{tcp_port}"
        with self._lock:
            self._resolved[http_url] = resolved
        return resolved

    def read_needle(self, volume_server_url: str, fid: str,
                    jwt: str = "") -> bytes:
        pool = self._pool(self.tcp_address(volume_server_url))
        with pool.use() as conn:
            line = f"G {fid} {jwt}\n" if jwt else f"G {fid}\n"
            conn.sendall(line.encode())
            header = _read_exact(conn, 8)
            status, length = struct.unpack(">II", header)
            payload = _read_exact(conn, length)
            if status != 0:
                raise VolumeTcpError(
                    payload.decode(errors="replace") or "read failed",
                    status)
            return payload

    def close(self):
        with self._lock:
            pools, self._pools = list(self._pools.values()), {}
        for pool in pools:
            pool.close()


def _read_exact(conn: socket.socket, n: int) -> bytes:
    parts = []
    while n > 0:
        chunk = conn.recv(n)
        if not chunk:
            raise VolumeTcpError("connection closed mid-frame")
        parts.append(chunk)
        n -= len(chunk)
    return b"".join(parts)
