"""TCP fast path for volume reads/writes (wdclient/volume_tcp_client.go).

HTTP adds per-request header parsing on the hottest path — the
reference's experimental TCP mode trades it for a trivial framed
protocol on a dedicated port (http port + 20000).  Frame format:

  request:  "G <fid>[ <jwt>]\n"          (read needle; jwt when the
                                          cluster signs reads)
            "W <fid> <length>\n<body>"   (write needle, native engine)
            "D <fid>\n"                  (delete needle, native engine)
  response: u32be status | u32be length | payload
            status 0 = ok, 307 = fall back to the HTTP port (volume not
            served natively), 401 = unauthorized, 404 = not found,
            500 = error

The server side is the native engine (native/vol_native.cpp) when the
library is available, else the Python TCP loop (reads only).
Connections are pooled per server address via ResourcePool.
"""

from __future__ import annotations

import socket
import struct
import threading
from typing import Optional

from .resource_pool import ResourcePool

TCP_PORT_OFFSET = 20000  # mirrors the reference's port+20000 convention


class VolumeTcpError(Exception):
    def __init__(self, message: str, status: int = 500):
        super().__init__(message)
        self.status = status


class VolumeTcpClient:
    """Pooled TCP connections to volume servers' fast-path ports."""

    def __init__(self, max_conns_per_server: int = 8):
        self._pools: dict[str, ResourcePool[socket.socket]] = {}
        self._resolved: dict[str, str] = {}  # http url -> tcp addr
        self._lock = threading.Lock()
        self._max = max_conns_per_server

    def _pool(self, tcp_addr: str) -> ResourcePool:
        with self._lock:
            pool = self._pools.get(tcp_addr)
            if pool is None:
                host, port = tcp_addr.rsplit(":", 1)

                def factory(host=host, port=int(port)):
                    s = socket.create_connection((host, port), timeout=30)
                    s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                    return s

                pool = ResourcePool(
                    factory, close_fn=lambda s: s.close(),
                    max_open=self._max, max_idle=self._max)
                self._pools[tcp_addr] = pool
            return pool

    def tcp_address(self, http_url: str) -> str:
        """port+20000 by convention, verified with one cheap probe; when
        the convention port is not listening (ephemeral test ports, or a
        combined process whose single native listener rides the master's
        port) ask the server's /admin/status for its actual tcp_port."""
        with self._lock:
            cached = self._resolved.get(http_url)
        if cached:
            return cached
        host, port = http_url.rsplit(":", 1)
        wanted = int(port) + TCP_PORT_OFFSET
        resolved = ""
        if wanted <= 65535:
            try:
                probe = socket.create_connection((host, wanted),
                                                 timeout=0.5)
                probe.close()
                resolved = f"{host}:{wanted}"
            except OSError:
                pass
        if not resolved:
            from ..rpc.http_rpc import call

            status = call(http_url, "/admin/status", timeout=10)
            tcp_port = status.get("tcp_port", 0)
            if not tcp_port:
                raise VolumeTcpError(
                    f"{http_url} does not serve the TCP fast path", 503)
            resolved = f"{host}:{tcp_port}"
        with self._lock:
            self._resolved[http_url] = resolved
        return resolved

    def _request(self, volume_server_url: str, frame: bytes) -> bytes:
        try:
            pool = self._pool(self.tcp_address(volume_server_url))
            with pool.use() as conn:
                conn.sendall(frame)
                header = _read_exact(conn, 8)
                status, length = struct.unpack(">II", header)
                payload = _read_exact(conn, length)
        except OSError as e:
            # a dead pooled connection often means the server restarted
            # on a new ephemeral port: drop the cached resolution so the
            # next call re-probes instead of pinning HTTP-fallback forever
            with self._lock:
                self._resolved.pop(volume_server_url, None)
            raise VolumeTcpError(f"fast path unreachable: {e}", 307) \
                from None
        if status != 0:
            raise VolumeTcpError(
                payload.decode(errors="replace") or "request failed",
                status)
        return payload

    def read_needle(self, volume_server_url: str, fid: str,
                    jwt: str = "", http_fallback: bool = True) -> bytes:
        """Fast-path read; a 307 (volume not served natively: EC volume,
        sqlite index, TTL volume, vacuum window) falls back to HTTP
        unless the caller wants to see the 307 and route itself."""
        line = f"G {fid} {jwt}\n" if jwt else f"G {fid}\n"
        try:
            return self._request(volume_server_url, line.encode())
        except VolumeTcpError as e:
            if e.status != 307 or not http_fallback:
                raise
            return self._http_fallback(volume_server_url, fid, "GET",
                                       jwt=jwt)

    def write_needle(self, volume_server_url: str, fid: str,
                     data: bytes, jwt: str = "") -> bytes:
        """Fast-path write (native engine only; JWT-secured clusters
        pass the assign's fid-scoped token).  307 (no native engine,
        replica set unpublished, vacuum window) falls back to the HTTP
        handler, whose fan-out + identical-rewrite dedup keep a
        partially-forwarded native attempt consistent."""
        line = f"W {fid} {len(data)} {jwt}\n" if jwt \
            else f"W {fid} {len(data)}\n"
        try:
            return self._request(volume_server_url, line.encode() + data)
        except VolumeTcpError as e:
            if e.status != 307:
                raise
            return self._http_fallback(volume_server_url, fid, "POST",
                                       body=data, jwt=jwt)

    def delete_needle(self, volume_server_url: str, fid: str,
                      jwt: str = "") -> bytes:
        line = f"D {fid} {jwt}\n" if jwt else f"D {fid}\n"
        try:
            return self._request(volume_server_url, line.encode())
        except VolumeTcpError as e:
            if e.status != 307:
                raise
            return self._http_fallback(volume_server_url, fid, "DELETE",
                                       jwt=jwt)

    def _http_fallback(self, url: str, fid: str, method: str,
                       body: Optional[bytes] = None, jwt: str = "") -> bytes:
        from ..rpc.http_rpc import RpcError, call

        headers = {"Authorization": "BEARER " + jwt} if jwt else {}
        try:
            result = call(url, f"/{fid}", method=method, raw=body,
                          headers=headers, timeout=30)
        except RpcError as e:
            raise VolumeTcpError(str(e), e.status) from None
        if isinstance(result, (bytes, bytearray)):
            return bytes(result)
        import json as _json

        return _json.dumps(result).encode()

    def close(self):
        with self._lock:
            pools, self._pools = list(self._pools.values()), {}
        for pool in pools:
            pool.close()


def _read_exact(conn: socket.socket, n: int) -> bytes:
    parts = []
    while n > 0:
        chunk = conn.recv(n)
        if not chunk:
            raise VolumeTcpError("connection closed mid-frame")
        parts.append(chunk)
        n -= len(chunk)
    return b"".join(parts)
