from .fid_lease import FidLeaseCache
from .masterclient import MasterClient, VidMap

__all__ = ["FidLeaseCache", "MasterClient", "VidMap"]
