from .masterclient import MasterClient, VidMap

__all__ = ["MasterClient", "VidMap"]
