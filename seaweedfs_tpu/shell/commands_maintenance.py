"""Admin shell: maintenance.* commands over the curator's HTTP surface.

Thin RPC wrappers around the master's /maintenance/* routes
(maintenance/curator.py): status and queue inspection, pause/resume,
and forcing a detector pass or a single explicit job.  No reference
analogue — the reference's maintenance lives in ad-hoc shell commands
run by an operator; here the curator runs them continuously.
"""

from __future__ import annotations

from typing import Optional

from .commands import CommandEnv


def maintenance_status(env: CommandEnv) -> dict:
    """Curator status: enabled/leader flags, scan counters, queue depth
    by state and type, per-volume last deep-scrub clock."""
    return env.master("/maintenance/status")


def maintenance_queue(env: CommandEnv) -> dict:
    """Live jobs plus the tail of finished-job history."""
    return env.master("/maintenance/queue")


def maintenance_pause(env: CommandEnv, paused: bool = True) -> dict:
    """Stop (or resume) handing out leases; detectors keep enqueueing."""
    return env.master("/maintenance/pause", {"paused": bool(paused)})


def maintenance_run(env: CommandEnv, job_type: Optional[str] = None,
                    volume: int = 0, collection: str = "",
                    params: Optional[dict] = None) -> dict:
    """Force work now: with job_type, enqueue that one job; without,
    run a full detector pass instead of waiting for the interval."""
    if job_type:
        return env.master("/maintenance/run",
                          {"type": job_type, "volume": int(volume),
                           "collection": collection,
                           "params": params or {}})
    return env.master("/maintenance/run", {})
