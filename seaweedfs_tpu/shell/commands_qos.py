"""Shell commands for the cluster QoS subsystem.

``qos.status`` fans ``GET /debug/qos`` out to every live daemon —
master, every volume server in the topology, and every filer / s3
gateway in the cluster registry — and returns one merged view plus a
small cluster-wide rollup (total shed / queued / in-flight per class).
"""

from __future__ import annotations

from concurrent.futures import ThreadPoolExecutor

from ..rpc.http_rpc import RpcError, call
from .commands import CommandEnv


def _discover(env: CommandEnv) -> dict:
    """{display_name: address} for every reachable daemon."""
    targets = {f"master {env.master_address}": env.master_address}
    topo = env.master("/dir/status")
    for dc in topo.get("datacenters", []):
        for rack in dc.get("racks", []):
            for n in rack.get("nodes", []):
                targets[f"volume {n['url']}"] = n["url"]
    for kind in ("filer", "s3"):
        try:
            nodes = env.master(f"/cluster/nodes?type={kind}")
        except (RpcError, OSError):
            continue
        for n in nodes.get("cluster_nodes", []):
            targets[f"{kind} {n['address']}"] = n["address"]
    return targets


def qos_status(env: CommandEnv) -> dict:
    targets = _discover(env)

    def fetch(addr: str):
        return call(addr, "/debug/qos", timeout=10)

    daemons: dict = {}
    failed: list = []
    with ThreadPoolExecutor(max_workers=max(4, len(targets))) as pool:
        futs = {name: pool.submit(fetch, addr)
                for name, addr in targets.items()}
        for name, fut in futs.items():
            try:
                daemons[name] = fut.result()
            except (RpcError, OSError) as e:
                failed.append(f"{name}: {e}")

    rollup = {"inflight": {}, "queued": {}, "shed": {}, "admitted": {}}
    lanes_totals = {"preemptions": 0, "background_wait_seconds": 0.0}
    for snap in daemons.values():
        gate = snap.get("gate") or {}
        for field in rollup:
            for cls, n in (gate.get(field) or {}).items():
                rollup[field][cls] = rollup[field].get(cls, 0) + n
        lanes = snap.get("lanes") or {}
        lanes_totals["preemptions"] += lanes.get("preemptions", 0)
        lanes_totals["background_wait_seconds"] += lanes.get(
            "background_wait_seconds", 0.0)
    lanes_totals["background_wait_seconds"] = round(
        lanes_totals["background_wait_seconds"], 3)
    return {"daemons": daemons, "rollup": rollup,
            "lanes": lanes_totals, "unreachable": failed}
