"""Admin shell: remote.* commands (weed/shell/command_remote_*.go).

All state lives in the filer (/etc/remote/); these commands drive the
filer's /remote/* endpoints."""

from __future__ import annotations

from typing import Optional

from ..rpc.http_rpc import call
from .commands import CommandEnv
from .commands_fs import find_filer


def remote_configure(env: CommandEnv, name: str = "", type: str = "s3",
                     endpoint: str = "", access_key: str = "",
                     secret_key: str = "", directory: str = "",
                     delete: bool = False) -> dict:
    if not name:  # bare remote.configure lists configured storages
        return call(find_filer(env), "/remote/list")
    return call(find_filer(env), "/remote/configure", {
        "name": name, "type": type, "endpoint": endpoint,
        "access_key": access_key, "secret_key": secret_key,
        "directory": directory, "delete": delete})


def remote_mount(env: CommandEnv, directory: str = "",
                 remote: str = "") -> dict:
    if not directory:  # bare remote.mount lists mappings
        return call(find_filer(env), "/remote/list").get("mappings", {})
    return call(find_filer(env), "/remote/mount",
                {"dir": directory, "remote": remote}, timeout=600)


def remote_unmount(env: CommandEnv, directory: str) -> dict:
    return call(find_filer(env), "/remote/unmount", {"dir": directory})


def remote_meta_sync(env: CommandEnv, directory: str) -> dict:
    return call(find_filer(env), "/remote/meta_sync",
                {"dir": directory}, timeout=600)


def remote_cache(env: CommandEnv, directory: str) -> dict:
    return call(find_filer(env), "/remote/cache", {"dir": directory},
                timeout=3600)


def remote_uncache(env: CommandEnv, directory: str) -> dict:
    return call(find_filer(env), "/remote/uncache", {"dir": directory},
                timeout=600)


def remote_mount_buckets(env: CommandEnv, remote: str,
                         buckets_dir: str = "/buckets") -> list[dict]:
    """command_remote_mount_buckets.go: mount every bucket of a remote
    under the buckets dir."""
    from ..remote_storage import RemoteLocation

    filer = find_filer(env)
    loc = RemoteLocation.parse(remote)
    # buckets on s3 = top-level listing isn't exposed by the minimal
    # client; mount the named bucket only, or each bucket listed locally
    out = []
    if loc.bucket:
        out.append(remote_mount(env, f"{buckets_dir}/{loc.bucket}",
                                str(loc)))
    return out
