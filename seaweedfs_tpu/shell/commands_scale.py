"""cluster.scale: elasticity status + manual scale job triggers.

The status view joins the curator's autoscale knobs with the per-node
load telemetry the detectors consume (occupancy / rps / draining from
each volume server's last heartbeat), so an operator sees exactly what
the autoscaler sees.  The up/drain verbs enqueue the same raft-
replicated scale.up / scale.drain jobs the detectors would."""

from __future__ import annotations

from ..maintenance.jobs import TYPE_SCALE_DRAIN, TYPE_SCALE_UP
from .commands import CommandEnv


def scale_status(env: CommandEnv) -> dict:
    """Autoscaler view: knobs, queue, and per-node telemetry."""
    maint = env.master("/maintenance/status")
    topo = env.master("/dir/status")
    nodes = [{"url": n["url"], "volumes": n["volumes"],
              "ec_shards": n.get("ecShards", 0),
              "occupancy": n.get("occupancy", 0.0),
              "rps": n.get("rps", 0.0),
              "draining": n.get("draining", False)}
             for dc in topo.get("datacenters", [])
             for rack in dc.get("racks", [])
             for n in rack.get("nodes", [])]
    scale_jobs = [j for j in env.master("/maintenance/queue")
                  .get("jobs", [])
                  if j.get("type") in (TYPE_SCALE_UP, TYPE_SCALE_DRAIN)]
    return {"autoscale": maint.get("autoscale", {}),
            "nodes": sorted(nodes, key=lambda n: n["url"]),
            "scale_jobs": scale_jobs}


def scale_up(env: CommandEnv) -> dict:
    """Enqueue a manual scale.up (grow the cluster by one server)."""
    return env.master("/maintenance/run",
                      {"type": TYPE_SCALE_UP,
                       "params": {"from": "shell"}})


def scale_drain(env: CommandEnv, server: str) -> dict:
    """Enqueue a graceful drain of `server` (read-only demotion ->
    paced evacuation -> deregistration)."""
    if not server:
        raise ValueError("cluster.scale -drain needs a server address")
    return env.master("/maintenance/run",
                      {"type": TYPE_SCALE_DRAIN,
                       "params": {"server": server, "from": "shell"}})
