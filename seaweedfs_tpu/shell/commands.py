"""Admin shell commands: the cluster orchestration layer.

Parity with weed/shell/command_ec_*.go and command_volume_*.go: ec.encode's
6-step flow (mark readonly -> generate on source -> spread shards by free
slots -> mount on targets -> cleanup source -> delete original volume;
command_ec_encode.go:95-192), ec.decode's collect-to-one-server flow,
ec.rebuild's roomiest-node rebuild, and ec.balance's spread.  Every command
supports plan-only mode (no RPCs) the way the reference's tests pass
applyBalancing=false (shell/command_ec_test.go).
"""

from __future__ import annotations

import concurrent.futures as cf
import threading
from dataclasses import dataclass, field
from typing import Optional

from ..rpc.http_rpc import RpcError, call
from ..storage.erasure_coding import TOTAL_SHARDS_COUNT

# shared fan-out pool for holder-parallel commands (ec.scrub): sized for
# I/O-bound RPC waits, lazily built so import stays thread-free
_fanout_pool: Optional[cf.ThreadPoolExecutor] = None
_fanout_lock = threading.Lock()


def _fanout() -> cf.ThreadPoolExecutor:
    global _fanout_pool
    with _fanout_lock:
        if _fanout_pool is None:
            _fanout_pool = cf.ThreadPoolExecutor(
                max_workers=16, thread_name_prefix="shell-fanout")
        return _fanout_pool


@dataclass
class CommandEnv:
    master_address: str
    filer_address: str = ""  # discovered lazily via the cluster registry
    admin_token: int = 0  # LeaseAdminToken lease for lock/unlock
    cwd: str = "/"  # fs.cd working directory for relative fs.* paths

    def master(self, path: str, payload=None, **kw):
        return call(self.master_address, path, payload, **kw)


@dataclass
class EcNode:
    url: str
    free_slots: int
    dc: str = ""
    rack: str = ""
    shards: dict[int, list[int]] = field(default_factory=dict)  # vid -> ids
    collections: dict[int, str] = field(default_factory=dict)  # vid -> name

    def shard_count(self) -> int:
        return sum(len(s) for s in self.shards.values())

    def rack_key(self) -> tuple[str, str]:
        return (self.dc, self.rack)


def collect_ec_nodes(env: CommandEnv) -> list[EcNode]:
    """Build the EC-capable node list from the master's topology view."""
    topo = env.master("/dir/status")
    nodes = []
    for dc in topo.get("datacenters", []):
        for rack in dc.get("racks", []):
            for n in rack.get("nodes", []):
                nodes.append(EcNode(url=n["url"], free_slots=n["free"],
                                    dc=n.get("dc", dc["id"]),
                                    rack=n.get("rack", rack["id"])))
    # fill current shard placements
    for vid in topo.get("ec_volumes", []):
        try:
            lookup = env.master(f"/ec/lookup?volumeId={vid}")
        except RpcError:
            continue
        collection = lookup.get("collection", "")
        for entry in lookup.get("shard_id_locations", []):
            for loc in entry["locations"]:
                for node in nodes:
                    if node.url == loc["url"]:
                        node.shards.setdefault(vid, []).append(
                            entry["shard_id"])
                        node.collections[vid] = collection
    return nodes


def balanced_ec_distribution(nodes: list[EcNode],
                             shard_count: int = TOTAL_SHARDS_COUNT
                             ) -> dict[str, list[int]]:
    """Rack-first shard spread: racks are filled round-robin (so a rack
    failure loses at most ceil(shards/racks) <= 4 of 14 shards whenever
    more than three racks exist), and within a rack shards round-robin
    over the nodes with free EC slots.  Combines balancedEcDistribution
    (command_ec_encode.go:253-269) with the rack-spreading objective of
    ec.balance (command_ec_balance.go:27-100) at placement time instead
    of fixing rack clustering after the fact.  Slot budget = free volume
    slots in shard units."""
    import random

    if not nodes:
        raise ValueError("no ec nodes available")
    allocation: dict[str, list[int]] = {n.url: [] for n in nodes}
    free = {n.url: n.free_slots * TOTAL_SHARDS_COUNT for n in nodes}

    racks: dict[tuple[str, str], list[EcNode]] = {}
    for n in nodes:
        racks.setdefault(n.rack_key(), []).append(n)
    rack_keys = list(racks.keys())
    random.shuffle(rack_keys)
    rack_node_index = {rk: random.randrange(len(racks[rk]))
                       for rk in rack_keys}

    def rack_has_free(rk) -> bool:
        return any(free[n.url] - len(allocation[n.url]) > 0
                   for n in racks[rk])

    shard_id = 0
    rack_index = 0
    spins = 0
    while shard_id < shard_count:
        rk = rack_keys[rack_index % len(rack_keys)]
        rack_index += 1
        if not rack_has_free(rk):
            spins += 1
            if spins > len(rack_keys):
                raise ValueError("not enough free ec slots")
            continue
        spins = 0
        # round-robin inside the rack, skipping slotless nodes
        rnodes = racks[rk]
        for _ in range(len(rnodes)):
            node = rnodes[rack_node_index[rk] % len(rnodes)]
            rack_node_index[rk] += 1
            if free[node.url] - len(allocation[node.url]) > 0:
                allocation[node.url].append(shard_id)
                shard_id += 1
                break
    return {url: ids for url, ids in allocation.items() if ids}


# -- ec.encode ---------------------------------------------------------------


def collect_volume_ids_for_ec_encode(env: CommandEnv, collection: str = "",
                                     full_percent: float = 95.0,
                                     quiet_seconds: float = 3600.0,
                                     now: Optional[float] = None
                                     ) -> list[int]:
    """Auto-EC candidate selection (collectVolumeIdsForEcEncode,
    command_ec_encode.go:271-302): volumes at least full_percent% of the
    master's volume size limit AND unmodified for quiet_seconds.  The
    reference keys on fullness + quiescence only; readonly volumes stay
    eligible (they encode fine)."""
    import time as _time

    topo = env.master("/dir/status")
    size_limit = topo.get("volume_size_limit", 0)
    if not size_limit:
        return []
    threshold = size_limit * full_percent / 100.0
    now = _time.time() if now is None else now
    vids: set[int] = set()
    for dc in topo.get("datacenters", []):
        for rack in dc.get("racks", []):
            for n in rack.get("nodes", []):
                for v in n.get("volume_list", []):
                    # exact-match selection, reference semantics
                    # (command_ec_encode.go:288): "" selects only the
                    # default (unnamed) collection, never a wildcard
                    if v.get("collection", "") != collection:
                        continue
                    if v.get("size", 0) < threshold:
                        continue
                    modified = v.get("modified_at", 0)
                    if modified and now - modified < quiet_seconds:
                        continue
                    vids.add(v["id"])
    return sorted(vids)


def ec_encode_auto(env: CommandEnv, collection: str = "",
                   full_percent: float = 95.0,
                   quiet_seconds: float = 3600.0,
                   plan_only: bool = False,
                   now: Optional[float] = None) -> list[dict]:
    """ec.encode -fullPercent=X -quietFor=Y: select full+quiet volumes
    from the topology and encode each (command_ec_encode.go:57-93)."""
    vids = collect_volume_ids_for_ec_encode(
        env, collection, full_percent, quiet_seconds, now=now)
    return [ec_encode(env, vid, collection, plan_only=plan_only)
            for vid in vids]


def _collection_ec_code(env: CommandEnv, collection: str) -> str:
    """The ``ec_code`` of the filer path-config rule that targets this
    collection (fs.configure -ecCode), "" when no filer / no rule.  The
    env-var overrides still win — the volume server's policy resolution
    (codes.family_for_collection) checks them first."""
    try:
        from ..filer.filer_conf import FILER_CONF_PATH
        from .commands_fs import _get_json_config, find_filer
        conf = _get_json_config(find_filer(env), FILER_CONF_PATH)
    except Exception:  # no filer in this deployment, or conf unreadable
        return ""
    for loc in conf.get("locations", []):
        if loc.get("collection", "") == collection and loc.get("ec_code"):
            return loc["ec_code"]
    return ""


def ec_encode(env: CommandEnv, vid: int, collection: str = "",
              plan_only: bool = False) -> dict:
    lookup = env.master(f"/dir/lookup?volumeId={vid}")
    locations = [loc["url"] for loc in lookup["locations"]]
    if not locations:
        raise RpcError(f"volume {vid} has no locations", 404)
    source = locations[0]
    nodes = collect_ec_nodes(env)
    allocation = balanced_ec_distribution(nodes)
    plan = {
        "volume": vid,
        "source": source,
        "replicas": locations,
        "allocation": allocation,
    }
    if plan_only:
        return plan

    # 1. freeze writes on every replica
    for url in locations:
        call(url, "/admin/readonly", {"volume": vid, "readonly": True})
    # 2. generate the 14 shard files + .ecx on the source (TPU encode);
    # the filer's per-collection ec_code rule rides along so the volume
    # server's policy resolution sees the path-config layer too
    payload: dict = {"volume": vid}
    ec_code = _collection_ec_code(env, collection)
    if ec_code:
        payload["code_family"] = ec_code
    call(source, "/admin/ec/generate", payload, timeout=3600)
    # 3/4. spread + mount
    for url, shard_ids in allocation.items():
        if url != source:
            call(url, "/admin/ec/copy",
                 {"volume": vid, "collection": collection,
                  "shard_ids": shard_ids, "source": source,
                  "copy_ecx_file": True}, timeout=3600)
        call(url, "/admin/ec/mount",
             {"volume": vid, "collection": collection,
              "shard_ids": shard_ids})
    # 5. cleanup: remove shard files that left the source
    source_kept = allocation.get(source, [])
    to_remove = [s for s in range(TOTAL_SHARDS_COUNT)
                 if s not in source_kept]
    if to_remove:
        call(source, "/admin/ec/delete_shards",
             {"volume": vid, "collection": collection,
              "shard_ids": to_remove})
    # 6. drop the original volume from every replica
    for url in locations:
        call(url, "/admin/delete_volume", {"volume": vid})
    return plan


# -- ec.decode ---------------------------------------------------------------


def ec_decode(env: CommandEnv, vid: int, collection: str = "",
              plan_only: bool = False) -> dict:
    lookup = env.master(f"/ec/lookup?volumeId={vid}")
    shard_locations = {
        e["shard_id"]: [loc["url"] for loc in e["locations"]]
        for e in lookup.get("shard_id_locations", [])
    }
    if not shard_locations:
        raise RpcError(f"ec volume {vid} not found", 404)
    # collect to the server already holding the most shards
    counts: dict[str, int] = {}
    for urls in shard_locations.values():
        for url in urls:
            counts[url] = counts.get(url, 0) + 1
    target = max(counts, key=counts.get)
    missing = [sid for sid, urls in shard_locations.items()
               if target not in urls]
    plan = {"volume": vid, "target": target, "copy_shards": missing}
    if plan_only:
        return plan

    for sid in missing:
        source = shard_locations[sid][0]
        call(target, "/admin/ec/copy",
             {"volume": vid, "collection": collection, "shard_ids": [sid],
              "source": source, "copy_ecx_file": False}, timeout=3600)
    call(target, "/admin/ec/to_volume",
         {"volume": vid, "collection": collection}, timeout=3600)
    # remove shards everywhere
    for url in set(u for urls in shard_locations.values() for u in urls):
        all_ids = [sid for sid, urls in shard_locations.items()
                   if url in urls]
        ids = all_ids if url != target else list(range(TOTAL_SHARDS_COUNT))
        if ids:
            try:
                call(url, "/admin/ec/delete_shards",
                     {"volume": vid, "collection": collection,
                      "shard_ids": ids})
            except RpcError:
                pass
    return plan


# -- ec.rebuild --------------------------------------------------------------


def _volume_family_info(vid: int, shard_locations: dict[int, list[str]]
                        ) -> dict:
    """Ask any shard holder which code family the volume was encoded with
    (served from its .vif record via /admin/ec/codes).  Holders predating
    the coding tier, or unreachable ones, fall back to the RS default so
    mixed clusters keep rebuilding the way they always did."""
    fallback = {"family": "rs_vandermonde",
                "data_shards": TOTAL_SHARDS_COUNT - 4, "repair_helpers": 0}
    holders = sorted({u for urls in shard_locations.values() for u in urls})
    for url in holders:
        try:
            info = call(url, f"/admin/ec/codes?volume={vid}")
        except (RpcError, OSError):
            continue
        vol = (info.get("volumes") or {}).get(str(vid))
        if not vol:
            continue
        fam = (info.get("families") or {}).get(vol.get("family", ""), {})
        return {"family": vol.get("family", fallback["family"]),
                "data_shards": fam.get("data_shards",
                                       fallback["data_shards"]),
                "repair_helpers": fam.get("repair_helpers", 0)}
    return fallback


def ec_rebuild(env: CommandEnv, vid: int, collection: str = "",
               plan_only: bool = False) -> dict:
    lookup = env.master(f"/ec/lookup?volumeId={vid}")
    shard_locations = {
        e["shard_id"]: [loc["url"] for loc in e["locations"]]
        for e in lookup.get("shard_id_locations", [])
    }
    present = sorted(shard_locations)
    missing = [s for s in range(TOTAL_SHARDS_COUNT) if s not in present]
    if not missing:
        return {"volume": vid, "missing": [], "rebuilder": None}
    fam = _volume_family_info(vid, shard_locations)
    # repairability bound is the family's, not RS's: any MDS family decodes
    # from data_shards survivors (pm_msr tolerates 9 losses, not 4)
    if len(present) < fam["data_shards"]:
        raise RpcError(
            f"ec volume {vid} has only {len(present)} shards "
            f"({fam['family']} needs {fam['data_shards']}), unrepairable",
            500)
    nodes = collect_ec_nodes(env)
    rebuilder = max(nodes, key=lambda n: n.free_slots)
    plan = {"volume": vid, "missing": missing, "rebuilder": rebuilder.url,
            "family": fam["family"], "mode": "copy_decode"}
    if plan_only:
        if (fam["repair_helpers"] and len(missing) == 1
                and len(present) >= fam["repair_helpers"]):
            plan["mode"] = "projection"
        return plan

    local = rebuilder.shards.get(vid, [])
    if (fam["repair_helpers"] and len(missing) == 1
            and len(present) >= fam["repair_helpers"]):
        # repair-optimal path: helpers stream sub-shard projections, the
        # rebuilder combines them — d/alpha of the lost bytes on the wire
        # instead of data_shards full shards
        try:
            if not local:
                # sidecars (.ecx/.vif) needed to mount + CRC-check the result
                call(rebuilder.url, "/admin/ec/copy",
                     {"volume": vid, "collection": collection,
                      "shard_ids": [], "source": shard_locations[present[0]][0],
                      "copy_ecx_file": True}, timeout=3600)
            sources = [{"shard_id": sid, "url": shard_locations[sid][0]}
                       for sid in present]
            reply = call(rebuilder.url, "/admin/ec/rebuild_projected",
                         {"volume": vid, "collection": collection,
                          "shard": missing[0], "sources": sources},
                         timeout=3600)
            call(rebuilder.url, "/admin/ec/mount",
                 {"volume": vid, "collection": collection,
                  "shard_ids": missing})
            plan.update(mode="projection",
                        read_bytes=reply.get("read_bytes"),
                        read_amp=reply.get("read_amp"))
            return plan
        except (RpcError, OSError):
            pass  # older holders / transient failure: full copy-decode below

    # gather surviving shards on the rebuilder
    for sid in present:
        if sid in local:
            continue
        source = shard_locations[sid][0]
        if source == rebuilder.url:
            continue
        call(rebuilder.url, "/admin/ec/copy",
             {"volume": vid, "collection": collection, "shard_ids": [sid],
              "source": source, "copy_ecx_file": True}, timeout=3600)
    call(rebuilder.url, "/admin/ec/rebuild",
         {"volume": vid, "collection": collection}, timeout=3600)
    call(rebuilder.url, "/admin/ec/mount",
         {"volume": vid, "collection": collection, "shard_ids": missing})
    # drop the temporarily copied survivors from the rebuilder's disk
    copied = [s for s in present
              if s not in local and s not in missing]
    if copied:
        call(rebuilder.url, "/admin/ec/delete_shards",
             {"volume": vid, "collection": collection,
              "shard_ids": copied})
    return plan


# -- ec.codes ----------------------------------------------------------------


def ec_codes(env: CommandEnv, vid: Optional[int] = None) -> dict:
    """Cluster view of the coding tier: registered families plus the
    family each mounted EC volume was encoded with, fanned over every
    volume server's /admin/ec/codes."""
    topo = env.master("/dir/status")
    urls = sorted({n["url"]
                   for dc in topo.get("datacenters", [])
                   for rack in dc.get("racks", [])
                   for n in rack.get("nodes", [])})
    path = "/admin/ec/codes" + (f"?volume={vid}" if vid is not None else "")
    futs = {url: _fanout().submit(call, url, path, timeout=30)
            for url in urls}
    report: dict = {"families": {}, "default_family": None,
                    "volumes": {}, "rebuild_read_amp": {}, "errors": []}
    for url in sorted(futs):
        try:
            r = futs[url].result()
        except (RpcError, OSError) as e:
            report["errors"].append({"node": url, "error": str(e)})
            continue
        report["families"].update(r.get("families", {}))
        report["default_family"] = (report["default_family"]
                                    or r.get("default_family"))
        for v, meta in (r.get("volumes") or {}).items():
            entry = report["volumes"].setdefault(
                v, {**meta, "shards": [], "holders": {}})
            entry["holders"][url] = sorted(meta.get("shards", []))
            entry["shards"] = sorted(
                set(entry["shards"]) | set(meta.get("shards", [])))
        if r.get("rebuild_read_amp"):
            # per-node snapshots: rebuild counters live on the rebuilder
            report["rebuild_read_amp"][url] = r["rebuild_read_amp"]
    if not report["errors"]:
        del report["errors"]
    return report


# -- ec.balance --------------------------------------------------------------


def _move_shard(moves: list[dict], source: EcNode, target: EcNode,
                vid: int, sid: int):
    source.shards[vid].remove(sid)
    if not source.shards[vid]:
        del source.shards[vid]
    target.shards.setdefault(vid, []).append(sid)
    target.collections.setdefault(vid, source.collections.get(vid, ""))
    moves.append({"volume": vid, "shard": sid,
                  "collection": source.collections.get(vid, ""),
                  "from": source.url, "to": target.url})


def _shard_slot_budget(nodes: list[EcNode]) -> dict[str, int]:
    """Free EC capacity per node in shard units (free volume slots x 14)."""
    return {n.url: n.free_slots * TOTAL_SHARDS_COUNT for n in nodes}


def _balance_racks(nodes: list[EcNode], moves: list[dict],
                   budget: dict[str, int]):
    """Phase 1 (doBalanceEcShardsAcrossRacks, command_ec_balance.go:27-63):
    per volume, no rack may hold more than ceil(shards/racks) shards —
    a rack failure must never take out more than one parity group's worth.
    Every pick is gated on remaining shard-slot budget (the reference's
    freeEcSlot > 0 gate in pickRackToBalanceShardsInto)."""
    racks: dict[tuple, list[EcNode]] = {}
    for n in nodes:
        racks.setdefault(n.rack_key(), []).append(n)
    if len(racks) <= 1:
        return
    vids = sorted({vid for n in nodes for vid in n.shards})
    for vid in vids:
        shards_per_rack = {
            rk: [(n, sid) for n in rnodes for sid in n.shards.get(vid, [])]
            for rk, rnodes in racks.items()}
        total = sum(len(v) for v in shards_per_rack.values())
        cap = -(-total // len(racks))  # ceil
        for rk, holders in sorted(shards_per_rack.items(),
                                  key=lambda kv: -len(kv[1])):
            while len(holders) > cap:
                node, sid = holders.pop()
                # a node may hold several distinct shard ids of one volume
                # (only the rack cap is a hard constraint); never duplicate
                # the same shard id on a node, never overfill a node
                candidates = [
                    (rk2, n2) for rk2, rnodes2 in racks.items()
                    if len(shards_per_rack[rk2]) < cap
                    for n2 in rnodes2
                    if budget[n2.url] > 0
                    and sid not in n2.shards.get(vid, [])]
                if not candidates:
                    break
                rk2, target = min(
                    candidates,
                    key=lambda c: (len(shards_per_rack[c[0]]),
                                   -budget[c[1].url]))
                _move_shard(moves, node, target, vid, sid)
                budget[target.url] -= 1
                budget[node.url] += 1
                shards_per_rack[rk2].append((target, sid))


def _balance_nodes(nodes: list[EcNode], moves: list[dict],
                   budget: dict[str, int]):
    """Phase 2 (doBalanceEcShardsWithinRacks + AcrossRacks node step):
    within each rack, even shard counts over nodes, never co-locating a
    volume's shards on one node, never overfilling a node."""
    racks: dict[tuple, list[EcNode]] = {}
    for n in nodes:
        racks.setdefault(n.rack_key(), []).append(n)
    for rnodes in racks.values():
        total = sum(n.shard_count() for n in rnodes)
        average = -(-total // len(rnodes))  # ceil
        overfull = [n for n in rnodes if n.shard_count() > average]
        for node in overfull:
            while node.shard_count() > average:
                vid, ids = max(node.shards.items(),
                               key=lambda kv: len(kv[1]))
                candidates = [n for n in rnodes if n is not node
                              and n.shard_count() < average
                              and budget[n.url] > 0
                              and vid not in n.shards]
                if not candidates:
                    break
                target = max(candidates, key=lambda n: budget[n.url])
                _move_shard(moves, node, target, vid, ids[-1])
                budget[target.url] -= 1
                budget[node.url] += 1


def ec_balance(env: CommandEnv, plan_only: bool = False) -> list[dict]:
    """Even out shard placement (command_ec_balance.go:27-100): first
    spread each volume's shards across racks (no rack over
    ceil(shards/racks)), then even node counts within each rack, never
    co-locating a volume's shards on one node."""
    nodes = collect_ec_nodes(env)
    if not nodes:
        return []
    moves: list[dict] = []
    budget = _shard_slot_budget(nodes)
    _balance_racks(nodes, moves, budget)
    _balance_nodes(nodes, moves, budget)
    if plan_only:
        return moves
    for move in moves:
        call(move["to"], "/admin/ec/copy",
             {"volume": move["volume"], "collection": move["collection"],
              "shard_ids": [move["shard"]],
              "source": move["from"], "copy_ecx_file": True}, timeout=3600)
        call(move["to"], "/admin/ec/mount",
             {"volume": move["volume"], "collection": move["collection"],
              "shard_ids": [move["shard"]]})
        call(move["from"], "/admin/ec/delete_shards",
             {"volume": move["volume"], "collection": move["collection"],
              "shard_ids": [move["shard"]]})
    return moves


def ec_evacuate(env: CommandEnv, server: str,
                plan_only: bool = False) -> list[dict]:
    """Move every EC shard off `server` (the shard half of a graceful
    drain; command_volume_server_evacuate.go's EC branch).  Targets are
    picked emptiest-first under the same never-duplicate-a-shard-id /
    slot-budget constraints as ec.balance."""
    nodes = collect_ec_nodes(env)
    source = next((n for n in nodes if n.url == server), None)
    if source is None or not source.shards:
        return []
    peers = [n for n in nodes if n.url != server]
    if not peers:
        raise RpcError(f"no peers to evacuate {server} onto", 409)
    budget = _shard_slot_budget(peers)
    moves: list[dict] = []
    for vid in sorted(source.shards):
        for sid in sorted(source.shards[vid]):
            candidates = [n for n in peers
                          if budget[n.url] > 0
                          and sid not in n.shards.get(vid, [])]
            if not candidates:
                raise RpcError(
                    f"no capacity to evacuate shard {vid}.{sid} "
                    f"off {server}", 507)
            target = min(candidates,
                         key=lambda n: (n.shard_count(), -budget[n.url],
                                        n.url))
            _move_shard(moves, source, target, vid, sid)
            budget[target.url] -= 1
    if plan_only:
        return moves
    for move in moves:
        call(move["to"], "/admin/ec/copy",
             {"volume": move["volume"], "collection": move["collection"],
              "shard_ids": [move["shard"]],
              "source": move["from"], "copy_ecx_file": True}, timeout=3600)
        call(move["to"], "/admin/ec/mount",
             {"volume": move["volume"], "collection": move["collection"],
              "shard_ids": [move["shard"]]})
        call(move["from"], "/admin/ec/delete_shards",
             {"volume": move["volume"], "collection": move["collection"],
              "shard_ids": [move["shard"]]})
    return moves


# -- ec.scrub ----------------------------------------------------------------


def ec_scrub(env: CommandEnv, vid: Optional[int] = None,
             repair: bool = False, plan_only: bool = False) -> list[dict]:
    """Cluster-wide EC integrity sweep: every shard holder verifies its
    local shards against the fused-encode CRC record (.vif); corrupt
    shards are deleted and rebuilt from survivors with -repair.  No
    reference analogue — the reference stores no shard checksums."""
    topo = env.master("/dir/status")
    vids = ([vid] if vid is not None
            else sorted(topo.get("ec_volumes", [])))
    reports = []
    for v in vids:
        try:
            lookup = env.master(f"/ec/lookup?volumeId={v}")
        except RpcError:
            continue
        collection = lookup.get("collection", "")
        holders = {loc["url"]
                   for e in lookup.get("shard_id_locations", [])
                   for loc in e["locations"]}
        corrupt: list[tuple[str, int]] = []
        errors: list[dict] = []
        clean_union: set[int] = set()
        # every holder walks its own disks — fan the scrub RPCs out in
        # parallel instead of serializing 600s-budget calls per holder
        futs = {url: _fanout().submit(
                    call, url, "/admin/ec/scrub",
                    {"volume": v, "collection": collection}, timeout=600)
                for url in sorted(holders)}
        for url in sorted(futs):
            try:
                r = futs[url].result()
            except (RpcError, OSError) as e:
                errors.append({"holder": url, "error": str(e)})
                continue
            clean_union.update(r.get("clean", []))
            corrupt.extend((url, sid) for sid in r.get("corrupt", []))
        # a shard corrupt on one holder but clean elsewhere is covered;
        # missing = no intact copy anywhere AND no corrupt copy either
        seen = clean_union | {sid for _, sid in corrupt}
        missing = sorted(set(range(TOTAL_SHARDS_COUNT)) - seen)
        report = {"volume": v, "clean_shards": len(clean_union),
                  "corrupt": [{"holder": u, "shard": s}
                              for u, s in corrupt
                              if s not in clean_union],
                  "missing": missing}
        if errors:
            report["errors"] = errors
        degraded = report["corrupt"] or missing
        if degraded and repair and not plan_only:
            # rebuild needs the volume's family's data_shards intact
            # copies (10 for RS/Cauchy, 5 for pm_msr)
            shard_locations = {
                e["shard_id"]: [loc["url"] for loc in e["locations"]]
                for e in lookup.get("shard_id_locations", [])}
            need = _volume_family_info(v, shard_locations)["data_shards"]
            if len(clean_union) < need:
                report["rebuild_error"] = (
                    f"only {len(clean_union)} clean shards — corrupt "
                    "copies left in place for manual recovery")
            else:
                for url, sid in corrupt:
                    call(url, "/admin/ec/delete_shards",
                         {"volume": v, "collection": collection,
                          "shard_ids": [sid]})
                try:
                    report["rebuild"] = ec_rebuild(env, v, collection)
                except RpcError as e:
                    report["rebuild_error"] = str(e)
        reports.append(report)
    return reports


# -- volume.* ----------------------------------------------------------------


def volume_list(env: CommandEnv) -> dict:
    return env.master("/dir/status")


def volume_vacuum(env: CommandEnv,
                  garbage_threshold: Optional[float] = None) -> dict:
    path = "/vol/vacuum"
    if garbage_threshold is not None:
        path += f"?garbageThreshold={garbage_threshold}"
    return env.master(path, {})


def volume_query(env: CommandEnv, file_ids: list[str],
                 selections: Optional[list[str]] = None, field: str = "",
                 op: str = "", value: str = "",
                 csv: bool = False) -> list[dict]:
    """SELECT over stored objects: route each fid to a server holding its
    volume and run the /query RPC there (volume_grpc_query.go)."""
    by_url: dict[str, list[str]] = {}
    for fid in file_ids:
        vid = fid.split(",")[0]
        found = env.master(f"/dir/lookup?volumeId={vid}")
        locations = found.get("locations", [])
        if not locations:
            raise RpcError(f"volume {vid} not found", 404)
        by_url.setdefault(locations[0]["url"], []).append(fid)
    records: list[dict] = []
    for url, fids in by_url.items():
        resp = call(url, "/query", {
            "from_file_ids": fids,
            "selections": selections or [],
            "filter": {"field": field, "operand": op, "value": value},
            "input_serialization": {"csv": {}} if csv else {"json": {}},
        })
        records.extend(resp.get("records", []))
    return records
