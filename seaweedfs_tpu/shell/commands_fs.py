"""Admin shell: fs.* and s3.* commands against a filer.

Parity with weed/shell/command_fs_*.go and command_s3_*.go: namespace
inspection (ls/du/tree/cat/meta), mutation (mkdir/rm/mv), metadata
save/load round-trips, bucket management under /buckets, stale multipart
upload cleanup, and identity configuration shared with the IAM API.
"""

from __future__ import annotations

import json
import time
import urllib.parse
from typing import Optional

from ..rpc.http_rpc import RpcError, call
from .commands import CommandEnv

BUCKETS_ROOT = "/buckets"
IDENTITY_CONFIG_PATH = "/etc/iam/identity.json"


def _get_json_config(filer: str, path: str) -> dict:
    """Fetch a JSON config entry through the filer HTTP read path; {} on
    absence or parse failure.  Shared by fs.configure / s3.configure /
    quota / circuitbreaker so the fetch-and-parse logic lives once."""
    try:
        raw = call(filer, urllib.parse.quote(path))
        return raw if isinstance(raw, dict) else json.loads(
            raw if isinstance(raw, str) else raw.decode())
    except (RpcError, ValueError):
        return {}


def find_filer(env: CommandEnv) -> str:
    """Resolve a filer address: explicit on the env, else the master's
    cluster registry (shell.go filer discovery)."""
    addr = getattr(env, "filer_address", "")
    if addr:
        return addr
    members = env.master("/cluster/nodes?type=filer") \
        .get("cluster_nodes", [])
    if not members:
        raise RpcError("no filer registered with the master", 404)
    addr = members[0]["address"]
    env.filer_address = addr
    return addr


def _list(filer: str, path: str, metadata: bool = False) -> list[dict]:
    dir_path = path if path.endswith("/") else path + "/"
    out: list[dict] = []
    last = ""
    while True:
        q = f"?limit=1000&lastFileName={urllib.parse.quote(last)}"
        if metadata:
            q += "&metadata=true"
        resp = call(filer, urllib.parse.quote(dir_path) + q)
        if not isinstance(resp, dict):
            # the filer answered with file CONTENT: path names a file
            raise RpcError(f"{path} is not a directory", 400)
        entries = resp.get("Entries", []) or []
        out.extend(entries)
        if not resp.get("ShouldDisplayLoadMore"):
            return out
        last = resp.get("LastFileName", "")
        if not last:
            return out


def _name(entry: dict) -> str:
    return (entry.get("FullPath") or entry.get("full_path", "")) \
        .rsplit("/", 1)[-1]


def _is_dir(entry: dict) -> bool:
    if "IsDirectory" in entry:
        return entry["IsDirectory"]
    return bool(entry.get("attr", {}).get("mode", 0) & 0o40000)


def _size(entry: dict) -> int:
    if "FileSize" in entry:
        return entry["FileSize"]
    return entry.get("attr", {}).get("file_size", 0)


# -- fs.* --------------------------------------------------------------------

def fs_ls(env: CommandEnv, path: str = "/",
          long_format: bool = False) -> list[dict]:
    filer = find_filer(env)
    entries = _list(filer, path)
    if long_format:
        return [{"name": _name(e), "is_dir": _is_dir(e),
                 "size": _size(e), "mode": e.get("Mode", 0),
                 "mtime": e.get("Mtime", 0)} for e in entries]
    return [{"name": _name(e), "is_dir": _is_dir(e)} for e in entries]


def fs_cat(env: CommandEnv, path: str) -> bytes:
    body = call(find_filer(env), urllib.parse.quote(path), parse=False)
    if isinstance(body, bytes):
        return body
    raise RpcError(f"{path} is a directory", 400)


def fs_mkdir(env: CommandEnv, path: str) -> dict:
    return call(find_filer(env), urllib.parse.quote(path.rstrip("/")) + "/",
                raw=b"", method="POST")


def fs_rm(env: CommandEnv, path: str, recursive: bool = False) -> None:
    q = "?recursive=true" if recursive else ""
    call(find_filer(env), urllib.parse.quote(path) + q, method="DELETE")


def fs_mv(env: CommandEnv, src: str, dst: str) -> dict:
    return call(find_filer(env),
                f"{urllib.parse.quote(dst)}?mv.from="
                f"{urllib.parse.quote(src, safe='')}",
                raw=b"", method="POST")


def fs_du(env: CommandEnv, path: str = "/") -> dict:
    """command_fs_du.go: recursive file/dir/byte accounting."""
    filer = find_filer(env)
    files = dirs = size = 0

    def walk(p: str):
        nonlocal files, dirs, size
        for e in _list(filer, p):
            if _is_dir(e):
                dirs += 1
                walk(p.rstrip("/") + "/" + _name(e))
            else:
                files += 1
                size += _size(e)

    walk(path)
    return {"path": path, "files": files, "dirs": dirs, "bytes": size}


def fs_tree(env: CommandEnv, path: str = "/") -> list[str]:
    filer = find_filer(env)
    lines: list[str] = []

    def walk(p: str, depth: int):
        for e in _list(filer, p):
            name = _name(e)
            lines.append("  " * depth
                         + (name + "/" if _is_dir(e) else name))
            if _is_dir(e):
                walk(p.rstrip("/") + "/" + name, depth + 1)

    walk(path, 0)
    return lines


def fs_meta_cat(env: CommandEnv, path: str) -> dict:
    """command_fs_meta_cat.go: the raw entry record."""
    filer = find_filer(env)
    parent, _, name = path.rstrip("/").rpartition("/")
    for e in _list(filer, parent or "/", metadata=True):
        if e.get("full_path", "").rsplit("/", 1)[-1] == name:
            return e
    raise RpcError(f"{path} not found", 404)


def fs_meta_save(env: CommandEnv, path: str = "/",
                 output: str = "") -> list[dict]:
    """command_fs_meta_save.go: dump the subtree's full metadata as
    JSON-lines (returned, and written to `output` when given)."""
    filer = find_filer(env)
    records: list[dict] = []

    def walk(p: str):
        for e in _list(filer, p, metadata=True):
            records.append(e)
            if _is_dir(e):
                walk(e["full_path"] + "/")

    walk(path)
    if output:
        with open(output, "w") as f:
            for r in records:
                f.write(json.dumps(r) + "\n")
    return records


def fs_meta_load(env: CommandEnv, input_path: str) -> int:
    """command_fs_meta_load.go: restore entries saved by fs.meta.save.
    Directories are recreated; file entries are restored with their
    chunk lists verbatim (the chunks must still exist on the volume
    servers)."""
    from ..filer.entry import Entry

    filer = find_filer(env)
    count = 0
    with open(input_path) as f:
        for line in f:
            if not line.strip():
                continue
            record = json.loads(line)
            entry = Entry.from_dict(record)
            if entry.is_directory:
                call(filer, urllib.parse.quote(entry.full_path) + "/",
                     raw=b"", method="POST")
            else:
                # restore metadata-only: re-post inlined content, or
                # re-attach chunks through the meta endpoint
                call(filer,
                     urllib.parse.quote(entry.full_path) + "?meta=true",
                     payload=record, method="POST")
            count += 1
    return count


# -- s3.* --------------------------------------------------------------------

def s3_bucket_list(env: CommandEnv) -> list[dict]:
    filer = find_filer(env)
    try:
        entries = _list(filer, BUCKETS_ROOT)
    except RpcError as e:
        if e.status == 404:
            return []
        raise
    return [{"name": _name(e)} for e in entries if _is_dir(e)]


def s3_bucket_create(env: CommandEnv, name: str) -> dict:
    return call(find_filer(env), f"{BUCKETS_ROOT}/{name}/",
                raw=b"", method="POST")


def s3_bucket_delete(env: CommandEnv, name: str) -> None:
    call(find_filer(env), f"{BUCKETS_ROOT}/{name}?recursive=true",
         method="DELETE")


def s3_clean_uploads(env: CommandEnv,
                     timeout_seconds: float = 24 * 3600) -> list[str]:
    """command_s3_clean_uploads.go: abort multipart uploads older than
    the timeout (their staging dirs live under <bucket>/.uploads/)."""
    filer = find_filer(env)
    removed = []
    now = time.time()
    for bucket in s3_bucket_list(env):
        uploads_dir = f"{BUCKETS_ROOT}/{bucket['name']}/.uploads"
        try:
            uploads = _list(filer, uploads_dir)
        except RpcError:
            continue
        for u in uploads:
            if now - u.get("Mtime", 0) > timeout_seconds:
                path = f"{uploads_dir}/{_name(u)}"
                call(filer, path + "?recursive=true", method="DELETE")
                removed.append(path)
    return removed


def s3_configure(env: CommandEnv, user: str, access_key: str,
                 secret_key: str,
                 actions: Optional[list[str]] = None) -> dict:
    """command_s3_configure.go: upsert an identity in the shared
    identity config (the same file the IAM API manages)."""
    filer = find_filer(env)
    config = _get_json_config(filer, IDENTITY_CONFIG_PATH)
    identities = [i for i in config.get("identities", [])
                  if i.get("name") != user]
    identities.append({
        "name": user,
        "credentials": [{"accessKey": access_key,
                         "secretKey": secret_key}],
        "actions": actions or ["Admin"],
    })
    config["identities"] = identities
    body = json.dumps(config, indent=2).encode()
    call(filer, IDENTITY_CONFIG_PATH, raw=body, method="POST",
         headers={"Content-Type": "application/json"})
    return config


def fs_configure(env: CommandEnv, location_prefix: str,
                 collection: str = "", replication: str = "",
                 ttl: str = "", read_only: Optional[bool] = None,
                 max_file_name_length: int = 0,
                 ec_code: str = "",
                 delete: bool = False) -> dict:
    """command_fs_configure.go: edit the per-path rules stored at
    /etc/seaweedfs/filer.conf in the filer itself."""
    from ..filer.filer_conf import FILER_CONF_PATH

    filer = find_filer(env)
    conf = _get_json_config(filer, FILER_CONF_PATH)
    existing = next((loc for loc in conf.get("locations", [])
                     if loc.get("location_prefix") == location_prefix), {})
    locations = [loc for loc in conf.get("locations", [])
                 if loc.get("location_prefix") != location_prefix]
    if not delete:
        # merge into the existing rule for this prefix: an unrelated
        # ttl/replication edit must not drop quota fields set by
        # s3.bucket.quota (or any other keys) on the same prefix
        rule: dict = dict(existing)
        rule["location_prefix"] = location_prefix
        if collection:
            rule["collection"] = collection
        if replication:
            rule["replication"] = replication
        if ttl:
            rule["ttl"] = ttl
        if read_only is not None:
            rule["read_only"] = read_only
        if max_file_name_length:
            rule["max_file_name_length"] = max_file_name_length
        if ec_code:
            # validate before persisting: a typo'd family name must fail
            # here, not at encode time months later
            from ..storage.erasure_coding.codes import get_family
            get_family(ec_code)
            rule["ec_code"] = ec_code
        locations.append(rule)
    conf["locations"] = locations
    call(filer, FILER_CONF_PATH, raw=json.dumps(conf, indent=2).encode(),
         method="POST", headers={"Content-Type": "application/json"})
    return conf


# -- fs.cd / fs.pwd (command_fs_cd.go, command_fs_pwd.go) --------------------

def resolve_path(env: CommandEnv, path: str) -> str:
    """Resolve `path` against the shell's working directory, handling
    "." / ".." segments (util.ResolvePath semantics)."""
    cwd = getattr(env, "cwd", "/") or "/"
    if not path:
        return cwd
    if not path.startswith("/"):
        path = cwd.rstrip("/") + "/" + path
    parts: list[str] = []
    for seg in path.split("/"):
        if seg in ("", "."):
            continue
        if seg == "..":
            if parts:
                parts.pop()
            continue
        parts.append(seg)
    return "/" + "/".join(parts)


def fs_cd(env: CommandEnv, path: str = "/") -> dict:
    """Change the shell's working directory; the target must be a
    listable directory."""
    target = resolve_path(env, path)
    if target != "/":
        # ONE limit=1 request proves existence + directory-ness; a full
        # _list() would page through every entry of a huge directory
        resp = call(find_filer(env),
                    urllib.parse.quote(target.rstrip("/") + "/")
                    + "?limit=1")
        if not isinstance(resp, dict):
            raise RpcError(f"{target} is not a directory", 400)
    env.cwd = target
    return {"cwd": target}


def fs_pwd(env: CommandEnv) -> dict:
    return {"cwd": getattr(env, "cwd", "/") or "/"}


# -- fs.meta.notify (command_fs_meta_notify.go) ------------------------------

def fs_meta_notify(env: CommandEnv, path: str = "/") -> dict:
    """Re-send a create EventNotification for every entry under `path`
    to the notification.toml sink — used to prime a fresh downstream
    consumer with the existing tree."""
    from ..notification import load_notification_queue
    from ..util.config import load_configuration

    queue = load_notification_queue(load_configuration("notification"))
    if queue is None:
        raise RpcError("no notification sink configured "
                       "(weed scaffold -config=notification)", 400)
    filer = find_filer(env)
    sent = 0

    def walk(p: str):
        nonlocal sent
        for e in _list(filer, p, metadata=True):
            full = p.rstrip("/") + "/" + _name(e)
            # flat MetaEvent shape (filer.MetaEvent.to_dict) — the same
            # records the filer's own queue emits, so replicator /
            # aggregator consumers see a normal create event
            queue.send(full, {
                "ts_ns": time.time_ns(),
                "directory": p.rstrip("/") or "/",
                "old_entry": None,
                "new_entry": e,
            })
            sent += 1
            if _is_dir(e):
                walk(full)

    try:
        walk(resolve_path(env, path))
    finally:
        queue.close()  # flush buffered events even on a mid-walk error
    return {"notified": sent}


# -- s3.bucket.quota / s3.bucket.quota.enforce -------------------------------
# (command_s3_bucket_quota.go, command_s3_bucket_quota_check.go) — quota
# rides the bucket's filer-conf rule; enforce compares the bucket
# collection's physical size from the master topology and toggles the
# rule's read_only flag

def _load_conf_locations(filer: str) -> list[dict]:
    from ..filer.filer_conf import FILER_CONF_PATH

    return _get_json_config(filer, FILER_CONF_PATH) \
        .get("locations", []) or []


def _save_conf_locations(filer: str, locations: list[dict]) -> None:
    from ..filer.filer_conf import FILER_CONF_PATH

    call(filer, urllib.parse.quote(FILER_CONF_PATH),
         raw=json.dumps({"locations": locations}, indent=2).encode(),
         method="POST")


def s3_bucket_quota(env: CommandEnv, name: str, op: str = "set",
                    size_mb: int = 0) -> dict:
    """set/get/remove/enable/disable a bucket's quota (stored as
    quota_mb on the bucket's path rule; negative means disabled)."""
    if not name:
        raise RpcError("empty bucket name", 400)
    filer = find_filer(env)
    prefix = f"{BUCKETS_ROOT}/{name}/"
    locations = _load_conf_locations(filer)
    rule = next((r for r in locations
                 if r.get("location_prefix") == prefix), None)
    current = int(rule.get("quota_mb", 0)) if rule else 0
    if op == "get":
        return {"bucket": name, "quota_mb": current}
    if op == "set":
        new = size_mb
    elif op == "remove":
        new = 0
    elif op == "enable":
        new = abs(current)
    elif op == "disable":
        new = -abs(current)
    else:
        raise RpcError(f"unknown op {op!r} "
                       "(set|get|remove|enable|disable)", 400)
    locations = [r for r in locations
                 if r.get("location_prefix") != prefix]
    if rule is None:
        rule = {"location_prefix": prefix}
    if new:
        rule["quota_mb"] = new
    else:
        rule.pop("quota_mb", None)
    if new <= 0 and rule.get("quota_read_only"):
        # removing/disabling the quota lifts an enforcement-set
        # read_only — enforce won't revisit a rule with no quota
        rule.pop("quota_read_only", None)
        rule.pop("read_only", None)
    # keep the rule if it still says anything
    if len(rule) > 1:
        locations.append(rule)
    _save_conf_locations(filer, locations)
    return {"bucket": name, "quota_mb": new}


def s3_bucket_quota_enforce(env: CommandEnv, apply: bool = False) -> dict:
    """Compare each bucket collection's physical size to its quota; over
    quota -> mark the bucket rule read_only (with -apply), under quota ->
    clear a read_only this command set."""
    filer = find_filer(env)
    status = env.master("/dir/status")
    sizes: dict[str, int] = {}
    for dc in status.get("datacenters", []):
        for rack in dc.get("racks", []):
            for node in rack.get("nodes", []):
                for v in node.get("volume_list", []):
                    col = v.get("collection", "")
                    sizes[col] = sizes.get(col, 0) + int(v.get("size", 0))
    locations = _load_conf_locations(filer)
    report, changed = [], False
    for rule in locations:
        prefix = rule.get("location_prefix", "")
        quota_mb = int(rule.get("quota_mb", 0))
        # rules with an enforcement-set read_only stay in scope even
        # after the quota is removed, so the flag can be cleared
        if not prefix.startswith(f"{BUCKETS_ROOT}/") or \
                (quota_mb <= 0 and not rule.get("quota_read_only")):
            continue
        bucket = prefix[len(BUCKETS_ROOT) + 1:].strip("/")
        used = sizes.get(bucket, 0)
        over = quota_mb > 0 and used > quota_mb << 20
        report.append({"bucket": bucket, "quota_mb": quota_mb,
                       "used_bytes": used, "over": over,
                       "read_only": rule.get("read_only", False)})
        if over and not rule.get("read_only"):
            rule["read_only"] = True
            rule["quota_read_only"] = True  # we set it; we may clear it
            changed = True
        elif not over and rule.get("quota_read_only"):
            rule["read_only"] = False
            rule.pop("quota_read_only", None)
            changed = True
    if changed and apply:
        _save_conf_locations(filer, locations)
    return {"buckets": report, "applied": bool(changed and apply)}


# -- s3.circuitbreaker (command_s3_circuitbreaker.go) ------------------------

def s3_circuitbreaker(env: CommandEnv, actions: str = "",
                      values: str = "", buckets: str = "",
                      enable: Optional[bool] = None,
                      delete: bool = False) -> dict:
    """Read or edit /etc/s3/circuit_breaker.json through the filer.

    actions: comma list like "Read:Count,Write:MB"; values: matching
    comma list of limits; buckets: comma list to scope the edit (global
    when empty)."""
    from ..s3api.circuit_breaker import CONFIG_PATH

    filer = find_filer(env)
    config = _get_json_config(filer, CONFIG_PATH)
    if actions or enable is not None or delete:
        targets = ([("buckets", b) for b in buckets.split(",") if b]
                   or [("global", None)])
        acts = [a for a in actions.split(",") if a]
        vals = [int(v) for v in values.split(",") if v] if values else []
        if acts and not delete and len(acts) != len(vals):
            raise RpcError("actions and values must pair up", 400)
        for scope, bucket in targets:
            if scope == "global":
                node = config.setdefault("global", {})
            else:
                node = config.setdefault("buckets", {}) \
                    .setdefault(bucket, {})
            if delete:
                for a in acts or list(node.get("actions", {})):
                    node.get("actions", {}).pop(a, None)
            else:
                for a, v in zip(acts, vals):
                    node.setdefault("actions", {})[a] = v
            if enable is not None:
                node["enabled"] = enable
            elif "enabled" not in node:
                node["enabled"] = True
        call(filer, urllib.parse.quote(CONFIG_PATH),
             raw=json.dumps(config, indent=2).encode(), method="POST")
    return config
